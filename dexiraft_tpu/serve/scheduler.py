"""SLO-aware request scheduler: the queue between HTTP handlers and the
engine.

The InferenceEngine batches a stream it can SEE (eval hands it the whole
dataset); a service only sees requests as they arrive. The scheduler
turns arrivals into engine batches under a latency contract:

  * per-bucket FIFO queues — only same-bucket requests can share an
    executable, so the queue is keyed by the same quantized shape the
    engine compiles for (buckets.bucket_shape; no registry state is
    touched from handler threads).
  * dispatch a FULL batch the moment a bucket reaches batch_size — the
    throughput-optimal case, identical to eval's grouping.
  * dispatch a PARTIAL batch when the oldest queued request's latency
    budget says waiting any longer would miss it: each bucket keeps an
    EWMA of its measured service time (compile time excluded — a fresh
    bucket's first batch would otherwise poison the estimate by 100x),
    and the head request's dispatch deadline is
    ``t_submit + max(0, slo - est_service)``. Before the first
    measurement the estimate is slo/2 — early traffic errs toward
    dispatching small batches rather than missing its budget while the
    scheduler is still learning.
  * bounded queue — past ``max_queue`` waiting requests, submit raises
    QueueFull and the server answers 503. Under overload the service
    sheds load at admission instead of stretching everyone's latency
    (goodput stays flat instead of collapsing; serve_bench --closed_loop
    measures exactly this).
  * adaptive iteration budgets (``adaptive=True``, engine built with
    ServeConfig(adaptive=True)) — each dispatch carries an iteration
    budget derived from the head request's REMAINING latency budget and
    the queue's overload state: ``affordable = remaining_slo /
    per_iter_est`` (a per-bucket EWMA of measured seconds-per-iteration)
    capped by ``max_iters * (1 - queue_pressure)``, floored at
    ``min_iters``. Under overload the service degrades refinement depth
    smoothly (every admitted request still gets >= min_iters of real
    work) BEFORE admission control starts shedding — a second, softer
    valve ahead of the 503. Budgets are per-BATCH (the engine's
    while_loop runs one budget per dispatch); convergence still exits
    items early below the budget.
  * drain — ``drain()`` flips every queue to dispatch-immediately and
    blocks until empty: the SIGTERM path finishes every admitted request
    before the process exits, and new submits are refused.

Exactly ONE dispatcher thread calls into the engine (it is not
thread-safe and the device wants one in-order submission stream);
handler threads only enqueue and wait on their request's event. The
decision logic is separated from the thread (``poll_once``) so tests
drive it with a fake clock, deterministically.

No jax import at module level — like the engine, the scheduler stays
importable (and unit-testable) with a numpy stub eval_fn.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from dexiraft_tpu.analysis.locks import OrderedLock
from dexiraft_tpu.serve.buckets import bucket_shape
from dexiraft_tpu.serve.engine import InferenceEngine, Result

# EWMA weight for new service-time samples: heavy enough to track a
# warming cache, light enough that one slow batch doesn't collapse the
# hold window
_EWMA = 0.3
_PCTL_WINDOW = 4096  # bounded sample windows, same rationale as ServeStats


class QueueFull(RuntimeError):
    """Admission refused: max_queue requests already waiting (503)."""


class SchedulerClosed(RuntimeError):
    """Submit after drain/close began: the service is shutting down."""


class SchedulerStats:
    """Counter block the /stats endpoint and serve_bench serialize.

    dispatch_full / dispatch_slo / dispatch_drain partition every batch
    by WHY it left the queue: bucket filled, latency budget said go, or
    shutdown flush. A high slo share at high load means batch_size or
    slo_ms is mis-tuned (batches never fill); a high full share at low
    concurrency means the SLO hold is queueing requests it should
    release.
    """

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self.submitted = 0
        self.completed = 0
        self.failed = 0          # engine raised; error re-raised to callers
        self.rejected = 0        # QueueFull admissions
        self.dispatch_full = 0
        self.dispatch_slo = 0
        self.dispatch_drain = 0
        self.queue_peak = 0
        self.batch_fill = 0      # real (non-pad) requests dispatched
        self.wait_s: "collections.deque" = collections.deque(
            maxlen=_PCTL_WINDOW)
        self.latency_s: "collections.deque" = collections.deque(
            maxlen=_PCTL_WINDOW)
        # adaptive mode only: the iteration budget each dispatched batch
        # was granted (empty on fixed-iteration schedulers) — /stats
        # reports p50/p99 so an operator can SEE the degradation valve
        # working under load
        self.iter_budget: "collections.deque" = collections.deque(
            maxlen=_PCTL_WINDOW)

    @staticmethod
    def _pctl(samples, p: float) -> float:
        if not samples:
            return 0.0
        return float(np.percentile(samples, p))

    @classmethod
    def _pctl_ms(cls, samples, p: float) -> float:
        return cls._pctl(samples, p) * 1e3

    def record(self) -> dict:
        batches = (self.dispatch_full + self.dispatch_slo
                   + self.dispatch_drain)
        return {
            "submitted": self.submitted,
            "completed": self.completed,
            "failed": self.failed,
            "rejected": self.rejected,
            "dispatch_full": self.dispatch_full,
            "dispatch_slo": self.dispatch_slo,
            "dispatch_drain": self.dispatch_drain,
            "queue_peak": self.queue_peak,
            "mean_batch_fill": (round(self.batch_fill / batches, 2)
                                if batches else 0.0),
            "wait_p50_ms": round(self._pctl_ms(self.wait_s, 50), 2),
            "wait_p99_ms": round(self._pctl_ms(self.wait_s, 99), 2),
            "latency_p50_ms": round(self._pctl_ms(self.latency_s, 50), 2),
            "latency_p99_ms": round(self._pctl_ms(self.latency_s, 99), 2),
        }


class _Request:
    __slots__ = ("item", "bucket", "t_submit", "event", "result", "error")

    def __init__(self, item: Dict[str, Any], bucket: Tuple[int, int],
                 t_submit: float):
        self.item = item
        self.bucket = bucket
        self.t_submit = t_submit
        self.event = threading.Event()
        self.result: Optional[Result] = None
        self.error: Optional[BaseException] = None


class Scheduler:
    """Request queue + SLO-aware dynamic batching over one engine."""

    def __init__(self, engine: InferenceEngine, *,
                 slo_ms: float = 200.0,
                 max_queue: int = 64,
                 adaptive: bool = False,
                 max_iters: int = 32,
                 min_iters: int = 4,
                 clock: Callable[[], float] = time.monotonic):
        if slo_ms <= 0:
            raise ValueError(f"slo_ms must be > 0, got {slo_ms}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if adaptive:
            if not engine.config.adaptive:
                raise ValueError(
                    "Scheduler(adaptive=True) needs an adaptive engine — "
                    "build it with ServeConfig(adaptive=True) and "
                    "make_eval_step(adaptive=True)")
            if not 1 <= min_iters <= max_iters:
                raise ValueError(
                    f"need 1 <= min_iters <= max_iters, got "
                    f"min_iters={min_iters} max_iters={max_iters}")
        self.engine = engine
        self.slo_s = slo_ms / 1e3
        self.max_queue = max_queue
        # adaptive budget policy knobs: max_iters mirrors the step's
        # configured full iteration count (the budget is CLAMPED there
        # again inside the while_loop, so a mismatch degrades safely);
        # min_iters is the quality floor no overload can push below
        self.adaptive = adaptive
        self.max_iters = max_iters
        self.min_iters = min_iters
        self.clock = clock
        self.stats = SchedulerStats()
        # called in the DISPATCHER thread after each successful batch,
        # with (bucket, results) — the one place extra per-bucket device
        # work (e.g. the server's carry-splat warm compile) can run with
        # a guarantee that no other dispatch is concurrent
        self.post_dispatch: Optional[
            Callable[[Tuple[int, int], List[Result]], None]] = None
        # the condition's lock is a named, REENTRANT OrderedLock: the
        # quiesced /stats snapshot re-enters it (run_quiesced ->
        # stats_record), and naming it puts every queue-lock nesting
        # (cv -> sessions/video stats in the service's quiesced reset)
        # under the declared LOCK_ORDER
        self._cv = threading.Condition(
            OrderedLock("serve.scheduler.cv", reentrant=True))
        self._running = False        # dispatcher currently inside _run()
        self._quiesce_waiters = 0    # run_quiesced() callers pending
        self._queues: Dict[Tuple[int, int], "collections.deque[_Request]"] \
            = {}
        self._pending = 0
        self._dispatched = 0   # popped for a batch, result not yet set
        self._service_s: Dict[Tuple[int, int], float] = {}
        # adaptive mode: per-bucket EWMA of measured seconds PER
        # REFINEMENT ITERATION (batch service time / iterations the
        # while_loop actually ran) — the unit the SLO budget divides by
        self._iter_s: Dict[Tuple[int, int], float] = {}
        self._draining = False
        self._closed = False
        self._drained = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- submission side (any thread) ----------------------------------

    def submit_async(self, item: Dict[str, Any]) -> _Request:
        """Admit one request; returns a handle whose ``event`` fires when
        ``result`` (or ``error``) is set. Raises QueueFull / Scheduler-
        Closed instead of queueing what the service cannot honor."""
        cfg = self.engine.config
        h, w = np.shape(item["image1"])[:2]
        bucket = bucket_shape(h, w, cfg.stride, cfg.bucket_multiple)
        with self._cv:
            if self._closed or self._draining:
                raise SchedulerClosed("scheduler is draining/closed")
            if self._pending >= self.max_queue:
                self.stats.rejected += 1
                raise QueueFull(
                    f"{self._pending} requests already queued "
                    f"(max_queue={self.max_queue})")
            req = _Request(item, bucket, self.clock())
            self._queues.setdefault(bucket, collections.deque()).append(req)
            self._pending += 1
            self.stats.submitted += 1
            self.stats.queue_peak = max(self.stats.queue_peak, self._pending)
            self._cv.notify()
        return req

    def submit(self, item: Dict[str, Any],
               timeout: Optional[float] = None) -> Result:
        """Blocking submit: admit, wait, return the Result (or re-raise
        the batch's engine error in the caller's thread). On timeout the
        request is CANCELLED out of its queue — a caller that already
        answered 504 must not leave the engine computing flow for a dead
        request (under overload with client timeouts that zombie work
        would eat exactly the capacity admission control protects)."""
        req = self.submit_async(item)
        if not req.event.wait(timeout):
            with self._cv:
                q = self._queues.get(req.bucket)
                if q is not None and req in q:
                    q.remove(req)
                    self._pending -= 1
            # re-check under no lock: the dispatcher may have taken the
            # request between the failed wait and the cancellation
            if not req.event.is_set():
                raise TimeoutError(
                    f"request not served within {timeout}s (bucket "
                    f"{req.bucket}; queue depth {self.queue_depth()})")
        if req.error is not None:
            raise req.error
        return req.result

    def queue_depth(self) -> int:
        with self._cv:
            return self._pending

    def inflight(self) -> int:
        """Admitted-but-unanswered requests: queued PLUS mid-dispatch.
        The /healthz readiness payload reports this so a router's drain
        can wait for genuinely-zero outstanding work — queue_depth alone
        goes to 0 the moment the last batch is TAKEN, while its
        requests are still computing in the engine."""
        with self._cv:
            return self._pending + self._dispatched

    # ---- dispatch decision (dispatcher thread / tests) ------------------

    def _hold_s(self, bucket: Tuple[int, int]) -> float:
        est = self._service_s.get(bucket, self.slo_s * 0.5)
        return max(0.0, self.slo_s - est)

    def _select(self, now: float):
        """Under self._cv. Returns (bucket, 0.0) when a batch should go
        NOW, (None, wait_s) when the earliest deadline is wait_s away,
        (None, None) when every queue is empty."""
        bs = self.engine.config.batch_size
        best: Optional[Tuple[float, Tuple[int, int]]] = None
        for bucket, q in self._queues.items():
            if not q:
                continue
            if len(q) >= bs or self._draining or self._closed:
                return bucket, 0.0
            deadline = q[0].t_submit + self._hold_s(bucket)
            if best is None or deadline < best[0]:
                best = (deadline, bucket)
        if best is None:
            return None, None
        if now >= best[0]:
            return best[1], 0.0
        return None, best[0] - now

    def _take(self, bucket: Tuple[int, int]):
        """Under self._cv: pop up to batch_size requests off a bucket."""
        bs = self.engine.config.batch_size
        q = self._queues[bucket]
        group = [q.popleft() for _ in range(min(len(q), bs))]
        self._pending -= len(group)
        self._dispatched += len(group)
        return group, len(group) == bs

    def _iter_budget(self, bucket: Tuple[int, int], group: List["_Request"],
                     now: float) -> Optional[int]:
        """Under self._cv, after _take. SLO + overload state → this
        dispatch's iteration budget (None on fixed schedulers).

        Two pressures compound, both clamped to the [min_iters,
        max_iters] band:
          * affordable — the batch head's (oldest request's) remaining
            SLO divided by the bucket's learned seconds-per-iteration.
            Before the first measurement this is max_iters: early
            batches run at full depth so the estimate learns the true
            per-iteration cost, not a degraded one.
          * pressure — queued/max_queue scales the cap linearly down
            from max_iters toward the floor, so depth degrades SMOOTHLY
            as the queue fills instead of binary full-depth-then-503.
        """
        if not self.adaptive:
            return None
        full = self.max_iters
        remaining = max(0.0, self.slo_s - (now - group[0].t_submit))
        per_iter = self._iter_s.get(bucket)
        affordable = (full if per_iter is None or per_iter <= 0
                      else remaining / per_iter)
        pressure = min(1.0, self._pending / self.max_queue)
        budget = int(min(affordable, full * (1.0 - pressure)))
        return max(self.min_iters, min(full, budget))

    def poll_once(self) -> bool:
        """One dispatch decision + (if due) one engine batch. The unit
        tests' deterministic entry point; the dispatcher thread is this
        in a loop with cv waiting in between."""
        with self._cv:
            now = self.clock()
            bucket, _wait = self._select(now)
            if bucket is None:
                return False
            group, full = self._take(bucket)
            budget = self._iter_budget(bucket, group, now)
        self._run(bucket, group, full, budget)
        return True

    # ---- dispatch execution (dispatcher thread only) --------------------

    def _run(self, bucket: Tuple[int, int], group: List[_Request],
             full: bool, budget: Optional[int] = None) -> None:
        try:
            self._run_inner(bucket, group, full, budget)
        finally:
            with self._cv:
                self._dispatched -= len(group)
                self._cv.notify_all()   # inflight()==0 pollers re-check

    def _run_inner(self, bucket: Tuple[int, int], group: List[_Request],
                   full: bool, budget: Optional[int] = None) -> None:
        st = self.stats
        t0 = self.clock()
        # counter bumps take the cv: handler threads mutate the same
        # SchedulerStats under it (submit/reject paths) and /stats reads
        # it — a bare dispatcher-side += is the RouterStats undercount
        # bug (threadlint JL021). The ENGINE call below stays outside
        # the lock: blocking a whole batch's device time under the cv
        # would stall every submit (JL023).
        with self._cv:
            if full:
                st.dispatch_full += 1
            elif self._draining or self._closed:
                st.dispatch_drain += 1
            else:
                st.dispatch_slo += 1
            st.batch_fill += len(group)
            for r in group:
                st.wait_s.append(t0 - r.t_submit)
            if budget is not None:
                st.iter_budget.append(budget)
        compile0 = self.engine.compile_s
        try:
            results = self.engine.run_batch([r.item for r in group],
                                            iter_budget=budget)
        except Exception as e:
            with self._cv:
                st.failed += len(group)
            for r in group:
                r.error = e
                r.event.set()
            return
        # service estimate excludes this batch's compile share: the
        # first batch on a fresh bucket traces+compiles synchronously,
        # and folding that into the EWMA would pin the hold window at 0
        # for the rest of the process life
        dt = (self.clock() - t0
              - max(0.0, self.engine.compile_s - compile0))
        with self._cv:
            prev = self._service_s.get(bucket)
            self._service_s[bucket] = (dt if prev is None
                                       else (1 - _EWMA) * prev + _EWMA * dt)
            if budget is not None:
                # the while_loop ran max(iters_used) steps, not the full
                # budget — divide by what EXECUTED so early-converging
                # batches don't inflate the per-iteration estimate
                ran = max((r.iters_used for r in results
                           if r.iters_used is not None), default=budget)
                if ran and ran > 0:
                    per = dt / ran
                    prevp = self._iter_s.get(bucket)
                    self._iter_s[bucket] = (
                        per if prevp is None
                        else (1 - _EWMA) * prevp + _EWMA * per)
        if self.post_dispatch is not None:
            # BEFORE the events fire: a waiter acting on its result
            # (e.g. the server's carry splat) must find whatever this
            # hook compiles already compiled
            try:
                self.post_dispatch(bucket, results)
            except Exception as e:
                print(f"[scheduler] post_dispatch hook failed: "
                      f"{type(e).__name__}: {e}", flush=True)
        now = self.clock()
        with self._cv:
            for r in group:
                st.latency_s.append(now - r.t_submit)
            st.completed += len(group)
        for r, res in zip(group, results):
            r.result = res
            r.event.set()

    def _loop(self) -> None:
        while True:
            with self._cv:
                self._running = False
                self._cv.notify_all()   # wake run_quiesced waiters
                while self._quiesce_waiters:
                    # yield to pending quiesced sections: under
                    # saturation the dispatcher would otherwise re-take
                    # work while still holding the lock and starve them
                    self._cv.wait(timeout=0.05)
                while True:
                    now = self.clock()
                    bucket, wait = self._select(now)
                    if bucket is not None:
                        group, full = self._take(bucket)
                        budget = self._iter_budget(bucket, group, now)
                        self._running = True
                        break
                    if self._pending == 0:
                        if self._closed:
                            self._drained.set()
                            return
                        if self._draining:
                            self._drained.set()
                    self._cv.wait(timeout=wait)
            self._run(bucket, group, full, budget)

    def run_quiesced(self, fn: Callable[[], None]) -> None:
        """Run `fn` while the dispatcher provably is NOT inside the
        engine: holding the lock keeps it from taking new work, and the
        _running flag excludes a batch already in flight. The /stats
        reset path uses this so zeroing engine.compile_s can never race
        a dispatch's read-modify-write (a mid-batch reset would fold a
        whole compile span into the bucket's EWMA service estimate)."""
        with self._cv:
            self._quiesce_waiters += 1
            try:
                while self._running:
                    self._cv.wait(timeout=0.05)
                fn()
            finally:
                self._quiesce_waiters -= 1
                self._cv.notify_all()

    # ---- lifecycle ------------------------------------------------------

    def start(self) -> "Scheduler":
        if self._thread is not None:
            raise RuntimeError("scheduler already started")
        self._thread = threading.Thread(
            target=self._loop, name="flow-scheduler", daemon=True)
        self._thread.start()
        return self

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting, dispatch everything queued (partial batches
        go immediately), return True when the queue hit empty."""
        with self._cv:
            self._draining = True
            if self._pending == 0 and self._thread is None:
                self._drained.set()
            self._cv.notify()
        return self._drained.wait(timeout)

    def close(self, timeout: float = 30.0) -> None:
        """Drain, then stop the dispatcher thread."""
        self.drain(timeout)
        with self._cv:
            self._closed = True
            self._cv.notify()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    @property
    def draining(self) -> bool:
        return self._draining or self._closed

    def stats_record(self) -> dict:
        """SchedulerStats counters + live queue state + the learned
        per-bucket service estimates (the SLO policy's working memory)."""
        with self._cv:
            depth = self._pending
            inflight = self._pending + self._dispatched
            ests = {f"{h}x{w}": round(s * 1e3, 2)
                    for (h, w), s in sorted(self._service_s.items())}
            # counters snapshot under the same lock their writers hold
            # (submit paths and the dispatcher's bumps): no torn
            # completed-vs-latency combinations in a scrape
            counters = self.stats.record()
            budget_p50 = SchedulerStats._pctl(self.stats.iter_budget, 50)
            budget_p99 = SchedulerStats._pctl(self.stats.iter_budget, 99)
            iter_ests = {f"{h}x{w}": round(s * 1e3, 3)
                         for (h, w), s in sorted(self._iter_s.items())}
        rec = {
            **counters,
            "queue_depth": depth,
            "inflight": inflight,
            "slo_ms": round(self.slo_s * 1e3, 2),
            "max_queue": self.max_queue,
            "service_est_ms": ests,
            "draining": self.draining,
        }
        if self.adaptive:
            # adaptive keys only on adaptive schedulers: fixed-path
            # /stats and bench schema pins stay byte-identical
            rec.update(
                adaptive=True,
                min_iters=self.min_iters,
                max_iters=self.max_iters,
                iter_budget_p50=round(budget_p50, 2),
                iter_budget_p99=round(budget_p99, 2),
                iter_est_ms=iter_ests,
            )
        return rec
