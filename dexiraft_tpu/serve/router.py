"""Fleet router: health-checked, session-affine load balancing over N
FlowService replicas.

One FlowService process is one failure domain: PR 6's ``--workers N``
pool scales accepts but gives no session affinity (the kernel balances
connections blindly) and no failure handling (a dead worker's accepted
connections just reset). This router is the layer above — a pure-stdlib
proxy process that keeps the fleet serving through replica death,
restart, and overload:

  * **active health checking** — a background thread probes every
    replica's ``/healthz`` on a cadence; ``fail_threshold`` consecutive
    failures open a per-replica circuit breaker
    (closed -> open -> half-open probe -> closed), and proxy-side
    connect-refused/timeouts mark failures passively so a crash is
    detected at the FIRST failed request, not the next probe tick.
  * **consistent-hash session affinity** — ``X-Session-Id`` maps to a
    replica through a hash ring (virtual nodes), so the RAFT warm-start
    carry (`flow_init` sessions, PR 6) keeps landing on the replica
    that holds it. Pool changes remap only the bounded key range the
    ring guarantees: adding replica N+1 moves ~1/(N+1) of the sessions,
    removing a replica moves ONLY its own. A session whose replica died
    restarts cold elsewhere — counted (``sticky_misses``), not an
    error.
  * **zero-drop lifecycle** — ``drain(rid)`` removes a replica from
    assignment, polls its ``/healthz`` readiness payload until
    ``inflight`` hits 0, then invokes the restart hook (router_cli
    wires the subprocess restart); nothing admitted is dropped. An
    upstream failure on an in-flight proxied request (connection
    refused/reset — the request provably did not complete; flow
    inference is idempotent, a pure function of the frames) retries
    ONCE on a different healthy replica after a jittered backoff, under
    a per-request deadline budget — so even an ABRUPT replica kill
    drops zero accepted requests.
  * **graceful overload** — a router-level admission bound (503 +
    Retry-After past ``max_inflight``), replica 503 sheds retried once
    elsewhere then surfaced, and ``/stats`` aggregation: per-replica
    breaker state + last health payload, affinity hit rate, retries,
    failovers, shed counts, and an ``autoscale`` block fed by the
    replica schedulers' EWMA service estimates + shed counters.

Endpoints (the router speaks the SAME wire protocol as one replica, so
clients cannot tell one FlowService from a fleet):

  POST /v1/flow       proxied to the session's (or next healthy)
                      replica; response gains ``X-Replica`` and
                      ``X-Router-Retries`` headers.
  GET  /healthz       200 while >=1 replica is routable, else 503.
  GET  /stats         router counters + per-replica health + autoscale
                      hints; ``?replicas=1`` also scrapes every live
                      replica's own /stats into the blob.
  POST /admin/drain?replica=<rid>   zero-drop drain (+restart, when a
                      restart hook is wired) in the background; 202.

No jax import anywhere in this module — the router is pure control
plane and must start in milliseconds, survive model-side crashes, and
be unit-testable with fake clocks and fake probers.
"""

from __future__ import annotations

import bisect
import hashlib
import http.client
import json
import random
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Sequence, Tuple
from urllib.parse import parse_qs, urlparse

import numpy as np

from dexiraft_tpu.analysis import locks as _locks
from dexiraft_tpu.analysis.locks import OrderedLock
from dexiraft_tpu.serve.httputil import QuietDisconnectsMixin

# breaker states
CLOSED = "closed"          # healthy: in the ring, taking traffic
OPEN = "open"              # failed: out of the ring, cooling down
HALF_OPEN = "half_open"    # cooldown elapsed: one probe decides


class NoHealthyReplica(RuntimeError):
    """Every replica is open/draining/unready — the router must shed."""


# ---- consistent hashing -------------------------------------------------


class HashRing:
    """Consistent-hash ring with virtual nodes.

    The property the fleet needs is BOUNDED REMAPPING: membership
    changes must not reshuffle every session's home (each reshuffled
    session restarts its warm-start carry cold). A mod-N table remaps
    ~100% of keys when N changes; the ring remaps ~1/(N+1) on add and
    exactly the departed member's keys on remove —
    tests/test_zzfleet_router.py pins both.
    """

    def __init__(self, members: Sequence[str] = (), vnodes: int = 64):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = vnodes
        self._points: List[Tuple[int, str]] = []   # sorted (point, member)
        self._members: set = set()
        for m in members:
            self.add(m)

    @staticmethod
    def _point(key: str) -> int:
        # blake2b over md5: no deprecation noise, stable across runs
        # and processes (hash() is salted per-process — useless here)
        return int.from_bytes(
            hashlib.blake2b(key.encode(), digest_size=8).digest(), "big")

    def add(self, member: str) -> None:
        if member in self._members:
            return
        self._members.add(member)
        for v in range(self.vnodes):
            bisect.insort(self._points,
                          (self._point(f"{member}#{v}"), member))

    def remove(self, member: str) -> None:
        if member not in self._members:
            return
        self._members.discard(member)
        self._points = [(p, m) for p, m in self._points if m != member]

    @property
    def members(self) -> set:
        return set(self._members)

    def __len__(self) -> int:
        return len(self._members)

    def lookup(self, key: str) -> Optional[str]:
        """The key's owner: first virtual node clockwise of its point."""
        if not self._points:
            return None
        i = bisect.bisect_right(self._points, (self._point(key), ""))
        if i == len(self._points):
            i = 0
        return self._points[i][1]

    def chain(self, key: str) -> List[str]:
        """Every member, in ring order starting at the key's owner —
        the deterministic failover order (the retry goes to chain[1]
        when chain[0] is the dead owner)."""
        if not self._points:
            return []
        i = bisect.bisect_right(self._points, (self._point(key), ""))
        seen: List[str] = []
        for j in range(len(self._points)):
            m = self._points[(i + j) % len(self._points)][1]
            if m not in seen:
                seen.append(m)
        return seen


# ---- replica pool: breaker + affinity + drain ---------------------------


class RouterConfig:
    """Router knobs (construction-time; no live mutation)."""

    def __init__(self, *,
                 fail_threshold: int = 3,
                 cooldown_s: float = 2.0,
                 probe_interval_s: float = 0.5,
                 probe_timeout_s: float = 2.0,
                 max_inflight: int = 128,
                 deadline_s: float = 60.0,
                 retry_backoff_s: float = 0.05,
                 upstream_timeout_s: float = 60.0,
                 vnodes: int = 64,
                 affinity_window: int = 4096):
        if fail_threshold < 1:
            raise ValueError(f"fail_threshold must be >= 1, got "
                             f"{fail_threshold}")
        if max_inflight < 1:
            raise ValueError(f"max_inflight must be >= 1, got "
                             f"{max_inflight}")
        self.fail_threshold = fail_threshold
        self.cooldown_s = cooldown_s
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.max_inflight = max_inflight
        self.deadline_s = deadline_s
        self.retry_backoff_s = retry_backoff_s
        self.upstream_timeout_s = upstream_timeout_s
        self.vnodes = vnodes
        self.affinity_window = affinity_window


class Replica:
    """One upstream FlowService: address + breaker state + last-seen
    health payload. All mutation happens under the pool's lock."""

    def __init__(self, rid: str, url: str,
                 restart: Optional[Callable[[], None]] = None):
        u = urlparse(url if "//" in url else f"http://{url}")
        if not u.hostname or not u.port:
            raise ValueError(f"replica {rid}: url {url!r} needs host:port")
        self.rid = rid
        self.host = u.hostname
        self.port = u.port
        self.restart = restart      # lifecycle hook (router_cli: respawn)
        self.state = CLOSED
        self.fails = 0              # consecutive failures
        self.opened_at = 0.0
        self.draining = False       # router-side: excluded from the ring
        self.ready = True           # replica-side: /healthz said 200
        self.health: dict = {}      # last /healthz payload (either status)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def routable(self) -> bool:
        return self.state == CLOSED and self.ready and not self.draining

    def record(self) -> dict:
        return {"url": self.url, "state": self.state,
                "ready": self.ready, "draining": self.draining,
                "consecutive_failures": self.fails,
                "health": self.health}


class ReplicaPool:
    """Breaker state machine + ring membership + affinity accounting.

    `clock` and `prober` are injectable so every policy path (breaker
    transitions, drain-waits-for-inflight, probe cadence) runs under a
    fake clock with no sockets. The default prober is a real HTTP GET
    of the replica's /healthz.
    """

    def __init__(self, replicas: Dict[str, str],
                 config: Optional[RouterConfig] = None, *,
                 clock: Callable[[], float] = time.monotonic,
                 prober: Optional[Callable[[Replica], dict]] = None,
                 sleep: Callable[[float], None] = time.sleep):
        if not replicas:
            raise ValueError("a router needs at least one replica")
        self.config = config or RouterConfig()
        self.clock = clock
        self.sleep = sleep
        self.prober = prober or self._http_probe
        # reentrant: record() re-enters via affinity_record()
        self._lock = OrderedLock("serve.router.pool", reentrant=True)
        self.replicas: Dict[str, Replica] = {
            rid: Replica(rid, url) for rid, url in replicas.items()}
        self.ring = HashRing(sorted(self.replicas),
                             vnodes=self.config.vnodes)
        self._last_probe: Dict[str, float] = {rid: -1e18
                                              for rid in self.replicas}
        self._rr = 0                # stateless round-robin cursor
        # session -> rid that served it last (bounded LRU): the ground
        # truth for affinity hits vs sticky misses
        self._session_home: "OrderedDict[str, str]" = OrderedDict()
        self.affinity_hits = 0
        self.affinity_new = 0
        self.sticky_misses = 0      # home replica changed under the session
        self.breaker_opens = 0
        self.drains = 0

    # ---- probing --------------------------------------------------------

    def _http_probe(self, replica: Replica) -> dict:
        """GET /healthz. Returns the payload (200 OR 503-draining —
        both mean ALIVE); raises on anything connection-shaped."""
        conn = http.client.HTTPConnection(
            replica.host, replica.port,
            timeout=self.config.probe_timeout_s)
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            body = resp.read()
            payload = json.loads(body) if body else {}
            payload["_status"] = resp.status
            return payload
        finally:
            conn.close()

    def probe_once(self) -> None:
        """One health-check sweep: probe every replica whose interval
        (or breaker cooldown) elapsed. The health thread calls this in
        a loop; fake-clock tests call it directly."""
        now = self.clock()
        cfg = self.config
        with self._lock:
            due = []
            for rid, r in self.replicas.items():
                if r.state == OPEN:
                    if now - r.opened_at < cfg.cooldown_s:
                        continue            # still cooling down
                    r.state = HALF_OPEN     # cooldown over: trial probe
                elif now - self._last_probe[rid] < cfg.probe_interval_s:
                    continue
                self._last_probe[rid] = now
                due.append(r)

        def _probe_one(r: Replica) -> None:
            try:
                payload = self.prober(r)
            except Exception:
                self.mark_failure(r.rid)
            else:
                self.mark_alive(r.rid, payload)

        if len(due) <= 1:
            for r in due:
                _probe_one(r)
            return
        # probe CONCURRENTLY: sequential probing lets one black-holing
        # replica (SYN dropped — each probe burns the full
        # probe_timeout_s) stretch the whole sweep, inflating every
        # other replica's detection/half-open latency with fleet size
        threads = [threading.Thread(target=_probe_one, args=(r,),
                                    name=f"probe-{r.rid}", daemon=True)
                   for r in due]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def mark_alive(self, rid: str, payload: dict) -> None:
        """The replica answered /healthz: close the breaker; ring
        membership follows READINESS (a draining replica is alive but
        must stop receiving new sessions)."""
        with self._lock:
            r = self.replicas[rid]
            r.fails = 0
            r.state = CLOSED
            r.health = {k: v for k, v in payload.items()
                        if not k.startswith("_")}
            r.ready = (payload.get("_status", 200) == 200
                       and not payload.get("draining", False))
            if r.routable():
                self.ring.add(rid)
            else:
                self.ring.remove(rid)

    def mark_failure(self, rid: str) -> None:
        """One failed probe OR one failed proxied request (passive
        marking): breaker math is shared, so a crash surfaces at the
        first failed request instead of waiting for the next probe."""
        with self._lock:
            r = self.replicas[rid]
            r.fails += 1
            if r.state == HALF_OPEN or (r.state == CLOSED
                                        and r.fails
                                        >= self.config.fail_threshold):
                if r.state != OPEN:
                    self.breaker_opens += 1
                r.state = OPEN
                r.opened_at = self.clock()
                self.ring.remove(rid)

    # ---- routing --------------------------------------------------------

    def route(self, session_id: Optional[str] = None) -> Replica:
        """Pick the replica for one request. Session requests go to
        their ring owner (affinity); stateless requests round-robin
        over routable replicas. Raises NoHealthyReplica."""
        with self._lock:
            routable = [r for r in self.replicas.values() if r.routable()]
            if not routable:
                raise NoHealthyReplica(
                    f"0 of {len(self.replicas)} replicas routable")
            if session_id is None:
                r = routable[self._rr % len(routable)]
                self._rr += 1
                return r
            owner = self.ring.lookup(session_id)
            if owner is None:          # ring empty but routable nonempty
                owner = routable[0].rid   # (draining edge) — any is fine
            self._note_affinity(session_id, owner)
            return self.replicas[owner]

    def _note_affinity(self, session_id: str, rid: str) -> None:
        # under self._lock
        home = self._session_home.get(session_id)
        if home is None:
            self.affinity_new += 1
        elif home == rid:
            self.affinity_hits += 1
            self._session_home.move_to_end(session_id)
        else:
            # the session's replica died/drained and the ring moved it:
            # its warm carry is gone, it restarts cold elsewhere
            self.sticky_misses += 1
        self._session_home[session_id] = rid
        self._session_home.move_to_end(session_id)
        while len(self._session_home) > self.config.affinity_window:
            self._session_home.popitem(last=False)

    def alternate(self, exclude: str,
                  session_id: Optional[str] = None) -> Optional[Replica]:
        """A DIFFERENT routable replica for the failover retry —
        ring-order next for session requests (deterministic), round-
        robin otherwise. None when no alternative exists."""
        with self._lock:
            if session_id is not None:
                for rid in self.ring.chain(session_id):
                    r = self.replicas[rid]
                    if rid != exclude and r.routable():
                        return r
            candidates = [r for r in self.replicas.values()
                          if r.rid != exclude and r.routable()]
            if not candidates:
                return None
            r = candidates[self._rr % len(candidates)]
            self._rr += 1
            return r

    # ---- lifecycle ------------------------------------------------------

    def drain(self, rid: str, *, timeout_s: float = 60.0,
              poll_s: float = 0.2, restart: bool = True) -> dict:
        """Zero-drop replica drain: (1) stop new assignment (out of the
        ring — its sessions remap now, under the ring's bounded-move
        guarantee), (2) poll the replica's /healthz readiness payload
        until ``inflight`` reaches 0, (3) run the restart hook. The
        health loop re-admits it once it probes ready again.

        Returns {rid, drained, waited_s, inflight_last, restarted};
        ``drained`` False means the timeout expired with work still in
        flight (the caller decides whether to restart anyway — we do
        NOT)."""
        with self._lock:
            r = self.replicas[rid]
            r.draining = True
            self.ring.remove(rid)
            self.drains += 1
        t0 = self.clock()
        inflight = None
        drained = False
        while self.clock() - t0 <= timeout_s:
            try:
                payload = self.prober(r)
                inflight = int(payload.get("inflight", 0))
            except Exception:
                # dead mid-drain: nothing in flight to wait for
                inflight = 0
            if inflight == 0:
                drained = True
                break
            self.sleep(poll_s)
        out = {"rid": rid, "drained": drained,
               "waited_s": round(self.clock() - t0, 3),
               "inflight_last": inflight,
               "restarted": bool(drained and restart
                                 and r.restart is not None)}
        if out["restarted"]:
            r.restart()
        with self._lock:
            r.draining = False
            # membership returns via mark_alive once it probes ready
        return out

    # ---- introspection --------------------------------------------------

    def healthy_count(self) -> int:
        with self._lock:
            return sum(r.routable() for r in self.replicas.values())

    def affinity_record(self) -> dict:
        with self._lock:
            tracked = self.affinity_hits + self.sticky_misses
            return {
                "hits": self.affinity_hits,
                "new": self.affinity_new,
                "sticky_misses": self.sticky_misses,
                # hit rate over requests whose session HAD a home —
                # first-contact requests can't hit by definition
                "hit_rate": (round(self.affinity_hits / tracked, 4)
                             if tracked else None),
            }

    def reset_counters(self) -> None:
        with self._lock:
            self.affinity_hits = self.affinity_new = 0
            self.sticky_misses = 0
            self.breaker_opens = 0
            self.drains = 0

    def record(self) -> dict:
        with self._lock:
            return {
                "replicas": {rid: r.record()
                             for rid, r in sorted(self.replicas.items())},
                "healthy": sum(r.routable()
                               for r in self.replicas.values()),
                "ring_members": sorted(self.ring.members),
                "breaker_opens": self.breaker_opens,
                "drains": self.drains,
                "affinity": self.affinity_record(),
            }


# ---- router stats -------------------------------------------------------

_PCTL_WINDOW = 4096


class RouterStats:
    """Proxy-side counters (the pool owns health/affinity ones).

    Handler threads mutate these concurrently, so every increment goes
    through ``bump()`` under one lock — bare ``+= 1`` is a load/store
    race that silently undercounts exactly the numbers the fleet bench
    and chaos phase report as results."""

    def __init__(self) -> None:
        self._lock = OrderedLock("serve.router.stats")
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.requests = 0
            self.proxied_ok = 0
            self.retries = 0           # failover attempts made
            self.failovers = 0         # retries that returned 200
            self.shed_router = 0       # router-level 503 (admission bound)
            self.shed_upstream = 0     # replica 503 surfaced to the client
            self.upstream_errors = 0   # 502s surfaced to the client
            self.no_healthy = 0        # 503: zero routable replicas
            self.latency_s: List[float] = []

    def bump(self, name: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + n)

    def note_latency(self, dt: float) -> None:
        with self._lock:
            self.latency_s.append(dt)
            if len(self.latency_s) > _PCTL_WINDOW:
                del self.latency_s[:len(self.latency_s) - _PCTL_WINDOW]

    def record(self) -> dict:
        with self._lock:   # one consistent snapshot, counters + window
            lat = list(self.latency_s)
            out = {
                "requests": self.requests,
                "proxied_ok": self.proxied_ok,
                "retries": self.retries,
                "failovers": self.failovers,
                "shed_router": self.shed_router,
                "shed_upstream": self.shed_upstream,
                "upstream_errors": self.upstream_errors,
                "no_healthy": self.no_healthy,
            }
        out["latency_p50_ms"] = (round(float(np.percentile(lat, 50)) * 1e3,
                                       2) if lat else 0.0)
        out["latency_p99_ms"] = (round(float(np.percentile(lat, 99)) * 1e3,
                                       2) if lat else 0.0)
        return out


# ---- the proxy ----------------------------------------------------------

# upstream failures that prove the request did NOT complete — safe to
# retry an idempotent request elsewhere. A read TIMEOUT is absent on
# purpose: the work may still finish, and re-running it would double
# load exactly when the fleet is slowest.
_RETRYABLE = (ConnectionRefusedError, ConnectionResetError,
              BrokenPipeError, http.client.BadStatusLine,
              http.client.RemoteDisconnected, ConnectionAbortedError)


class _UpstreamResult:
    __slots__ = ("status", "body", "headers")

    def __init__(self, status: int, body: bytes, headers: dict):
        self.status = status
        self.body = body
        self.headers = headers


class _RouterHTTPServer(QuietDisconnectsMixin, ThreadingHTTPServer):
    daemon_threads = False
    block_on_close = True

    def __init__(self, addr, handler, router: "Router"):
        self.router = router
        super().__init__(addr, handler)


class _RouterHandler(BaseHTTPRequestHandler):
    server_version = "dexiraft-router/1.0"
    protocol_version = "HTTP/1.1"
    timeout = 30.0

    def log_message(self, fmt, *args):
        pass

    def _send(self, status: int, body: bytes, content_type: str,
              headers: Optional[dict] = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, status: int, payload: dict,
                   headers: Optional[dict] = None) -> None:
        self._send(status, json.dumps(payload).encode(),
                   "application/json", headers)

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        router = self.server.router
        url = urlparse(self.path)
        if url.path == "/healthz":
            healthy = router.pool.healthy_count()
            self._send_json(200 if healthy else 503,
                            {"status": "ok" if healthy else "no_healthy",
                             "replicas": len(router.pool.replicas),
                             "healthy": healthy})
        elif url.path == "/livez":
            self._send_json(200, {"status": "alive"})
        elif url.path == "/stats":
            scrape = parse_qs(url.query).get("replicas", ["0"])[0] == "1"
            self._send_json(200, router.stats_record(
                scrape_replicas=scrape))
        else:
            self._send_json(404, {"error": f"no such endpoint {url.path!r}"})

    def _read_body(self) -> Optional[bytes]:
        te = self.headers.get("Transfer-Encoding", "")
        if te and te.lower() != "identity":
            self.close_connection = True
            return None
        try:
            length = int(self.headers.get("Content-Length", "0"))
            if length < 0:
                raise ValueError(length)
        except ValueError:
            self.close_connection = True
            return None
        return self.rfile.read(length) if length > 0 else b""

    def do_POST(self) -> None:  # noqa: N802 (http.server API)
        router = self.server.router
        body = self._read_body()
        if body is None:
            self._send_json(400, {"error": "unsupported Transfer-Encoding "
                                           "or bad Content-Length"})
            return
        path = urlparse(self.path)
        if path.path == "/admin/drain":
            rid = parse_qs(path.query).get("replica", [None])[0]
            if rid is None or rid not in router.pool.replicas:
                self._send_json(400, {"error": f"unknown replica {rid!r} "
                                               f"(have "
                                               f"{sorted(router.pool.replicas)})"})
                return
            def _drain_and_report(rid=rid):
                out = router.pool.drain(rid)
                # the 202 already went out — the OUTCOME must land
                # somewhere visible, or a timed-out drain (replica NOT
                # restarted, returned to rotation still running the old
                # process) silently impersonates a completed one
                verdict = (("complete, replica restarted"
                            if out["restarted"] else
                            "complete (no restart hook wired)")
                           if out["drained"] else
                           "TIMED OUT with work in flight — NOT "
                           "restarted, returned to rotation")
                print(f"[router] drain {rid}: {verdict} "
                      f"(waited {out['waited_s']}s, last inflight "
                      f"{out['inflight_last']})", flush=True)

            threading.Thread(target=_drain_and_report,
                             name=f"drain-{rid}", daemon=True).start()
            self._send_json(202, {"status": "draining", "replica": rid})
            return
        if path.path != "/v1/flow":
            self._send_json(404, {"error": f"no such endpoint {self.path!r}"})
            return
        status, resp_body, headers = router.proxy_flow(
            body, self.headers.get("X-Session-Id"),
            self.headers.get("Content-Type", "application/x-npz"))
        self._send(status, resp_body,
                   headers.pop("Content-Type", "application/json"), headers)


class Router:
    """The fleet front: ReplicaPool policy + HTTP proxy + health loop.

    ``replicas`` maps replica id -> base url (``http://host:port`` or
    bare ``host:port``). ``restarts`` optionally maps replica id -> a
    zero-arg restart hook for the drain lifecycle (router_cli wires the
    subprocess respawn; tests wire stubs).
    """

    def __init__(self, replicas: Dict[str, str], *,
                 host: str = "127.0.0.1", port: int = 0,
                 config: Optional[RouterConfig] = None,
                 restarts: Optional[Dict[str, Callable[[], None]]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 prober: Optional[Callable[[Replica], dict]] = None,
                 rng: Optional[random.Random] = None):
        self.config = config or RouterConfig()
        self.pool = ReplicaPool(replicas, self.config, clock=clock,
                                prober=prober)
        for rid, hook in (restarts or {}).items():
            self.pool.replicas[rid].restart = hook
        self.stats = RouterStats()
        # the autoscale window's since-last-scrape snapshot: /stats can
        # be scraped concurrently (operator curl + the bench + a second
        # router probe), and an unlocked read-swap would hand two
        # scrapes overlapping windows — double-counting shed into two
        # scale_up verdicts. Ranked above pool/stats: the whole
        # counters-read + prev-swap runs under it as one window
        self._autoscale_lock = OrderedLock("serve.router.autoscale")
        self._autoscale_prev = {"requests": 0, "shed": 0}
        self.clock = clock
        self._rng = rng or random.Random(0)
        self._inflight = 0
        # ranked before the stats lock: proxy_flow bumps counters while
        # holding the admission bound
        self._inflight_lock = OrderedLock("serve.router.inflight")
        self._httpd = _RouterHTTPServer((host, port), _RouterHandler,
                                        router=self)
        self._http_thread: Optional[threading.Thread] = None
        self._health_stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None

    # ---- addresses ------------------------------------------------------

    @property
    def address(self) -> Tuple[str, int]:
        host, port = self._httpd.server_address[:2]
        return host, port

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    # ---- proxying -------------------------------------------------------

    def _upstream(self, replica: Replica, body: bytes, session_id,
                  content_type: str, timeout: float) -> _UpstreamResult:
        """One proxied request over a FRESH connection — deliberately
        not pooled: a reused keep-alive connection the replica idled
        out raises the same RemoteDisconnected a crash does, which
        would passively mark (and eventually breaker-open) a healthy
        replica. A fresh connect can only fail if the replica is
        actually unreachable, keeping the retry/breaker signal clean;
        the connect itself is loopback/intra-cell cheap next to a flow
        forward."""
        conn = http.client.HTTPConnection(replica.host, replica.port,
                                          timeout=timeout)
        try:
            headers = {"Content-Type": content_type}
            if session_id:
                headers["X-Session-Id"] = session_id
            conn.request("POST", "/v1/flow", body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            keep = {k: v for k, v in resp.getheaders()
                    if k in ("X-Warm-Start", "X-Bucket", "Content-Type",
                             "Retry-After")}
            return _UpstreamResult(resp.status, data, keep)
        finally:
            conn.close()

    def proxy_flow(self, body: bytes, session_id: Optional[str],
                   content_type: str) -> Tuple[int, bytes, dict]:
        """One client request end to end: admission -> route -> proxy
        -> (maybe) one failover retry. Returns (status, body, headers);
        never raises."""
        st = self.stats
        cfg = self.config
        with self._inflight_lock:
            st.bump("requests")
            if self._inflight >= cfg.max_inflight:
                st.bump("shed_router")
                return (503,
                        json.dumps({"error": "router overloaded: "
                                    f"{self._inflight} in flight"}).encode(),
                        {"Retry-After": "1"})
            self._inflight += 1
        t0 = self.clock()
        deadline = t0 + cfg.deadline_s
        try:
            return self._proxy_with_retry(body, session_id, content_type,
                                          deadline)
        finally:
            with self._inflight_lock:
                self._inflight -= 1
            st.note_latency(self.clock() - t0)

    def _proxy_with_retry(self, body, session_id, content_type,
                          deadline) -> Tuple[int, bytes, dict]:
        st = self.stats
        cfg = self.config
        try:
            replica = self.pool.route(session_id)
        except NoHealthyReplica as e:
            st.bump("no_healthy")
            return (503, json.dumps({"error": str(e)}).encode(),
                    {"Retry-After": "1"})
        retried = False
        first_rid = replica.rid
        last_shed: Optional[_UpstreamResult] = None
        for attempt in (0, 1):
            budget = deadline - self.clock()
            if budget <= 0:
                st.bump("upstream_errors")
                return (504, json.dumps(
                    {"error": f"deadline ({cfg.deadline_s:g}s) exhausted"
                              f" after {attempt} attempt(s)"}).encode(), {})
            try:
                res = self._upstream(
                    replica, body, session_id, content_type,
                    timeout=min(budget, cfg.upstream_timeout_s))
            except _RETRYABLE:
                # the request provably never completed upstream — mark
                # the replica (passive breaker input) and fail over
                self.pool.mark_failure(replica.rid)
                res = None
            except OSError:
                # timeouts and the rest of the socket zoo: mark, but do
                # NOT retry (the work may still be running — re-running
                # doubles load exactly when the fleet is slowest)
                self.pool.mark_failure(replica.rid)
                st.bump("upstream_errors")
                return (502, json.dumps(
                    {"error": f"upstream {replica.rid} failed"}).encode(),
                    {})
            if res is not None and res.status != 503:
                if res.status == 200:
                    st.bump("proxied_ok")
                    if retried:
                        st.bump("failovers")
                res.headers["X-Replica"] = replica.rid
                res.headers["X-Router-Retries"] = str(int(retried))
                return res.status, res.body, res.headers
            if res is not None:
                # replica shed (or is draining): it is healthy, just
                # loaded — not a breaker failure. Try one other replica.
                last_shed = res
            if attempt == 1:
                break
            alt = self.pool.alternate(first_rid, session_id)
            if alt is None:
                break
            # jittered backoff, capped by the remaining budget
            pause = min(cfg.retry_backoff_s * (1 + self._rng.random()),
                        max(0.0, deadline - self.clock()))
            if pause > 0:
                time.sleep(pause)
            st.bump("retries")
            retried = True
            replica = alt
        if last_shed is not None:
            # every replica we could reach shed: the honest answer is
            # the fleet-wide 503 (+ Retry-After), never a 502
            st.bump("shed_upstream")
            last_shed.headers["X-Replica"] = replica.rid
            last_shed.headers.setdefault("Retry-After", "1")
            return last_shed.status, last_shed.body, last_shed.headers
        st.bump("upstream_errors")
        return (502, json.dumps(
            {"error": f"upstream failed "
                      f"({'both attempts' if retried else first_rid}); "
                      f"no healthy alternate"}).encode(), {})

    # ---- stats ----------------------------------------------------------

    def _autoscale_record(self) -> dict:
        """The autoscale hook: the signals a scaler needs, derived from
        what the fleet already measures — replica queue depths (off the
        schedulers' health payloads, backed by their EWMA service
        estimates) and the shed counters. The window is SINCE THE LAST
        SCRAPE (deltas against a kept snapshot): cumulative lifetime
        counters would latch one ancient shed into scale_up forever and
        make scale_down unreachable after the first request.
        Recommendation: UP when anything shed this window or every
        routable replica is carrying queued work; DOWN when >1 replica
        is routable and the window was idle; else steady."""
        with self._autoscale_lock:
            # read-and-swap is ONE atomic window: computing `cur`
            # outside the lock lets two concurrent scrapes swap
            # snapshots out of order (an older cur stored as prev
            # double-counts the newer scrape's window). The autoscale
            # lock ranks ABOVE pool/stats in LOCK_ORDER precisely so
            # these record() calls may nest under it
            pool_rec = self.pool.record()
            st = self.stats.record()
            cur = {"requests": st["requests"],
                   "shed": (st["shed_router"] + st["shed_upstream"]
                            + st["no_healthy"])}
            prev = self._autoscale_prev
            self._autoscale_prev = cur
        # counters only move forward except across reset_stats(); a
        # negative delta means a reset — the window restarts at cur
        d_req = (cur["requests"] - prev["requests"]
                 if cur["requests"] >= prev["requests"]
                 else cur["requests"])
        d_shed = (cur["shed"] - prev["shed"]
                  if cur["shed"] >= prev["shed"] else cur["shed"])
        healthy = pool_rec["healthy"]
        # queue depths from ROUTABLE replicas only: a breaker-open
        # corpse's last cached payload is a frozen snapshot, and its
        # stale depth would bias toward spurious scale_up
        depths = [r["health"].get("queue_depth", 0)
                  for r in pool_rec["replicas"].values()
                  if r["health"] and r["state"] == CLOSED
                  and r["ready"] and not r["draining"]]
        busy = bool(depths) and all(d > 0 for d in depths)
        if d_shed > 0 or (healthy and busy):
            rec = "scale_up"
        elif healthy > 1 and d_req == 0:
            rec = "scale_down"
        else:
            rec = "steady"
        return {"recommendation": rec, "healthy": healthy,
                "shed_window": d_shed, "queue_depths": depths}

    def stats_record(self, scrape_replicas: bool = False) -> dict:
        rec = {
            "router": self.stats.record(),
            "pool": self.pool.record(),
            "autoscale": self._autoscale_record(),
            # lock-order runtime verdicts + contention gauges for the
            # router's own thread fabric (handler threads, health loop,
            # drain threads) — the chaos failover phase pins the
            # violation counters at 0
            "locks": _locks.stats_record(),
        }
        if scrape_replicas:
            scraped = {}
            for rid, r in self.pool.replicas.items():
                try:
                    conn = http.client.HTTPConnection(
                        r.host, r.port, timeout=self.config.probe_timeout_s)
                    try:
                        conn.request("GET", "/stats")
                        scraped[rid] = json.loads(
                            conn.getresponse().read())
                    finally:
                        conn.close()
                except Exception as e:
                    scraped[rid] = {"error": f"{type(e).__name__}: {e}"}
            rec["replica_stats"] = scraped
        return rec

    def reset_stats(self) -> None:
        self.stats.reset()
        self.pool.reset_counters()
        with self._autoscale_lock:
            self._autoscale_prev = {"requests": 0, "shed": 0}

    # ---- lifecycle ------------------------------------------------------

    def _health_loop(self) -> None:
        while not self._health_stop.is_set():
            try:
                self.pool.probe_once()
            except Exception as e:   # a probe bug must not kill routing
                print(f"[router] health sweep failed: "
                      f"{type(e).__name__}: {e}", flush=True)
            self._health_stop.wait(self.config.probe_interval_s / 2)

    def start(self, *, health_thread: bool = True) -> "Router":
        if health_thread:
            # synchronous first sweep: the listener opens with breaker
            # state that reflects reality, not optimism
            self.pool.probe_once()
            self._health_thread = threading.Thread(
                target=self._health_loop, name="router-health", daemon=True)
            self._health_thread.start()
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="router-http",
            daemon=True)
        self._http_thread.start()
        return self

    def stop(self) -> None:
        self._health_stop.set()
        if self._http_thread is not None:
            self._httpd.shutdown()
        self._httpd.server_close()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5.0)
