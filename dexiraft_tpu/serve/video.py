"""Streaming video engine: cross-frame feature reuse over the split model.

The pair engine (serve.engine) treats every request as two fresh frames:
for a chained video stream that recomputes the full encoder stack on
BOTH frames of every pair even though frame t+1's ``fmap1`` is
byte-identical to frame t's ``fmap2`` (models/raft.py). This engine
serves the chained-pairs workload (the reference repo's demo.py loop)
through the split model instead:

  * ``encode_fn`` (train.step.make_encode_step) runs ONCE per NEW frame
    — the previous frame's feature dict comes from the device-resident
    session carry (sessions.DeviceSessionStore), so a warm stream pays
    half the encoder FLOPs of chained pair calls;
  * ``refine_fn`` (train.step.make_refine_step) runs the scanned
    refinement from the two feature dicts with an always-materialized
    flow_init (zeros == cold — one executable per bucket);
  * ``splat_fn`` forward-interpolates flow_low into the next frame's
    seed ON DEVICE — together with the feature carry, the per-frame
    host<->device traffic is exactly one frame up and one flow_up down
    (the payload), ZERO carry bytes.

Chunk semantics (the ``POST /v1/flow/stream`` wire contract): a chunk of
T same-geometry frames under one ``X-Session-Id`` yields

  * T flows when the session has a carry (pairs: (carry, f_0),
    (f_0, f_1), ..., (f_{T-2}, f_{T-1})),
  * T-1 flows cold (consecutive pairs only; a cold T=1 chunk yields no
    flow and just primes the carry).

Frames are processed one at a time, so memory is CONSTANT in T and in
the total stream length; a bucket change mid-stream restarts that one
stream cold (the misaligned-seed rule, same as SessionStore).

Compile discipline: ``warmup()`` drives a 2-frame zero chunk per named
geometry, compiling the encode, refine, and splat signatures before
traffic; after that a strict service is compile-flat (the engine keys
compiled buckets and raises through the shared RecompileWatch on an
unexpected retrace). Like the pair engine, this module imports no jax at
module level — numpy-stub encode/refine/splat fns unit-test the chunk
and carry logic without a model.
"""

from __future__ import annotations

import collections
import contextlib
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from dexiraft_tpu.analysis.locks import OrderedLock
from dexiraft_tpu.data.padder import InputPadder
from dexiraft_tpu.serve.buckets import bucket_shape
from dexiraft_tpu.serve.sessions import DeviceSessionStore

EncodeFn = Callable[[Any], Dict[str, Any]]
RefineFn = Callable[[Dict[str, Any], Dict[str, Any], Any], Tuple[Any, Any]]
SplatFn = Callable[[Any], Any]

_PCTL_WINDOW = 4096  # bounded latency window, same rationale as ServeStats


class StreamOverloaded(RuntimeError):
    """Raised at admission when too many chunks are already queued on
    the engine lock — the streaming twin of scheduler.QueueFull (the
    HTTP layer sheds with a 503 + Retry-After instead of letting every
    handler thread pile up behind one in-flight chunk)."""


def _to_host(x):
    if isinstance(x, np.ndarray):
        return x
    import jax  # deferred: module stays importable without jax

    # explicit device->host fetch (jaxlint JL007): flow_up IS the
    # response payload — the one sanctioned D2H of the streaming path
    return jax.device_get(x)


class ChunkResult(NamedTuple):
    """One processed chunk: host flows (each unpadded (H, W, 2)), and
    what served it — the HTTP layer maps these onto response headers."""

    flows: List[np.ndarray]
    warm: bool                  # the session carry seeded the first pair
    bucket: Tuple[int, int]
    frames_in: int
    # adaptive engines only (None on fixed refine fns): mean refinement
    # iterations actually run across this chunk's pairs — the stream
    # twin of the pair endpoint's X-Iters-Used header
    iters_used: Optional[float] = None


class VideoEngine:
    """Session-carried streaming driver over the split encode/refine
    steps. One chunk at a time (``_lock``): frames of a stream are
    serially dependent anyway, and one in-order device stream keeps the
    compile/strict discipline simple — parallelism at this tier comes
    from replicas (serve/router.py), not intra-process threads."""

    def __init__(
        self,
        encode_fn: EncodeFn,
        refine_fn: RefineFn,
        splat_fn: Optional[SplatFn] = None,
        *,
        sessions: Optional[DeviceSessionStore] = None,
        put: Optional[Callable[[Any], Any]] = None,
        mode: str = "sintel",
        stride: int = 8,
        bucket_multiple: Optional[int] = None,
        max_chunk_frames: int = 64,
        max_pending_chunks: int = 8,
        adaptive: bool = False,
        strict: bool = False,
        watch=None,
    ):
        if max_chunk_frames < 1:
            raise ValueError(
                f"max_chunk_frames must be >= 1, got {max_chunk_frames}")
        if max_pending_chunks < 1:
            raise ValueError(
                f"max_pending_chunks must be >= 1, got {max_pending_chunks}")
        self.encode_fn = encode_fn
        self.refine_fn = refine_fn
        # identity splat = raw flow_low seeds the next pair (numpy-stub
        # tests); serve_cli wires the jitted on-device forward_interpolate
        self.splat_fn = splat_fn if splat_fn is not None else (lambda x: x)
        self.sessions = sessions
        # identity put suits numpy-stub fns; jax callers MUST pass
        # jax.device_put (an implicit H2D inside the jitted encode would
        # trip the strict transfer guard — and hide a real per-frame copy)
        self.put = put if put is not None else (lambda x: x)
        self.mode = mode
        self.stride = stride
        self.bucket_multiple = bucket_multiple
        self.max_chunk_frames = max_chunk_frames
        self.max_pending_chunks = max_pending_chunks
        # adaptive contract: refine_fn returns (flow_low, flow_up,
        # iters_used, final_delta) — the convergence gate exits early
        # per-pair; streaming rides the FULL iteration budget (chunks
        # bypass the scheduler's SLO budgets; the gate is the win here)
        self.adaptive = adaptive
        self.strict = strict
        if watch is None:
            from dexiraft_tpu.analysis.guards import RecompileWatch

            watch = RecompileWatch("video")
        self.watch = watch
        # named + rank-ordered (analysis/locks.py LOCK_ORDER): the chunk
        # lock is the fleet's outermost — a chunk's frame loop nests the
        # stats lock, the device session store, and the shared watch
        self._lock = OrderedLock("serve.video.chunk")
        # chunks admitted but unanswered (waiting on _lock OR mid-loop):
        # the router's zero-drop drain polls /healthz inflight to 0, so
        # streaming work must count there like scheduler.inflight()
        self._inflight_lock = OrderedLock("serve.video.inflight")
        self._inflight = 0
        # counters/latency get their OWN lock: _lock is held for a whole
        # chunk's frame loop, and a /stats scrape must not stall behind
        # one live chunk
        self._stats_lock = OrderedLock("serve.video.stats")
        self._compiled: set = set()
        self._zero_fi: Dict[Tuple[int, ...], Any] = {}
        # counters (reset via reset_stats; surfaced on /stats)
        self.chunks = 0
        self.frames_in = 0
        self.flows_out = 0
        self.warm_chunks = 0
        self.cold_chunks = 0
        self.flow_latency_s: "collections.deque" = collections.deque(
            maxlen=_PCTL_WINDOW)
        # adaptive mode: per-pair iters_used / final-delta samples
        # (empty deques on fixed engines — /stats keys are conditional)
        self.iters_used: "collections.deque" = collections.deque(
            maxlen=_PCTL_WINDOW)
        self.final_delta: "collections.deque" = collections.deque(
            maxlen=_PCTL_WINDOW)

    # ---- input validation ----------------------------------------------

    def validate_frames(self, frames: Any) -> np.ndarray:
        """Reject a malformed chunk at the door (HTTP 400) instead of a
        shape error deep inside the jitted encode step."""
        frames = np.asarray(frames)
        if frames.ndim != 4 or frames.shape[-1] != 3:
            raise ValueError(
                f"frames must be rank-4 (T, H, W, 3) RGB, got shape "
                f"{frames.shape}")
        if frames.shape[0] < 1:
            raise ValueError("frames chunk is empty (T must be >= 1)")
        if frames.shape[0] > self.max_chunk_frames:
            # one chunk holds the engine lock for its whole frame loop:
            # an unbounded T would starve every other stream behind one
            # request — clients split long video into bounded chunks
            # (the carry makes that free)
            raise ValueError(
                f"frames chunk has T={frames.shape[0]} frames; this "
                f"replica caps chunks at {self.max_chunk_frames} — "
                f"split the stream into smaller chunks (the session "
                f"carry keeps them warm across requests)")
        if not (np.issubdtype(frames.dtype, np.floating)
                or np.issubdtype(frames.dtype, np.integer)):
            raise ValueError(
                f"frames dtype must be a real numeric type castable to "
                f"float32, got {frames.dtype}")
        return frames

    # ---- core ----------------------------------------------------------

    def _zero_flow_init(self, h8: int, w8: int):
        """Cached cold seed at the bucket's 1/8 shape — flow_init is
        ALWAYS materialized so cold and warm pairs share one refine
        executable (zeros == no warm start; the engine contract)."""
        key = (h8, w8)
        fi = self._zero_fi.get(key)
        if fi is None:
            fi = self._zero_fi[key] = self.put(
                np.zeros((1, h8, w8, 2), np.float32))
        return fi

    def process_chunk(self, session_id: Optional[str],
                      frames: Any) -> ChunkResult:
        """Run one chunk of same-geometry frames through the stream.

        With a ``session_id`` (and a session store) the carry persists
        across chunks: the previous chunk's last frame pairs with this
        chunk's first frame, and the newest frame's features + splatted
        seed are stored back — all device-resident, no per-frame
        host<->device carry bytes. ``session_id=None`` processes the
        chunk standalone (cold, nothing stored).
        """
        # empty/blank id == sessionless, matching the pair endpoint's
        # truthiness check — "" as a real key would silently share one
        # carry across every client that sends a blank header
        session_id = session_id or None
        frames = self.validate_frames(frames)
        t_frames, h, w = frames.shape[0], frames.shape[1], frames.shape[2]
        bucket = bucket_shape(h, w, self.stride, self.bucket_multiple)
        padder = InputPadder((h, w, 3), mode=self.mode, stride=self.stride,
                             target=bucket)
        h8, w8 = bucket[0] // self.stride, bucket[1] // self.stride

        with self._inflight_lock:
            if self._inflight >= self.max_pending_chunks:
                # bounded admission (scheduler.QueueFull discipline):
                # chunks serialize on the engine lock, so past the cap
                # each extra request pins a handler thread for minutes —
                # shed loudly instead
                raise StreamOverloaded(
                    f"{self._inflight} chunk(s) already queued "
                    f"(max_pending_chunks={self.max_pending_chunks}); "
                    f"retry with backoff")
            self._inflight += 1
        try:
            return self._process_locked(session_id, frames, t_frames,
                                        bucket, padder, h8, w8)
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    def _process_locked(self, session_id, frames, t_frames, bucket, padder,
                        h8, w8) -> ChunkResult:
        with self._lock:
            fresh = bucket not in self._compiled
            feats_prev = flow_init = None
            warm = False
            if session_id is not None and self.sessions is not None:
                carry = self.sessions.get(session_id, bucket)
                if carry is not None:
                    feats_prev, flow_init = carry
                    warm = True

            flows: List[np.ndarray] = []
            chunk_iters: List[int] = []
            # a fresh bucket's frame loop compiles encode/refine/splat:
            # run it inside a sanctioned window so the pair dispatcher's
            # concurrent strict check (shared watch, process-global
            # compile counter, separate thread) never reads the expected
            # compiles as drift
            win = (self.watch.sanctioned() if fresh
                   else contextlib.nullcontext())
            with win:
                for i in range(t_frames):
                    t0 = time.perf_counter()
                    padded = padder.pad(
                        np.asarray(frames[i], np.float32))[0][None]
                    feats = self.encode_fn(self.put(padded))
                    if feats_prev is not None:
                        if flow_init is None:
                            flow_init = self._zero_flow_init(h8, w8)
                        if self.adaptive:
                            (flow_low, flow_up, pair_iters,
                             pair_delta) = self.refine_fn(
                                feats_prev, feats, flow_init)
                            # one fetch per pair, same sanctioned D2H as
                            # flow_up (the (1,) scalars piggyback on the
                            # payload fetch, not a new transfer class)
                            iu = int(_to_host(pair_iters)[0])
                            fd = float(_to_host(pair_delta)[0])
                        else:
                            flow_low, flow_up = self.refine_fn(
                                feats_prev, feats, flow_init)
                        flow_init = self.splat_fn(flow_low)
                        flows.append(padder.unpad(_to_host(flow_up)[0]))
                        with self._stats_lock:
                            self.flow_latency_s.append(
                                time.perf_counter() - t0)
                            if self.adaptive:
                                chunk_iters.append(iu)
                                self.iters_used.append(iu)
                                self.final_delta.append(fd)
                    feats_prev = feats

            if session_id is not None and self.sessions is not None:
                self.sessions.put(
                    session_id, bucket, feats_prev,
                    flow_init if flow_init is not None
                    else self._zero_flow_init(h8, w8))

            with self._stats_lock:
                self.chunks += 1
                self.frames_in += t_frames
                self.flows_out += len(flows)
                if warm:
                    self.warm_chunks += 1
                else:
                    self.cold_chunks += 1
            if fresh:
                # expected compiles (encode + refine + splat for a new
                # bucket): move the shared drift baseline past them,
                # exactly like the pair engine's first bucket dispatch
                with self._stats_lock:
                    self._compiled.add(bucket)
                self.watch.mark_warm()
            elif self.strict:
                self.watch.check()
            else:
                self.watch.warn_if_drifted()
        mean_iters = (sum(chunk_iters) / len(chunk_iters)
                      if chunk_iters else None)
        return ChunkResult(flows, warm, bucket, t_frames, mean_iters)

    # ---- lifecycle / observability -------------------------------------

    def inflight(self) -> int:
        """Chunks admitted but unanswered (queued on the engine lock or
        mid-frame-loop) — counted into /healthz ``inflight`` so the
        router's zero-drop drain waits out live streaming work exactly
        like scheduler-admitted pairs."""
        with self._inflight_lock:
            return self._inflight

    def warmup(self, geometries) -> None:
        """Pre-compile the streaming signatures (encode, refine, splat)
        for each "HxW" geometry with a 2-frame zero chunk — after this a
        --strict service is compile-flat from the first streamed frame.
        Nothing is stored (no session id) and the counters are reset:
        warmup is not traffic."""
        for geom in geometries:
            h, w = (int(v) for v in geom.split("x"))
            self.process_chunk(None, np.zeros((2, h, w, 3), np.float32))
        self.reset_stats()

    def reset_stats(self) -> None:
        """Zero the traffic counters; compiled buckets, the warm
        baseline, and live session carries survive (state, not
        statistics) — the /stats?reset=1 window handoff."""
        with self._stats_lock:
            self.chunks = self.frames_in = self.flows_out = 0
            self.warm_chunks = self.cold_chunks = 0
            self.flow_latency_s.clear()
            self.iters_used.clear()
            self.final_delta.clear()
        if self.sessions is not None:
            self.sessions.reset_counters()

    def _pctl_ms(self, p: float) -> float:
        if not self.flow_latency_s:
            return 0.0
        return round(float(np.percentile(self.flow_latency_s, p)) * 1e3, 2)

    def stats_record(self) -> dict:
        """Self-describing blob for /stats: chunk/flow counters,
        per-flow latency percentiles, and the device-carry session store
        (byte budget, evictions). Takes only the stats lock — a scrape
        never stalls behind a live chunk's frame loop."""
        with self._stats_lock:
            rec = {
                "chunks": self.chunks,
                "frames_in": self.frames_in,
                "flows_out": self.flows_out,
                "warm_chunks": self.warm_chunks,
                "cold_chunks": self.cold_chunks,
                "flow_p50_ms": self._pctl_ms(50),
                "flow_p99_ms": self._pctl_ms(99),
                "compiled_buckets": sorted(
                    f"{h}x{w}" for h, w in self._compiled),
            }
            if self.adaptive:
                # conditional like the engine's block: fixed-path /stats
                # schema pins stay byte-identical
                iu = list(self.iters_used)
                rec.update(
                    adaptive=True,
                    iters_used_mean=(round(sum(iu) / len(iu), 2)
                                     if iu else 0.0),
                    iters_used_p99=(round(float(
                        np.percentile(iu, 99)), 2) if iu else 0.0),
                    final_delta_p50=(round(float(np.percentile(
                        list(self.final_delta), 50)), 5)
                        if self.final_delta else 0.0),
                )
        rec["sessions"] = (self.sessions.stats_record()
                          if self.sessions is not None else None)
        return rec
