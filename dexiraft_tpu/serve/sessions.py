"""Session affinity: per-stream flow_init carry with TTL eviction.

RAFT's iterative design makes consecutive frames of one video stream a
measured win (scripts/warmstart_bench.py): the previous frame's low-res
flow seeds the next frame's refinement, so the model starts near the
answer instead of from zeros. A stateless request API throws that away.
This store keeps the carry server-side, keyed by a client-chosen stream
id (the ``X-Session-Id`` header), so a camera/video client gets
warm-start across plain independent HTTP requests.

Semantics:

  * the carry is BUCKET-SCOPED — flow_init lives at the padded bucket's
    1/8 resolution (the engine's per-item carry contract, see
    engine.Result.flow_low). A session whose frames change geometry into
    a different bucket silently restarts cold (counted, not an error):
    re-gridding across buckets would hand the model a misaligned seed.
  * TTL eviction — a stream that stops talking for ``ttl_s`` is dropped;
    the next request with that id starts cold. Expiry is enforced lazily
    on every get/put (no reaper thread to leak) plus a full sweep on
    ``stats_record()`` so /stats never reports ghosts.
  * LRU bound — at most ``max_sessions`` live streams; admitting one
    more evicts the least-recently-used (a public endpoint must bound
    memory against id churn, deliberate or buggy).
  * thread-safe — handler threads get/put concurrently; one lock, no
    I/O under it.

A session holds ONE most-recent carry, not history: flow_init for frame
j+1 is exactly frame j's (splatted) flow_low, nothing older matters.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Optional, Tuple

import numpy as np


class _Entry:
    __slots__ = ("bucket", "carry", "t_touch")

    def __init__(self, bucket: Tuple[int, int], carry: np.ndarray,
                 t_touch: float):
        self.bucket = bucket
        self.carry = carry
        self.t_touch = t_touch


class SessionStore:
    """TTL+LRU map: stream id -> (bucket, latest flow carry)."""

    def __init__(self, ttl_s: float = 60.0, max_sessions: int = 1024,
                 clock=None):
        if ttl_s <= 0:
            raise ValueError(f"ttl_s must be > 0, got {ttl_s}")
        if max_sessions < 1:
            raise ValueError(f"max_sessions must be >= 1, got {max_sessions}")
        if clock is None:
            import time

            clock = time.monotonic
        self.ttl_s = ttl_s
        self.max_sessions = max_sessions
        self.clock = clock
        self._lock = threading.Lock()
        # insertion order == recency order (move_to_end on touch)
        self._entries: "collections.OrderedDict[str, _Entry]" = \
            collections.OrderedDict()
        self.hits = 0
        self.misses = 0            # unknown id (fresh stream)
        self.expired = 0           # TTL evictions
        self.lru_evicted = 0       # max_sessions evictions
        self.bucket_resets = 0     # geometry moved buckets -> cold restart

    # ---- internal (lock held) ------------------------------------------

    def _sweep(self, now: float) -> None:
        """Drop every TTL-expired entry (oldest-touched first)."""
        dead = [sid for sid, e in self._entries.items()
                if now - e.t_touch > self.ttl_s]
        for sid in dead:
            del self._entries[sid]
        self.expired += len(dead)

    # ---- handler-thread API --------------------------------------------

    def get(self, session_id: str,
            bucket: Tuple[int, int]) -> Optional[np.ndarray]:
        """The stream's carry for this bucket, or None (cold start:
        unknown id, TTL-expired, or the stream changed buckets)."""
        now = self.clock()
        with self._lock:
            e = self._entries.get(session_id)
            if e is None:
                self.misses += 1
                return None
            if now - e.t_touch > self.ttl_s:
                del self._entries[session_id]
                self.expired += 1
                return None
            if e.bucket != bucket:
                # misaligned seed is worse than a cold start — restart
                del self._entries[session_id]
                self.bucket_resets += 1
                return None
            e.t_touch = now
            self._entries.move_to_end(session_id)
            self.hits += 1
            return e.carry

    def put(self, session_id: str, bucket: Tuple[int, int],
            carry: Any) -> None:
        """Record the stream's newest carry (frame j's splatted flow_low,
        already host numpy — the engine fetches before yielding)."""
        carry = np.asarray(carry)
        now = self.clock()
        with self._lock:
            self._sweep(now)
            e = self._entries.get(session_id)
            if e is None:
                while len(self._entries) >= self.max_sessions:
                    self._entries.popitem(last=False)
                    self.lru_evicted += 1
                self._entries[session_id] = _Entry(bucket, carry, now)
            else:
                e.bucket = bucket
                e.carry = carry
                e.t_touch = now
                self._entries.move_to_end(session_id)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def reset_counters(self) -> None:
        """Zero the flow counters (the /stats?reset=1 scrape window
        handoff); live sessions — the actual carry state — survive."""
        with self._lock:
            self.hits = self.misses = self.expired = 0
            self.lru_evicted = self.bucket_resets = 0

    def stats_record(self) -> dict:
        """Self-describing blob for the /stats endpoint."""
        with self._lock:
            self._sweep(self.clock())
            return {
                "active": len(self._entries),
                "ttl_s": self.ttl_s,
                "max_sessions": self.max_sessions,
                "hits": self.hits,
                "misses": self.misses,
                "expired": self.expired,
                "lru_evicted": self.lru_evicted,
                "bucket_resets": self.bucket_resets,
            }
