"""Session affinity: per-stream flow_init carry with TTL eviction.

RAFT's iterative design makes consecutive frames of one video stream a
measured win (scripts/warmstart_bench.py): the previous frame's low-res
flow seeds the next frame's refinement, so the model starts near the
answer instead of from zeros. A stateless request API throws that away.
This store keeps the carry server-side, keyed by a client-chosen stream
id (the ``X-Session-Id`` header), so a camera/video client gets
warm-start across plain independent HTTP requests.

Semantics:

  * the carry is BUCKET-SCOPED — flow_init lives at the padded bucket's
    1/8 resolution (the engine's per-item carry contract, see
    engine.Result.flow_low). A session whose frames change geometry into
    a different bucket silently restarts cold (counted, not an error):
    re-gridding across buckets would hand the model a misaligned seed.
  * TTL eviction — a stream that stops talking for ``ttl_s`` is dropped;
    the next request with that id starts cold. Expiry is enforced lazily
    on every get/put (no reaper thread to leak) plus a full sweep on
    ``stats_record()`` so /stats never reports ghosts.
  * LRU bound — at most ``max_sessions`` live streams; admitting one
    more evicts the least-recently-used (a public endpoint must bound
    memory against id churn, deliberate or buggy).
  * thread-safe — handler threads get/put concurrently; one lock, no
    I/O under it.

A session holds ONE most-recent carry, not history: flow_init for frame
j+1 is exactly frame j's (splatted) flow_low, nothing older matters.

Two stores live here: :class:`SessionStore` (the PR 6 flow-seed carry —
one small array per stream, TTL+LRU is enough) and
:class:`DeviceSessionStore` (the streaming tier's per-frame FEATURE
carry — device arrays heavy enough that a BYTE budget governs
admission; see its docstring for the math).
"""

from __future__ import annotations

import collections
from typing import Any, Dict, Optional, Tuple

import numpy as np

from dexiraft_tpu.analysis.locks import OrderedLock


class _Entry:
    __slots__ = ("bucket", "carry", "t_touch")

    def __init__(self, bucket: Tuple[int, int], carry: np.ndarray,
                 t_touch: float):
        self.bucket = bucket
        self.carry = carry
        self.t_touch = t_touch


class SessionStore:
    """TTL+LRU map: stream id -> (bucket, latest flow carry)."""

    def __init__(self, ttl_s: float = 60.0, max_sessions: int = 1024,
                 clock=None):
        if ttl_s <= 0:
            raise ValueError(f"ttl_s must be > 0, got {ttl_s}")
        if max_sessions < 1:
            raise ValueError(f"max_sessions must be >= 1, got {max_sessions}")
        if clock is None:
            import time

            clock = time.monotonic
        self.ttl_s = ttl_s
        self.max_sessions = max_sessions
        self.clock = clock
        self._lock = OrderedLock("serve.sessions.store")
        # insertion order == recency order (move_to_end on touch)
        self._entries: "collections.OrderedDict[str, _Entry]" = \
            collections.OrderedDict()
        self.hits = 0
        self.misses = 0            # unknown id (fresh stream)
        self.expired = 0           # TTL evictions
        self.lru_evicted = 0       # max_sessions evictions
        self.bucket_resets = 0     # geometry moved buckets -> cold restart

    # ---- internal (lock held) ------------------------------------------

    def _sweep(self, now: float) -> None:
        """Drop every TTL-expired entry (oldest-touched first)."""
        dead = [sid for sid, e in self._entries.items()
                if now - e.t_touch > self.ttl_s]
        for sid in dead:
            del self._entries[sid]
        self.expired += len(dead)

    # ---- handler-thread API --------------------------------------------

    def get(self, session_id: str,
            bucket: Tuple[int, int]) -> Optional[np.ndarray]:
        """The stream's carry for this bucket, or None (cold start:
        unknown id, TTL-expired, or the stream changed buckets)."""
        now = self.clock()
        with self._lock:
            e = self._entries.get(session_id)
            if e is None:
                self.misses += 1
                return None
            if now - e.t_touch > self.ttl_s:
                del self._entries[session_id]
                self.expired += 1
                return None
            if e.bucket != bucket:
                # misaligned seed is worse than a cold start — restart
                del self._entries[session_id]
                self.bucket_resets += 1
                return None
            e.t_touch = now
            self._entries.move_to_end(session_id)
            self.hits += 1
            return e.carry

    def put(self, session_id: str, bucket: Tuple[int, int],
            carry: Any) -> None:
        """Record the stream's newest carry (frame j's splatted flow_low).
        Host numpy OR a device array: the device-resident handoff
        (serve_cli default since the streaming PR) stores the jax array
        as-is — np.asarray on it would be the exact D2H round-trip the
        handoff removes — while list-like host input still normalizes."""
        if not hasattr(carry, "shape"):
            carry = np.asarray(carry)
        now = self.clock()
        with self._lock:
            self._sweep(now)
            e = self._entries.get(session_id)
            if e is None:
                while len(self._entries) >= self.max_sessions:
                    self._entries.popitem(last=False)
                    self.lru_evicted += 1
                self._entries[session_id] = _Entry(bucket, carry, now)
            else:
                e.bucket = bucket
                e.carry = carry
                e.t_touch = now
                self._entries.move_to_end(session_id)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def reset_counters(self) -> None:
        """Zero the flow counters (the /stats?reset=1 scrape window
        handoff); live sessions — the actual carry state — survive."""
        with self._lock:
            self.hits = self.misses = self.expired = 0
            self.lru_evicted = self.bucket_resets = 0

    def stats_record(self) -> dict:
        """Self-describing blob for the /stats endpoint."""
        with self._lock:
            self._sweep(self.clock())
            return {
                "active": len(self._entries),
                "ttl_s": self.ttl_s,
                "max_sessions": self.max_sessions,
                "hits": self.hits,
                "misses": self.misses,
                "expired": self.expired,
                "lru_evicted": self.lru_evicted,
                "bucket_resets": self.bucket_resets,
            }


# --------------------------------------------------------------------------
# device-resident streaming carry
# --------------------------------------------------------------------------


def carry_nbytes(features: Dict[str, Any], flow_init: Any) -> int:
    """HBM bytes one stream's carry pins: every feature array plus the
    flow seed. Works on numpy AND jax arrays (both expose .nbytes
    without a transfer) — the store never touches array CONTENTS, so it
    stays importable and unit-testable without jax."""
    total = 0 if flow_init is None else int(flow_init.nbytes)
    for v in features.values():
        total += int(v.nbytes)
    return total


class _DeviceEntry:
    __slots__ = ("bucket", "features", "flow_init", "nbytes", "t_touch")

    def __init__(self, bucket: Tuple[int, int], features: Dict[str, Any],
                 flow_init: Any, nbytes: int, t_touch: float):
        self.bucket = bucket
        self.features = features
        self.flow_init = flow_init
        self.nbytes = nbytes
        self.t_touch = t_touch


class DeviceSessionStore:
    """Byte-budgeted TTL+LRU map: stream id -> the DEVICE-resident
    streaming carry {per-frame feature dict, splatted flow_init}.

    The streaming path's carry is much heavier than the PR 6 flow seed:
    a 256-channel fmap + ctx (and the edge twins for v4/v5) at the
    bucket's 1/8 resolution — hundreds of KB to tens of MB per stream
    depending on geometry. Keeping it on device is the whole point (no
    per-frame H2D/D2H carry traffic, no re-encode of the shared frame),
    which means N streams x cached features now pin HBM. So on top of
    SessionStore's TTL + max_sessions discipline this store enforces a
    BYTE budget: admitting or growing a carry evicts least-recently-used
    streams until the total fits, and every eviction is counted for
    /stats (``budget_evicted``). One over-budget stream is kept (and
    counted via ``over_budget``) rather than thrashing itself cold.

    The arrays are stored as handed in — jax device arrays from the
    jitted encode/splat steps (their shardings are whatever the step's
    LAYOUT-pinned out_shardings resolved; the store never re-lays them
    out) or plain numpy in unit tests. Only ``.nbytes`` is ever read, so
    the module keeps the serve tier's no-jax-at-import contract.
    """

    def __init__(self, budget_bytes: int = 256 << 20, ttl_s: float = 60.0,
                 max_sessions: int = 1024, clock=None):
        if budget_bytes < 1:
            raise ValueError(f"budget_bytes must be >= 1, got {budget_bytes}")
        if ttl_s <= 0:
            raise ValueError(f"ttl_s must be > 0, got {ttl_s}")
        if max_sessions < 1:
            raise ValueError(f"max_sessions must be >= 1, got {max_sessions}")
        if clock is None:
            import time

            clock = time.monotonic
        self.budget_bytes = budget_bytes
        self.ttl_s = ttl_s
        self.max_sessions = max_sessions
        self.clock = clock
        self._lock = OrderedLock("serve.sessions.device")
        self._entries: "collections.OrderedDict[str, _DeviceEntry]" = \
            collections.OrderedDict()
        self.bytes_in_use = 0
        self.peak_bytes = 0
        self.hits = 0
        self.misses = 0
        self.expired = 0
        self.lru_evicted = 0       # max_sessions evictions
        self.budget_evicted = 0    # byte-budget evictions
        self.bucket_resets = 0     # geometry moved buckets -> cold restart
        self.over_budget = 0       # single stream alone exceeded the budget

    # ---- internal (lock held) ------------------------------------------

    def _drop(self, sid: str) -> None:
        e = self._entries.pop(sid)
        self.bytes_in_use -= e.nbytes

    def _sweep(self, now: float) -> None:
        dead = [sid for sid, e in self._entries.items()
                if now - e.t_touch > self.ttl_s]
        for sid in dead:
            self._drop(sid)
        self.expired += len(dead)

    def _evict_to_fit(self, keep: str) -> None:
        """Evict LRU streams (never ``keep``) until the budget holds."""
        while self.bytes_in_use > self.budget_bytes:
            victim = next((sid for sid in self._entries if sid != keep),
                          None)
            if victim is None:
                # the surviving stream alone busts the budget: keep it
                # (evicting the carry just written would silently turn
                # streaming into cold pairs) but make it observable
                self.over_budget += 1
                return
            self._drop(victim)
            self.budget_evicted += 1

    # ---- handler-thread API --------------------------------------------

    def get(self, session_id: str, bucket: Tuple[int, int]
            ) -> Optional[Tuple[Dict[str, Any], Any]]:
        """(features, flow_init) for the stream at this bucket, or None
        (cold: unknown id, TTL-expired, or the stream changed buckets —
        a misaligned carry is worse than a cold start, so a bucket
        change restarts exactly that stream)."""
        now = self.clock()
        with self._lock:
            e = self._entries.get(session_id)
            if e is None:
                self.misses += 1
                return None
            if now - e.t_touch > self.ttl_s:
                self._drop(session_id)
                self.expired += 1
                return None
            if e.bucket != bucket:
                self._drop(session_id)
                self.bucket_resets += 1
                return None
            e.t_touch = now
            self._entries.move_to_end(session_id)
            self.hits += 1
            return e.features, e.flow_init

    def put(self, session_id: str, bucket: Tuple[int, int],
            features: Dict[str, Any], flow_init: Any) -> None:
        """Record the stream's newest carry (the just-encoded frame's
        features + the splatted flow seed), evicting LRU streams if the
        byte budget demands it."""
        nbytes = carry_nbytes(features, flow_init)
        now = self.clock()
        with self._lock:
            self._sweep(now)
            if session_id in self._entries:
                self._drop(session_id)
            while len(self._entries) >= self.max_sessions:
                self._drop(next(iter(self._entries)))
                self.lru_evicted += 1
            self._entries[session_id] = _DeviceEntry(
                bucket, features, flow_init, nbytes, now)
            self.bytes_in_use += nbytes
            self._evict_to_fit(keep=session_id)
            self.peak_bytes = max(self.peak_bytes, self.bytes_in_use)

    def drop(self, session_id: str) -> bool:
        """Explicitly forget one stream (the streaming endpoint's
        bucket-change reset); True if it existed."""
        with self._lock:
            if session_id not in self._entries:
                return False
            self._drop(session_id)
            return True

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def reset_counters(self) -> None:
        """Zero the flow counters (the /stats?reset=1 window handoff);
        live carries — actual state — survive, as do the byte gauges
        that describe them (bytes_in_use is state, not a statistic)."""
        with self._lock:
            self.hits = self.misses = self.expired = 0
            self.lru_evicted = self.budget_evicted = 0
            self.bucket_resets = self.over_budget = 0
            self.peak_bytes = self.bytes_in_use

    def stats_record(self) -> dict:
        """Self-describing blob for the /stats endpoint."""
        with self._lock:
            self._sweep(self.clock())
            return {
                "active": len(self._entries),
                "ttl_s": self.ttl_s,
                "max_sessions": self.max_sessions,
                "budget_mb": round(self.budget_bytes / 2**20, 2),
                "bytes_in_use_mb": round(self.bytes_in_use / 2**20, 3),
                "peak_mb": round(self.peak_bytes / 2**20, 3),
                "hits": self.hits,
                "misses": self.misses,
                "expired": self.expired,
                "lru_evicted": self.lru_evicted,
                "budget_evicted": self.budget_evicted,
                "bucket_resets": self.bucket_resets,
                "over_budget": self.over_budget,
            }
