"""Throughput-mode inference engine over the jitted eval step.

The per-image eval loop (eval/validate.py pre-engine) ran one padded
frame pair at a time, synchronously: pad -> dispatch -> np.asarray (host
blocks until the chip finishes) -> unpad, with every distinct geometry
paying a fresh XLA compile. This engine gives the forward's consumers
the same treatment PR 2 gave training:

  * shape buckets (serve.buckets): geometries quantize to a bounded set
    of stride-aligned bucket shapes; one executable per bucket, cached
    in-process and in the PR 2 persistent XLA cache.
  * micro-batching: same-bucket frame pairs group into batches of
    `batch_size`, amortizing the DexiNed prelude / pyramid build exactly
    like training batches do. The tail batch of a bucket is padded back
    up to `batch_size` by replicating its last item — shape stability
    keeps the one-executable-per-bucket contract — and the filler
    results are masked out, so metrics cover exactly the dataset.
  * async in-flight dispatch: eval_fn only ENQUEUES device work (jax
    async dispatch) and the host->device put is async too, so holding
    `inflight` dispatched tickets before fetching overlaps device
    compute with host pad/stack/encode work. ServeStats (profiling.py)
    accounts the residual honestly: fetch_s is the compute the window
    failed to hide.
  * data-parallel serving: with a mesh, each batch device_puts sharded
    over the 'data' axis and the pinned eval step (train.step
    make_eval_step(mesh=...)) runs it SPMD across chips.

eval_fn contract: eval_fn(image1, image2, flow_init) -> (flow_low,
flow_up), POSITIONAL (the mesh path pins in_shardings, and jit rejects
kwargs when shardings are pinned), batched NHWC in [0, 255], flow_init
either None or a (B, H/8, W/8, 2) array. A flow_init row of ZEROS is
numerically identical to no warm start (RAFT adds it to coords0), which
is what makes per-item carry work: one batch can mix warm-started items
and cold items without a second executable.

ADAPTIVE engines (ServeConfig.adaptive): the eval_fn grows a trailing
``iter_budget`` positional and returns (flow_low, flow_up,
iters_used[B], final_delta[B]) — the convergence-gated while_loop path
(train.step make_eval_step(adaptive=True)). The budget is a TRACED
int32 scalar, so every budget value rides the bucket's ONE compiled
executable; the engine normalizes it to np.int32 in exactly one place
(_dispatch) so a warmup dispatch and a scheduler-budgeted dispatch can
never present different scalar avals (= a second executable). A None
budget means "the step's full configured iters" and is resolved by the
eval_fn wrapper, again to the same normalized aval.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import time
from typing import Any, Callable, Dict, Iterable, Iterator, List, NamedTuple, Optional, Tuple

import numpy as np

from dexiraft_tpu.data.padder import InputPadder
from dexiraft_tpu.profiling import ServeStats
from dexiraft_tpu.serve.buckets import BucketRegistry

EvalFn = Callable[..., Tuple[Any, Any]]


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine knobs (see module docstring for the design)."""

    batch_size: int = 1
    mode: str = "sintel"         # pad placement (data.padder modes)
    stride: int = 8
    # bucket quantization granule; None -> stride (reference pad shapes,
    # the metric-parity configuration)
    bucket_multiple: Optional[int] = None
    # dispatched-unfetched tickets to hold before blocking on a fetch
    inflight: int = 2
    # always materialize flow_init (zeros for cold items) so warm-start
    # streams keep one executable per bucket instead of two (None vs
    # array signatures)
    warm_start: bool = False
    # strict guard mode (analysis/guards.py): a recompile on an
    # already-compiled bucket signature RAISES RecompileBudgetExceeded
    # instead of the default one-line drift warning
    strict: bool = False
    # device-resident warm-start carry: per-item flow_init may be a jax
    # DEVICE array (the session store's splatted carry, never fetched to
    # host) — the engine assembles the batch's flow_init ON DEVICE (a
    # jitted row stack over cached zero rows) and keeps each Result's
    # flow_low as a device row instead of fetching it, so the carry
    # path moves ZERO host<->device bytes per frame. flow_up is still
    # fetched (it IS the response). Off (default): the PR 6 host-numpy
    # carry semantics, kept for multi-worker pools and the data-parallel
    # mesh path (pinned shardings re-lay the batch out anyway).
    device_carry: bool = False
    # adaptive-iteration eval_fn (module docstring "ADAPTIVE engines"):
    # dispatches thread an iter_budget scalar through the eval_fn and
    # Results carry per-item iters_used / final_delta
    adaptive: bool = False

    def __post_init__(self):
        if self.batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {self.batch_size}")
        if self.inflight < 1:
            raise ValueError(f"inflight must be >= 1, got {self.inflight}")

    @classmethod
    def from_args(cls, args, *, mode: str = "sintel",
                  warm_start: bool = False,
                  strict: Optional[bool] = None,
                  device_carry: bool = False,
                  adaptive: Optional[bool] = None) -> "ServeConfig":
        """Build from an argparse namespace that went through
        :func:`add_engine_args` — the ONE construction path eval_cli,
        serve_cli, and serve_bench share, so the batching knobs cannot
        drift between the batch-eval and persistent-service code paths."""
        return cls(
            batch_size=args.batch_size,
            mode=mode,
            bucket_multiple=args.bucket_multiple,
            inflight=args.inflight,
            warm_start=warm_start,
            strict=(getattr(args, "strict", False)
                    if strict is None else strict),
            device_carry=device_carry,
            adaptive=(getattr(args, "adaptive", False)
                      if adaptive is None else adaptive),
        )


def add_engine_args(p, *, batch_size: int = 1,
                    bucket_multiple: Optional[int] = None) -> None:
    """The shared engine-knob argparse surface (see ServeConfig.from_args).
    Defaults differ by caller — and the help text reflects the CALLER's
    defaults, not a hardcoded story: eval keeps batch_size=1 / reference
    pad shapes (the metric-parity configuration); the serving CLI raises
    both (batching + bounded executables are the point of a service)."""
    p.add_argument("--batch_size", type=int, default=batch_size,
                   help="frame pairs per forward: 1 = the reference "
                        "per-image loop; >1 streams through the "
                        "throughput-mode inference engine "
                        "(dexiraft_tpu.serve) with identical metrics "
                        f"(default: {batch_size})")
    p.add_argument("--inflight", type=int, default=2,
                   help="dispatched-unfetched batches the engine holds "
                        "before blocking on a host fetch (default: 2)")
    p.add_argument("--bucket_multiple", type=int, default=bucket_multiple,
                   help="quantize pad shapes up to multiples of this "
                        "(bounds compiled executables across mixed "
                        "geometries); default: "
                        + (f"{bucket_multiple}" if bucket_multiple
                           else "stride 8, the exact reference pad "
                                "shapes"))


class Result(NamedTuple):
    """One frame pair's inference output.

    flow_up is unpadded back to the item's own (H, W, 2); flow_low stays
    at the bucket's padded 1/8 resolution — it is the warm-start carry,
    and the next frame of the same sequence pads to the same bucket.

    iters_used / final_delta are the adaptive path's per-item
    convergence evidence (refinement updates actually applied; last
    pre-freeze 1/8-res flow-delta norm); None on fixed-iteration
    engines.
    """

    index: int
    item: Dict[str, Any]
    flow_low: np.ndarray
    flow_up: np.ndarray
    iters_used: Optional[int] = None
    final_delta: Optional[float] = None


class _Ticket(NamedTuple):
    flow_low: Any             # device array future (B, bh/8, bw/8, 2)
    flow_up: Any              # device array future (B, bh, bw, 2)
    entries: List[Tuple[int, Dict[str, Any], InputPadder]]
    t_dispatch: float
    iters_used: Any = None    # adaptive: device (B,) int32 future
    final_delta: Any = None   # adaptive: device (B,) float32 future


class InferenceEngine:
    """Bucketed, batched, pipelined driver for a jitted eval forward."""

    def __init__(
        self,
        eval_fn: EvalFn,
        config: ServeConfig = ServeConfig(),
        *,
        mesh=None,
        put: Optional[Callable[[Any], Any]] = None,
    ):
        self.eval_fn = eval_fn
        self.config = config
        self.mesh = mesh
        if mesh is not None:
            n_data = int(np.prod(list(mesh.shape.values())))
            if config.batch_size % n_data:
                raise ValueError(
                    f"batch_size {config.batch_size} not divisible by the "
                    f"mesh's {n_data} devices — every chip needs a full "
                    f"shard of each dispatched batch")
        if put is None:
            from dexiraft_tpu.parallel.layout import batch_putter

            put = batch_putter(mesh)
        self.put = put
        self.registry = BucketRegistry(config.stride, config.bucket_multiple)
        self.stats = ServeStats()
        self.compile_s = 0.0  # time inside first-dispatch eval_fn calls
        self._inflight: "collections.deque[_Ticket]" = collections.deque()
        # recompile drift sentinel (analysis.guards): a fresh bucket is
        # an EXPECTED compile; a compile on an already-compiled signature
        # is shape/dtype drift eating throughput — surfaced as a
        # one-line warning even when the caller never asked for --strict
        from dexiraft_tpu.analysis.guards import RecompileWatch

        self.watch = RecompileWatch("serve")
        # device-carry machinery (config.device_carry): cached per-shape
        # device zero rows (cold seeds) and the jitted row stack that
        # assembles a batch's flow_init on device — one executable per
        # (batch_size, row shape) signature, compiled inside the
        # bucket's expected first-dispatch window
        self._zero_rows: Dict[Tuple[int, ...], Any] = {}
        self._stack_fn = None

    # ---- input validation ----------------------------------------------

    def validate_item(self, item: Dict[str, Any]) -> None:
        """Public single-item validation (see _validate_item): the HTTP
        server rejects malformed requests with a 400 at the door instead
        of poisoning the scheduler's whole batch with a 500."""
        self._validate_item(0, item)

    def _validate_item(self, index: int, item: Dict[str, Any]) -> None:
        """Reject malformed frames at the door with a clear ValueError.

        Without this, a wrong rank/dtype/channel count fails deep inside
        the jitted bucket step (a shape mismatch against a compiled
        executable, or a tracer-time TypeError) where the message names
        engine internals rather than the offending input.

        The normalized arrays are written back into `item`: validating
        an np.asarray VIEW while the engine later indexes the raw value
        would let an array-like (a nested list) pass the checks and
        still crash on `.shape` — the exact opacity this guard removes.
        """
        shapes = {}
        for key in ("image1", "image2"):
            im = item.get(key)
            if im is None:
                raise ValueError(f"item {index}: missing {key!r}")
            im = item[key] = np.asarray(im)
            if im.ndim != 3:
                raise ValueError(
                    f"item {index}: {key!r} must be rank-3 (H, W, C), got "
                    f"shape {im.shape}")
            if im.shape[-1] != 3:
                raise ValueError(
                    f"item {index}: {key!r} must have 3 channels (RGB HWC), "
                    f"got {im.shape[-1]} (shape {im.shape})")
            if not (np.issubdtype(im.dtype, np.floating)
                    or np.issubdtype(im.dtype, np.integer)):
                raise ValueError(
                    f"item {index}: {key!r} dtype must be a real numeric "
                    f"type castable to float32, got {im.dtype}")
            shapes[key] = im.shape
        if shapes["image1"] != shapes["image2"]:
            raise ValueError(
                f"item {index}: image1 {shapes['image1']} and image2 "
                f"{shapes['image2']} must agree (one flow field per pair)")
        fi = item.get("flow_init")
        if fi is not None:
            if not (hasattr(fi, "ndim") and hasattr(fi, "shape")):
                fi = item["flow_init"] = np.asarray(fi)
            # a real array — numpy OR a jax device array (the session
            # store's device-resident carry) — passes through untouched:
            # np.asarray on a device array would be exactly the implicit
            # D2H transfer the device-carry path exists to remove.
            # Spatial dims are bucket-relative (the carry stays at the
            # PADDED 1/8 resolution), so only rank/channels are checkable
            if fi.ndim != 3 or fi.shape[-1] != 2:
                raise ValueError(
                    f"item {index}: flow_init must be rank-3 (H/{self.config.stride}, "
                    f"W/{self.config.stride}, 2), got shape {fi.shape}")

    # ---- dispatch side -------------------------------------------------

    def _dispatch(self, bucket: Tuple[int, int],
                  group: List[Tuple[int, Dict[str, Any]]],
                  mode: str,
                  iter_budget: Optional[int] = None) -> None:
        cfg = self.config
        if iter_budget is not None and not cfg.adaptive:
            raise ValueError(
                "iter_budget passed to a fixed-iteration engine — build "
                "it with ServeConfig(adaptive=True) and an adaptive "
                "eval_fn (make_eval_step(adaptive=True))")
        t0 = time.perf_counter()
        padders = [InputPadder(it["image1"].shape, mode=mode,
                               stride=cfg.stride, target=bucket)
                   for _, it in group]
        im1 = [p.pad(np.asarray(it["image1"], np.float32))[0]
               for p, (_, it) in zip(padders, group)]
        im2 = [p.pad(np.asarray(it["image2"], np.float32))[0]
               for p, (_, it) in zip(padders, group)]
        fill = cfg.batch_size - len(group)
        if fill:  # tail: replicate the last item up to the batch shape
            im1 += [im1[-1]] * fill
            im2 += [im2[-1]] * fill
            self.stats.pad_frames += fill
        im1 = np.stack(im1)
        im2 = np.stack(im2)

        inits = [it.get("flow_init") for _, it in group]
        will_fi = cfg.warm_start or any(x is not None for x in inits)
        fresh = self.registry.mark_compiled((bucket, will_fi))
        # every expected first-dispatch compile rides ONE sanctioned
        # window: the watch is SHARED with the streaming engine
        # (process-global compile counter), whose handler-thread check
        # must not read an in-progress expected compile as drift. That
        # covers the carry stack fn (_assemble_fi device path), the
        # bucket step itself, and the per-row carry slices below.
        win = (self.watch.sanctioned() if fresh
               else contextlib.nullcontext())
        iters_used = final_delta = None
        with win:
            fi = self._assemble_fi(bucket, inits) if will_fi else None
            im1, im2, fi = self.put((im1, im2, fi))
            t1 = time.perf_counter()
            if cfg.adaptive:
                # the ONE budget-normalization site (module docstring):
                # every dispatch — warmup, scheduler-budgeted, default —
                # presents the same int32 scalar aval, so the signature
                # stays one executable per bucket
                ib = None if iter_budget is None else np.int32(iter_budget)
                flow_low, flow_up, iters_used, final_delta = \
                    self.eval_fn(im1, im2, fi, ib)
            else:
                flow_low, flow_up = self.eval_fn(im1, im2, fi)
            if (fresh and cfg.device_carry
                    and not isinstance(flow_low, np.ndarray)):
                # pre-compile the per-row carry slices: _fetch_one's
                # low[row] is one executable per STATIC row index, and
                # warmup batches carry one real item — without this the
                # first multi-warm batch would compile rows 1.. after
                # mark_warm and trip a --strict check
                for row in range(cfg.batch_size):
                    flow_low[row]
        t2 = time.perf_counter()
        if fresh:
            # the first call on a fresh signature traces+compiles
            # synchronously before enqueueing — charge that span to
            # compile_s ONLY, so dispatch_s stays what ServeStats
            # documents (host pad/stack/put/enqueue time)
            self.compile_s += t2 - t1
            self.stats.dispatch_s += t1 - t0
            # expected compile: move the drift baseline past it
            self.watch.mark_warm()
        else:
            self.stats.dispatch_s += t2 - t0
            # compiled-signature dispatch that still compiled = drift:
            # strict engines fail the run, default engines warn once
            if cfg.strict:
                self.watch.check()
            else:
                self.watch.warn_if_drifted()
        self.stats.batches += 1
        self._inflight.append(_Ticket(
            flow_low, flow_up,
            [(idx, it, p) for (idx, it), p in zip(group, padders)],
            t_dispatch=t0, iters_used=iters_used,
            final_delta=final_delta))
        self.stats.peak_inflight = max(self.stats.peak_inflight,
                                       len(self._inflight))

    def _assemble_fi(self, bucket: Tuple[int, int], inits: List[Any]):
        """The dispatch group's (batch_size, h/8, w/8, 2) flow_init.

        Host path (device_carry off): a host zeros batch with warm rows
        copied in, transferred with the frames — the PR 6 semantics,
        with the warm rows' bytes counted as carry H2D traffic.

        Device path (device_carry on — ALWAYS, even for an all-cold
        group, so a warmup dispatch compiles the same executables real
        warm traffic rides): rows are stacked ON DEVICE by a jitted
        stack over cached zero rows — warm device rows are never
        fetched, cold rows reuse one resident zero row, and the only
        executable is one stack per (batch_size, row shape), compiled
        inside the bucket's expected first-dispatch window.
        """
        cfg = self.config
        bh, bw = bucket
        shape = (bh // cfg.stride, bw // cfg.stride, 2)
        if not cfg.device_carry:
            if any(init is not None and not isinstance(init, np.ndarray)
                   for init in inits):
                raise ValueError(
                    "a device-array flow_init reached an engine without "
                    "ServeConfig(device_carry=True) — np.asarray on it "
                    "would silently round-trip the carry through the "
                    "host; enable device_carry or hand host numpy")
            fi = np.zeros((cfg.batch_size,) + shape, np.float32)
            for row, init in enumerate(inits):
                if init is not None:
                    fi[row] = np.asarray(init, np.float32)
                    self.stats.carry_h2d_bytes += fi[row].nbytes
            return fi
        import jax  # deferred: module stays importable without jax

        zero = self._zero_rows.get(shape)
        if zero is None:
            # explicit H2D (jaxlint JL007 / strict transfer guard): one
            # resident zero row per shape seeds every cold slot
            zero = self._zero_rows[shape] = jax.device_put(
                np.zeros(shape, np.float32))
        rows = []
        for init in inits:
            if init is None:
                rows.append(zero)
            elif isinstance(init, np.ndarray):
                # mixed stream: a host-carry row (e.g. a client-supplied
                # seed) rides an explicit put; still counted as carry
                # H2D — it IS host carry traffic
                self.stats.carry_h2d_bytes += init.nbytes
                rows.append(jax.device_put(
                    np.ascontiguousarray(init, np.float32)))
            else:
                rows.append(init)
        rows += [zero] * (cfg.batch_size - len(rows))
        if self._stack_fn is None:
            self._stack_fn = jax.jit(lambda *rs: jax.numpy.stack(rs))
        return self._stack_fn(*rows)

    # ---- fetch side ----------------------------------------------------

    def _fetch_one(self) -> Iterator[Result]:
        ticket = self._inflight.popleft()
        t0 = time.perf_counter()
        if (isinstance(ticket.flow_low, np.ndarray)
                and isinstance(ticket.flow_up, np.ndarray)):
            # stub eval_fns (unit tests, the fleet tests' subprocess
            # replicas) already returned host arrays — nothing to fetch
            low, up = ticket.flow_low, ticket.flow_up
        else:
            import jax  # deferred: module stays importable without jax

            # explicit device->host fetch (jaxlint JL007): this sync IS
            # the fetch side's job, and device_get passes a strict
            # transfer guard
            if self.config.device_carry:
                # the carry consumer (session splat) lives on device —
                # keep flow_low there; Result.flow_low rows become
                # device slices and the carry never crosses the bus
                low = ticket.flow_low
            else:
                low = jax.device_get(ticket.flow_low)
                if self.config.warm_start:
                    # carry traffic only when the engine is configured
                    # for session carry (serve sets warm_start with
                    # sessions); a stateless replica's flow_low fetch is
                    # plain Result plumbing, not carry bytes
                    self.stats.carry_d2h_bytes += low.nbytes
            up = jax.device_get(ticket.flow_up)
        iu = fd = None
        if ticket.iters_used is not None:
            if isinstance(ticket.iters_used, np.ndarray):
                # stub eval_fns hand host arrays straight through
                iu, fd = ticket.iters_used, ticket.final_delta
            else:
                import jax  # deferred like the flow fetches above

                # explicit D2H (jaxlint JL007): (B,) vectors, a few bytes
                iu = jax.device_get(ticket.iters_used)
                fd = jax.device_get(ticket.final_delta)
        now = time.perf_counter()
        self.stats.fetch_s += now - t0
        self.stats.fetches += 1
        self.stats.batch_latency_s.append(now - ticket.t_dispatch)
        for row, (idx, item, padder) in enumerate(ticket.entries):
            self.stats.frames += 1
            if iu is None:
                yield Result(idx, item, low[row], padder.unpad(up[row]))
            else:
                self.stats.iters_used.append(int(iu[row]))
                self.stats.final_delta.append(float(fd[row]))
                yield Result(idx, item, low[row], padder.unpad(up[row]),
                             iters_used=int(iu[row]),
                             final_delta=float(fd[row]))

    def _drain_to(self, n: int) -> Iterator[Result]:
        while len(self._inflight) > n:
            yield from self._fetch_one()

    # ---- public API ----------------------------------------------------

    def stream(self, items: Iterable[Dict[str, Any]],
               mode: Optional[str] = None,
               iter_budget: Optional[int] = None) -> Iterator[Result]:
        """Run every item through the engine; yield Results as their
        batches complete (bucket-grouped, NOT input order — each Result
        carries its original index).

        items: dicts with image1/image2 (H, W, C) and anything else the
        caller wants back on the Result (gt flow, extra_info, ...);
        an optional per-item flow_init rides the same dict.

        iter_budget (adaptive engines only) caps every dispatched
        batch's refinement iterations; None rides the full iters.
        """
        mode = mode or self.config.mode
        cfg = self.config
        pending: Dict[Tuple[int, int], List[Tuple[int, Dict[str, Any]]]] = {}
        for index, item in enumerate(items):
            self._validate_item(index, item)
            h, w = item["image1"].shape[-3], item["image1"].shape[-2]
            bucket = self.registry.bucket_for(h, w)
            pending.setdefault(bucket, []).append((index, item))
            if len(pending[bucket]) == cfg.batch_size:
                # fetch down to a free slot BEFORE dispatching, so at
                # most `inflight` tickets are ever outstanding
                yield from self._drain_to(cfg.inflight - 1)
                self._dispatch(bucket, pending.pop(bucket), mode,
                               iter_budget=iter_budget)
        for bucket in sorted(pending):  # partial tails, deterministic order
            yield from self._drain_to(cfg.inflight - 1)
            self._dispatch(bucket, pending.pop(bucket), mode,
                           iter_budget=iter_budget)
        yield from self._drain_to(0)

    def run_batch(self, items: List[Dict[str, Any]],
                  mode: Optional[str] = None,
                  iter_budget: Optional[int] = None) -> List[Result]:
        """Dispatch ONE batch synchronously and return Results in input
        order — the building block for sequenced workloads (Sintel
        warm-start carries the previous frame's flow_low, so frame j+1
        cannot dispatch before frame j fetches). All items must share a
        bucket; len(items) <= batch_size (the tail pad fills the rest).

        iter_budget (adaptive engines only): this dispatch's iteration
        budget — the scheduler's SLO/overload policy hands it in here;
        None rides the step's full configured iters.
        """
        if not items:
            return []
        if len(items) > self.config.batch_size:
            raise ValueError(f"{len(items)} items > batch_size "
                             f"{self.config.batch_size}")
        mode = mode or self.config.mode
        for index, item in enumerate(items):
            self._validate_item(index, item)
        buckets = {self.registry.bucket_for(
            it["image1"].shape[-3], it["image1"].shape[-2]) for it in items}
        if len(buckets) > 1:
            raise ValueError(f"run_batch items span buckets {buckets}")
        if self._inflight:
            # fetching here would silently discard an unfinished
            # stream()'s Results — make the misuse loud instead
            raise RuntimeError(
                f"run_batch with {len(self._inflight)} ticket(s) still in "
                "flight from a previous stream(); consume that iterator "
                "first (or use a separate engine)")
        self._dispatch(buckets.pop(), list(enumerate(items)), mode,
                       iter_budget=iter_budget)
        out = sorted(self._fetch_one(), key=lambda r: r.index)
        return out

    def reset_stats(self) -> None:
        """Zero the accounting for a fresh measurement window while
        keeping the compiled-executable state.

        A long-lived server scrapes /stats on a cadence; without this the
        ServeStats counters (and the latency sample list) accumulate for
        the life of the process and every scrape re-reports history. The
        compiled-signature set and the watch baseline survive on purpose:
        resetting them would misreport the next dispatch on a warm bucket
        as a fresh compile (and re-arm the drift warning the bucket
        already absorbed). serve_bench's warmup->timed handoff is the
        same operation.
        """
        self.stats.reset()
        self.registry.hits.clear()
        self.compile_s = 0.0

    def stats_record(self) -> dict:
        """Self-describing stats blob for bench records / logs.

        The adaptive keys appear ONLY on adaptive engines: fixed-path
        records (and the serve_bench schemas pinned over them) are
        byte-identical to before the adaptive path existed.
        """
        rec = {
            "batch_size": self.config.batch_size,
            "inflight": self.config.inflight,
            "frames": self.stats.frames,
            "batches": self.stats.batches,
            "pad_frames": self.stats.pad_frames,
            "peak_inflight": self.stats.peak_inflight,
            "fetch_blocked_ms": round(self.stats.fetch_s * 1e3, 2),
            "dispatch_ms": round(self.stats.dispatch_s * 1e3, 2),
            "compile_s": round(self.compile_s, 2),
            "device_carry": self.config.device_carry,
            "carry_h2d_bytes": self.stats.carry_h2d_bytes,
            "carry_d2h_bytes": self.stats.carry_d2h_bytes,
            "latency_p50_ms": round(self.stats.latency_ms(50), 2),
            "latency_p99_ms": round(self.stats.latency_ms(99), 2),
            **self.registry.stats(),
        }
        if self.config.adaptive:
            rec.update(
                adaptive=True,
                iters_used_mean=round(self.stats.iters_used_mean(), 2),
                iters_used_p50=round(self.stats.iters_used_pctl(50), 2),
                iters_used_p99=round(self.stats.iters_used_pctl(99), 2),
                final_delta_p50=round(self.stats.final_delta_pctl(50), 5),
                final_delta_p99=round(self.stats.final_delta_pctl(99), 5),
            )
        return rec
