"""Shape-bucket registry: arbitrary geometries -> a bounded executable set.

Every distinct padded input shape the jitted eval step sees costs one
XLA compile (in-process jit cache + the PR 2 persistent disk cache).
Per-image eval pads each frame to its own next-stride-multiple shape, so
a mixed-geometry stream (KITTI's per-frame sizes, multi-dataset serving)
compiles an executable per distinct geometry. The registry quantizes
geometries UP to multiples of `multiple` (itself a multiple of the
model's stride-8 contract): frames land in a small set of bucket shapes,
each bucket compiles exactly once, and the replicate-edge pad out to the
bucket is undone per item on the way back (data.padder.InputPadder with
`target=`).

multiple == stride (the default) reproduces the reference pad shapes
exactly — the parity configuration eval_cli uses; serving deployments
raise it (e.g. 64) to bound the executable count across datasets.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple


def bucket_shape(ht: int, wd: int, stride: int = 8,
                 multiple: Optional[int] = None) -> Tuple[int, int]:
    """Smallest (H, W) >= input with both dims multiples of `multiple`."""
    m = multiple or stride
    if m % stride:
        raise ValueError(f"bucket multiple {m} must be a multiple of the "
                         f"model stride {stride}")
    return (-(-ht // m) * m, -(-wd // m) * m)


class BucketRegistry:
    """Maps input geometries to bucket shapes and counts hits/compiles."""

    def __init__(self, stride: int = 8, multiple: Optional[int] = None):
        self.stride = stride
        self.multiple = multiple or stride
        self.hits: Dict[Tuple[int, int], int] = {}
        self._compiled: set = set()

    def bucket_for(self, ht: int, wd: int) -> Tuple[int, int]:
        b = bucket_shape(ht, wd, self.stride, self.multiple)
        self.hits[b] = self.hits.get(b, 0) + 1
        return b

    def mark_compiled(self, key) -> bool:
        """Record a dispatch-signature key (bucket shape + flow_init
        presence); True the first time = a fresh executable."""
        if key in self._compiled:
            return False
        self._compiled.add(key)
        return True

    @property
    def compiles(self) -> int:
        return len(self._compiled)

    @staticmethod
    def _signature_name(key) -> str:
        """Human name for a compiled-signature key. The engine's keys are
        ((H, W), warm_bool); anything else renders via str()."""
        try:
            (h, w), warm = key
            return f"{h}x{w}" + ("+warm" if warm else "")
        except (TypeError, ValueError):
            return str(key)

    def stats(self) -> dict:
        """Self-describing registry blob. `buckets` carries the SHAPES
        with their hit counts (which geometries are hot), `compiled` the
        executable signatures actually built (which are compiling) — the
        /stats endpoint and serve_bench report both, so a deployment can
        see a cold bucket (compiled, zero recent hits) vs a hot one vs a
        geometry still paying compiles."""
        return {
            "stride": self.stride,
            "multiple": self.multiple,
            "buckets": {f"{h}x{w}": n
                        for (h, w), n in sorted(self.hits.items())},
            "bucket_count": len(self.hits),
            "compiles": self.compiles,
            "compiled": sorted(self._signature_name(k)
                               for k in self._compiled),
        }
