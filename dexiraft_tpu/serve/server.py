"""Persistent flow service: the HTTP tier over engine + scheduler +
sessions.

Pure stdlib (`http.server.ThreadingHTTPServer`) — the repo adds no
dependency to become a service. One process hosts:

  handler threads  -> Scheduler (SLO-aware same-bucket batching)
                      -> ONE dispatcher thread -> InferenceEngine
  SessionStore     -> per-stream flow_init warm-start across requests

Endpoints:

  POST /v1/flow     body = .npz with float arrays ``image1``/``image2``
                    (H, W, 3); optional ``X-Session-Id`` header opts the
                    request into warm-start carry. Response: .npz with
                    ``flow_up`` (H, W, 2) float32; ``X-Warm-Start`` and
                    ``X-Bucket`` headers describe what served it.
                    400 malformed, 503 queue-full/draining, 504 SLO-
                    timeout, 500 engine error.
  POST /v1/flow/stream
                    body = .npz with ``frames`` (T, H, W, 3) — one CHUNK
                    of a video stream through the split-encoder
                    streaming engine (serve/video.py): each frame is
                    encoded ONCE, the previous frame's features + flow
                    seed ride the device-resident session carry keyed by
                    ``X-Session-Id``. Response: .npz ``flows``
                    (N, H, W, 2) with N = T warm / T-1 cold
                    (X-Frames-In / X-Flows-Out headers spell it out).
                    404 when streaming is disabled on the replica.
  GET  /healthz     JSON READINESS; 200 while serving, 503 once
                    draining (load balancers stop routing before the
                    exit). The payload always carries {draining,
                    inflight, sessions}: a router can tell "dying"
                    (drain in progress, inflight counting down) from
                    "busy" and can poll inflight to 0 for a zero-drop
                    drain.
  GET  /livez       JSON LIVENESS; 200 as long as the process answers
                    — stays 200 through a drain. Restart on /livez,
                    route on /healthz.
  GET  /stats       JSON {service, engine, scheduler, sessions} —
                    ServeStats/SchedulerStats/SessionStore records.
                    ``?reset=1`` zeroes the counters after the scrape
                    (engine.reset_stats + SchedulerStats.reset): each
                    scrape window reports ITS traffic, not history.

Graceful shutdown (the PR 4 preemption discipline, service-shaped):
the first SIGTERM/SIGINT stops admissions (503), lets the scheduler
drain every queued request, joins the handler threads so every response
is flushed, then exits; a second signal aborts immediately. In-flight
work is never dropped — the closed-loop bench and the service test pin
this.

The npz wire format is deliberate: frames are arrays, JSON-of-lists is
~10x the bytes and the decode dominates small-image latency; npz is the
one container numpy reads/writes with zero new deps
(``allow_pickle=False`` — no code execution surface).
"""

from __future__ import annotations

import io
import json
import signal
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

import numpy as np

from dexiraft_tpu.analysis import locks
from dexiraft_tpu.analysis.locks import OrderedLock
from dexiraft_tpu.serve.buckets import bucket_shape
from dexiraft_tpu.serve.engine import InferenceEngine
from dexiraft_tpu.serve.httputil import QuietDisconnectsMixin
from dexiraft_tpu.serve.scheduler import (QueueFull, Scheduler,
                                          SchedulerClosed)
from dexiraft_tpu.serve.sessions import SessionStore

# ---- wire format (shared by server, bench client, tests) ----------------


def encode_request(image1, image2) -> bytes:
    """Client side: one frame pair -> the POST /v1/flow body."""
    buf = io.BytesIO()
    np.savez(buf, image1=np.asarray(image1), image2=np.asarray(image2))
    return buf.getvalue()


def decode_request(body: bytes) -> Dict[str, Any]:
    """Server side: POST body -> engine item dict. ValueError on any
    malformed payload (the handler's 400 path)."""
    try:
        z = np.load(io.BytesIO(body), allow_pickle=False)
        arrays = {k: z[k] for k in z.files}
    except Exception as e:
        raise ValueError(f"body is not a readable .npz archive: {e}")
    for key in ("image1", "image2"):
        if key not in arrays:
            raise ValueError(f"npz body missing required array {key!r} "
                             f"(got {sorted(arrays)})")
    return {"image1": arrays["image1"], "image2": arrays["image2"]}


def encode_response(flow_up: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, flow_up=np.asarray(flow_up, np.float32))
    return buf.getvalue()


def decode_response(body: bytes) -> np.ndarray:
    """Client side: response body -> (H, W, 2) float32 flow."""
    z = np.load(io.BytesIO(body), allow_pickle=False)
    return z["flow_up"]


# ---- streaming wire format (POST /v1/flow/stream) -----------------------


def encode_stream_request(frames) -> bytes:
    """Client side: one CHUNK of a video stream -> the POST
    /v1/flow/stream body. ``frames`` is (T, H, W, 3) [0, 255] — T
    same-geometry frames; the carry across chunks rides the
    ``X-Session-Id`` header, so a client streams arbitrary-length video
    as a sequence of bounded chunks."""
    buf = io.BytesIO()
    np.savez(buf, frames=np.asarray(frames, np.float32))
    return buf.getvalue()


def decode_stream_request(body: bytes) -> np.ndarray:
    """Server side: POST body -> (T, H, W, 3) frames array. ValueError
    on any malformed payload (the handler's 400 path); shape/dtype
    validation is VideoEngine.validate_frames' job."""
    try:
        z = np.load(io.BytesIO(body), allow_pickle=False)
        arrays = {k: z[k] for k in z.files}
    except Exception as e:
        raise ValueError(f"body is not a readable .npz archive: {e}")
    if "frames" not in arrays:
        raise ValueError(f"npz body missing required array 'frames' "
                         f"(got {sorted(arrays)})")
    return arrays["frames"]


def encode_stream_response(flows) -> bytes:
    """(N, H, W, 2) stacked flows (N may be T or T-1 — a cold chunk has
    no carry pair for its first frame; N=0 for a cold single-frame
    chunk that only primed the carry)."""
    buf = io.BytesIO()
    if len(flows):
        arr = np.stack([np.asarray(f, np.float32) for f in flows])
    else:
        arr = np.zeros((0,), np.float32)
    np.savez(buf, flows=arr)
    return buf.getvalue()


def decode_stream_response(body: bytes) -> np.ndarray:
    """Client side: response body -> (N, H, W, 2) float32 flows."""
    z = np.load(io.BytesIO(body), allow_pickle=False)
    return z["flows"]


# ---- HTTP plumbing ------------------------------------------------------


class _FlowHTTPServer(QuietDisconnectsMixin, ThreadingHTTPServer):
    """ThreadingHTTPServer that (a) carries the FlowService reference,
    (b) JOINS handler threads on close — the drain path's guarantee that
    every admitted response is flushed before exit — and (c) optionally
    binds with SO_REUSEPORT so ``--workers N`` processes share one port
    (the kernel load-balances accepts across workers)."""

    daemon_threads = False      # joined at server_close(), not abandoned
    block_on_close = True

    def __init__(self, addr, handler, service: "FlowService",
                 reuse_port: bool = False):
        self.service = service
        self._reuse_port = reuse_port
        super().__init__(addr, handler)

    def server_bind(self):
        if self._reuse_port:
            if not hasattr(socket, "SO_REUSEPORT"):
                raise OSError("SO_REUSEPORT unavailable on this platform "
                              "— multi-worker mode needs it")
            self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        super().server_bind()


class _Handler(BaseHTTPRequestHandler):
    server_version = "dexiraft-serve/1.0"
    # keep-alive: closed-loop clients reuse one connection per thread
    protocol_version = "HTTP/1.1"
    # an IDLE keep-alive connection must not pin its handler thread
    # forever: drain joins handler threads (block_on_close), so a
    # client that holds a connection open without sending would
    # otherwise stall shutdown until it went away
    timeout = 30.0

    # ---- helpers -------------------------------------------------------

    def log_message(self, fmt, *args):  # quiet: /stats carries the signal
        pass

    def _send(self, status: int, body: bytes, content_type: str,
              headers: Optional[Dict[str, str]] = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, status: int, payload: dict,
                   headers: Optional[Dict[str, str]] = None) -> None:
        self._send(status, json.dumps(payload).encode(),
                   "application/json", headers)

    def _send_error_json(self, status: int, message: str,
                         retry: bool = False) -> None:
        self._send_json(status, {"error": message},
                        {"Retry-After": "1"} if retry else None)

    # ---- GET: health + stats -------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        svc = self.server.service
        url = urlparse(self.path)
        if url.path == "/livez":
            # liveness: 200 as long as the process answers — a DRAINING
            # replica is alive (finishing admitted work), only a dead
            # one fails this. Routers restart on /livez, route on
            # /healthz.
            self._send_json(200, {"status": "alive"})
        elif url.path == "/healthz":
            # readiness: 503 once draining (load balancers stop routing
            # before the exit), but the payload always reports the full
            # {draining, inflight, sessions} picture so a router can
            # tell "dying" (drain + inflight counting down) from "busy"
            # (ready with a deep queue) instead of a bare status flip.
            payload = svc.health_record()
            self._send_json(503 if payload["draining"] else 200, payload)
        elif url.path == "/stats":
            reset = parse_qs(url.query).get("reset", ["0"])[0] == "1"
            payload = (svc.snapshot_and_reset() if reset
                       else svc.stats_record())
            self._send_json(200, payload)
        else:
            self._send_error_json(404, f"no such endpoint {url.path!r}")

    # ---- POST: inference -----------------------------------------------

    def _read_body(self) -> Optional[bytes]:
        """Read the request body on EVERY path (including the ones that
        answer 4xx): an unread body on a keep-alive connection would be
        parsed as the next request line, desyncing every later request
        on that connection. None (and close_connection) on a body we
        cannot frame (chunked, bad Content-Length)."""
        te = self.headers.get("Transfer-Encoding", "")
        if te and te.lower() != "identity":
            self.close_connection = True
            return None
        try:
            length = int(self.headers.get("Content-Length", "0"))
            if length < 0:
                raise ValueError(length)
        except ValueError:
            self.close_connection = True
            return None
        return self.rfile.read(length) if length > 0 else b""

    def do_POST(self) -> None:  # noqa: N802 (http.server API)
        svc = self.server.service
        body = self._read_body()
        if body is None:
            self._send_error_json(
                400, "unsupported Transfer-Encoding or bad Content-Length")
            return
        path = urlparse(self.path).path
        if path == "/v1/flow/stream":
            self._post_stream(svc, body)
            return
        if path != "/v1/flow":
            self._send_error_json(404, f"no such endpoint {self.path!r}")
            return
        try:
            item = decode_request(body)
            # reject malformed input at the door (400) instead of
            # poisoning a whole scheduler batch deep in the engine (500)
            svc.engine.validate_item(item)
        except ValueError as e:
            self._send_error_json(400, str(e))
            return

        cfg = svc.engine.config
        h, w = item["image1"].shape[:2]
        bucket = bucket_shape(h, w, cfg.stride, cfg.bucket_multiple)
        session_id = self.headers.get("X-Session-Id")
        warm = False
        if session_id and svc.sessions is not None:
            init = svc.sessions.get(session_id, bucket)
            if init is not None:
                item["flow_init"] = init
                warm = True

        try:
            result = svc.scheduler.submit(item, timeout=svc.request_timeout_s)
        except QueueFull as e:
            self._send_error_json(503, f"overloaded: {e}", retry=True)
            return
        except SchedulerClosed:
            self._send_error_json(503, "draining: service is shutting down")
            return
        except TimeoutError as e:
            self._send_error_json(504, str(e))
            return
        except Exception as e:  # engine error, re-raised by submit()
            self._send_error_json(
                500, f"inference failed: {type(e).__name__}: {e}")
            return

        if session_id and svc.sessions is not None:
            # frame j's carry seeds frame j+1 of the same stream;
            # carry_fn is the splat hook (serve_cli wires the on-device
            # forward_interpolate; identity — raw flow_low — otherwise).
            # Its per-bucket jit compile already happened in the
            # dispatcher thread (FlowService._post_dispatch), so this
            # call rides a cached executable — handler threads never
            # compile, which is what keeps --strict serving race-free.
            svc.sessions.put(session_id, bucket,
                             svc.carry_fn(result.flow_low))
        headers = {"X-Warm-Start": "1" if warm else "0",
                   "X-Bucket": f"{bucket[0]}x{bucket[1]}"}
        if result.iters_used is not None:
            # adaptive engines only: how many refinement iterations THIS
            # item actually ran before its convergence gate (or the
            # scheduler's SLO budget) stopped it, and the last pre-stop
            # flow-delta norm — per-request convergence evidence on the
            # wire, no extra body bytes
            headers["X-Iters-Used"] = str(result.iters_used)
            headers["X-Final-Delta"] = f"{result.final_delta:.6f}"
        self._send(200, encode_response(result.flow_up),
                   "application/x-npz", headers)

    def _post_stream(self, svc: "FlowService", body: bytes) -> None:
        """POST /v1/flow/stream: one chunk of a video stream through the
        split-encoder VideoEngine. The response's ``flows`` array may be
        one SHORTER than the chunk (cold start has no carry pair for the
        first frame) — X-Frames-In / X-Flows-Out spell it out."""
        if svc.video is None:
            self._send_error_json(
                404, "streaming is not enabled on this replica (start "
                     "serve with sessions on and --stream_sessions_mb "
                     "> 0; docs/serving.md \"Streaming\")")
            return
        if svc.draining:
            self._send_error_json(503, "draining: service is shutting "
                                       "down")
            return
        try:
            frames = decode_stream_request(body)
            frames = svc.video.validate_frames(frames)
        except ValueError as e:
            self._send_error_json(400, str(e))
            return
        session_id = self.headers.get("X-Session-Id")
        try:
            res = svc.video.process_chunk(session_id, frames)
        except Exception as e:
            from dexiraft_tpu.serve.video import StreamOverloaded

            if isinstance(e, StreamOverloaded):
                # bounded admission, scheduler.QueueFull discipline:
                # shed with a retry signal instead of pinning handler
                # threads behind one in-flight chunk
                self._send_error_json(503, str(e), retry=True)
                return
            self._send_error_json(
                500, f"streaming inference failed: "
                     f"{type(e).__name__}: {e}")
            return
        headers = {"X-Warm-Start": "1" if res.warm else "0",
                   "X-Bucket": f"{res.bucket[0]}x{res.bucket[1]}",
                   "X-Frames-In": str(res.frames_in),
                   "X-Flows-Out": str(len(res.flows))}
        if getattr(res, "iters_used", None) is not None:
            # adaptive streaming: mean refinement iterations across this
            # chunk's frame pairs (per-pair detail is in /stats)
            headers["X-Iters-Used"] = f"{res.iters_used:.1f}"
        self._send(200, encode_stream_response(res.flows),
                   "application/x-npz", headers)


# ---- the service object -------------------------------------------------


class FlowService:
    """Engine + scheduler + sessions behind one persistent HTTP endpoint.

    Lifecycle: ``start()`` launches the dispatcher and the HTTP thread;
    ``drain_and_stop()`` (or the installed SIGTERM handler) refuses new
    work, finishes everything admitted, flushes responses, and sets
    ``stopped``. ``port=0`` binds an ephemeral port (tests/bench);
    ``reuse_port=True`` lets N worker processes share one port.
    """

    def __init__(
        self,
        engine: InferenceEngine,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        slo_ms: float = 200.0,
        max_queue: int = 64,
        adaptive: Optional[bool] = None,
        max_iters: int = 32,
        min_iters: int = 4,
        session_ttl_s: float = 60.0,
        max_sessions: int = 1024,
        carry_fn: Optional[Callable[[np.ndarray], np.ndarray]] = None,
        request_timeout_s: float = 60.0,
        reuse_port: bool = False,
        video=None,
        clock=None,
    ):
        if clock is None:
            import time

            clock = time.monotonic
        self.engine = engine
        # optional streaming tier (serve.video.VideoEngine): owns its
        # own device-carry session store and serialization; None keeps
        # /v1/flow/stream answering 404 with a how-to-enable message
        self.video = video
        self.clock = clock
        # adaptive defaults to the engine's mode: an adaptive engine
        # behind the service gets SLO-driven iteration budgets unless
        # the caller explicitly opts the scheduler out (adaptive=False
        # keeps budgets at the full iters; convergence exits still fire)
        if adaptive is None:
            adaptive = engine.config.adaptive
        self.scheduler = Scheduler(engine, slo_ms=slo_ms,
                                   max_queue=max_queue, adaptive=adaptive,
                                   max_iters=max_iters, min_iters=min_iters,
                                   clock=clock)
        # session_ttl_s <= 0 = stateless mode (multi-worker default:
        # kernel accept-balancing breaks per-worker affinity anyway)
        self.sessions = (SessionStore(session_ttl_s, max_sessions,
                                      clock=clock)
                         if session_ttl_s > 0 else None)
        self.carry_fn = carry_fn if carry_fn is not None else np.asarray
        self._carry_warm: set = set()   # dispatcher-thread only
        self.scheduler.post_dispatch = self._post_dispatch
        self.request_timeout_s = request_timeout_s
        self._httpd = _FlowHTTPServer((host, port), _Handler, service=self,
                                      reuse_port=reuse_port)
        self._http_thread: Optional[threading.Thread] = None
        self._t0 = clock()
        self._signal_latched = False
        # ranked ABOVE the scheduler cv in LOCK_ORDER: drain_and_stop
        # holds it across scheduler.drain()/close(), which take the cv
        self._stop_lock = OrderedLock("serve.server.stop")
        self.stopped = threading.Event()

    # ---- introspection -------------------------------------------------

    @property
    def address(self) -> Tuple[str, int]:
        host, port = self._httpd.server_address[:2]
        return host, port

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    @property
    def draining(self) -> bool:
        return self.scheduler.draining

    def uptime_s(self) -> float:
        return self.clock() - self._t0

    def health_record(self) -> dict:
        """The /healthz readiness payload: liveness is implied by
        answering at all; readiness is `not draining`; `inflight`
        (admitted-but-unanswered, queued AND mid-batch) is what a
        router's zero-drop drain polls down to 0; `sessions` says how
        much warm state dies with this replica."""
        return {
            "status": "draining" if self.draining else "ok",
            "draining": self.draining,
            # streaming chunks bypass the scheduler, so they count here
            # explicitly — a drain that polled scheduler inflight alone
            # would restart a replica over a live stream
            "inflight": self.scheduler.inflight()
            + (self.video.inflight() if self.video is not None else 0),
            "sessions": len(self.sessions) if self.sessions is not None
            else 0,
            "uptime_s": round(self.uptime_s(), 3),
            "queue_depth": self.scheduler.queue_depth(),
        }

    def stats_record(self) -> dict:
        return {
            "service": {
                "uptime_s": round(self.uptime_s(), 3),
                "draining": self.draining,
                "slo_ms": round(self.scheduler.slo_s * 1e3, 2),
                "sessions_enabled": self.sessions is not None,
                # the engine/scheduler blocks carry the adaptive detail
                # (iters_used percentiles, budget policy state); this
                # flag is the one-glance "is this replica adaptive"
                "adaptive": self.engine.config.adaptive,
            },
            "engine": self.engine.stats_record(),
            "scheduler": self.scheduler.stats_record(),
            "sessions": (self.sessions.stats_record()
                         if self.sessions is not None else None),
            "video": (self.video.stats_record()
                      if self.video is not None else None),
            # the lock-order runtime's verdict block (analysis/locks):
            # order violations / deadlock cycles must read 0 on a
            # healthy replica; contention + max-held-ms surface the
            # lock hot spots a latency investigation needs
            "locks": locks.stats_record(),
        }

    def _post_dispatch(self, bucket, results) -> None:
        """Dispatcher-thread hook (scheduler.post_dispatch): compile the
        carry splat for a freshly served bucket while NO other dispatch
        can be concurrent, and re-baseline the engine's drift watch past
        that expected compile. Doing this from handler threads instead
        would race the dispatcher's --strict check: the splat's backend
        compile lands in the global counter before any handler-side
        mark_warm could, and an unrelated batch would raise."""
        if (self.sessions is None or not results
                or bucket in self._carry_warm):
            return
        self._carry_warm.add(bucket)
        self.carry_fn(results[0].flow_low)
        self.engine.watch.mark_warm()

    def _zero_stats(self) -> None:
        # quiesced-context only (dispatcher provably outside the engine):
        # zeroing engine.compile_s mid-batch would race the dispatch's
        # accumulation and fold a compile span into the bucket's EWMA
        # service estimate
        self.engine.reset_stats()
        self.scheduler.stats.reset()
        if self.sessions is not None:
            self.sessions.reset_counters()
        if self.video is not None:
            self.video.reset_stats()

    def reset_stats(self) -> None:
        """One measurement-window handoff across every layer: engine
        counters+latency window, scheduler counters, session flow
        counters. Compiled executables, learned service-time estimates,
        and live session carries all survive — they are state, not
        statistics."""
        self.scheduler.run_quiesced(self._zero_stats)

    def snapshot_and_reset(self) -> dict:
        """The /stats?reset=1 path: capture the window's record and zero
        the counters as ONE quiesced operation. Snapshotting first and
        resetting after the response went out would lose every request
        completing in the gap — zeroed without ever being reported in
        either window."""
        record: dict = {}

        def _snapshot_reset():
            record.update(self.stats_record())
            self._zero_stats()

        self.scheduler.run_quiesced(_snapshot_reset)
        return record

    # ---- lifecycle -----------------------------------------------------

    def start(self) -> "FlowService":
        self.scheduler.start()
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="flow-http", daemon=True)
        self._http_thread.start()
        return self

    def drain_and_stop(self, timeout: Optional[float] = 30.0) -> bool:
        """Refuse new work, finish everything admitted, flush responses,
        stop. Returns True when the queue drained inside `timeout`.
        Idempotent — the signal path and an explicit caller can race;
        the loser of the race just waits for `stopped`."""
        if not self._stop_lock.acquire(blocking=False):
            self.stopped.wait(timeout)
            return not self.scheduler.queue_depth()
        try:
            drained = self.scheduler.drain(timeout)
            # handler threads blocked in submit() have their results;
            # closing the listener now joins them (block_on_close) so
            # every response hits the wire before we report stopped
            if self._http_thread is not None:
                self._httpd.shutdown()
            self._httpd.server_close()
            self.scheduler.close()
            self.stopped.set()
            return drained
        finally:
            self._stop_lock.release()

    # ---- signals (PR 4 preemption discipline) --------------------------

    def install_signal_handlers(self) -> bool:
        """First SIGTERM/SIGINT -> background graceful drain; second ->
        immediate KeyboardInterrupt (a wedged drain must not trap the
        operator). Returns False off the main thread (signals can only
        install there — library embedders keep their own handling)."""

        def _handle(signum, frame):
            if self._signal_latched:
                raise KeyboardInterrupt(
                    f"second signal {signum} during drain")
            self._signal_latched = True
            print(f"[serve] received signal {signum}; draining "
                  f"{self.scheduler.queue_depth()} queued request(s), "
                  f"refusing new work (signal again to abort)", flush=True)
            threading.Thread(target=self.drain_and_stop,
                             name="flow-drain", daemon=True).start()

        try:
            for s in (signal.SIGTERM, signal.SIGINT):
                signal.signal(s, _handle)
        except ValueError:
            return False
        return True
