"""Shared stdlib-HTTP plumbing for the serve tier's servers."""

from __future__ import annotations

import sys


class QuietDisconnectsMixin:
    """ThreadingHTTPServer mixin: a peer vanishing mid keep-alive (a
    killed replica's client, a chaos test's abrupt close, the router
    dropping an upstream) is business as usual for a serving fleet —
    not a traceback. Real handler bugs still print."""

    def handle_error(self, request, client_address):
        exc = sys.exc_info()[1]
        if isinstance(exc, (ConnectionResetError, BrokenPipeError,
                            ConnectionAbortedError, TimeoutError)):
            return
        super().handle_error(request, client_address)
