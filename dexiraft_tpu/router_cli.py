"""Fleet router CLI: one router process fronting N FlowService replicas.

Two modes:

  # front an EXISTING pool (replicas started any way you like)
  python -m dexiraft_tpu router --port 8000 \
      --replicas 127.0.0.1:8101,127.0.0.1:8102

  # SPAWN the pool too: N single-worker serve processes on
  # port_base..port_base+N-1, supervised (restart-on-death with
  # backoff), every flag after `--` forwarded to each replica
  python -m dexiraft_tpu router --port 8000 --spawn 4 --port_base 8101 \
      -- --model checkpoints/raft-sintel --variant v5 --warmup 440x1024

This is the sanctioned multi-replica path (PR 6's ``serve --workers``
SO_REUSEPORT pool has NO session affinity — the kernel balances
accepts blindly): each replica is a complete stateful service, and the
router keeps ``X-Session-Id`` streams pinned to the replica holding
their warm-start carry via a consistent-hash ring (serve/router.py).

Lifecycle discipline:
  * a replica that DIES is routed around within the breaker's failure
    threshold (in-flight requests fail over to a healthy replica) and,
    in spawn mode, restarted with jittered backoff — bounded by
    ``--max_restarts`` consecutive failures per replica so a
    crash-looping model cannot flap forever.
  * ``POST /admin/drain?replica=<rid>`` does a ZERO-DROP rolling
    restart: out of assignment, wait in-flight to 0 (the replica's
    /healthz readiness payload), SIGTERM (the replica's own drain
    discipline finishes the tail), respawn.
  * SIGTERM on the router: stop supervising (no respawns), drain every
    spawned replica, exit. A second signal aborts.

No jax import in this process, ever: the router must keep routing while
model processes compile, crash, and restart.
"""

from __future__ import annotations

import argparse
import http.client
import json
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from dexiraft_tpu.analysis.locks import OrderedLock
from dexiraft_tpu.serve.router import Router, RouterConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "dexiraft-router",
        description="health-checked, session-affine router over N "
                    "FlowService replicas (everything after `--` is "
                    "forwarded to spawned replicas)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000,
                   help="the router's own listen port (0 = ephemeral)")
    p.add_argument("--replicas", default=None,
                   help="comma-separated replica addresses "
                        "(host:port or http://host:port) to front")
    p.add_argument("--spawn", type=int, default=0,
                   help="spawn this many single-worker serve replicas "
                        "(flags after `--` are forwarded to each)")
    p.add_argument("--port_base", type=int, default=8101,
                   help="spawned replica i listens on port_base + i")
    p.add_argument("--fail_threshold", type=int, default=3,
                   help="consecutive probe/request failures that open a "
                        "replica's circuit breaker")
    p.add_argument("--cooldown_s", type=float, default=2.0,
                   help="open-breaker cooldown before the half-open "
                        "trial probe")
    p.add_argument("--probe_interval_s", type=float, default=0.5,
                   help="active /healthz probe cadence per replica")
    p.add_argument("--max_inflight", type=int, default=128,
                   help="router-level admission bound (503 + Retry-After "
                        "past it)")
    p.add_argument("--deadline_s", type=float, default=60.0,
                   help="per-request budget covering the proxy AND the "
                        "one failover retry")
    p.add_argument("--max_restarts", type=int, default=5,
                   help="consecutive supervised restarts per replica "
                        "before giving up on it")
    p.add_argument("--restart_backoff_s", type=float, default=1.0,
                   help="base (jittered, doubling) backoff between "
                        "supervised restarts")
    p.add_argument("--boot_timeout_s", type=float, default=600.0,
                   help="how long to wait for spawned replicas' first "
                        "healthy /healthz (model restore + compile)")
    return p


# ---- spawn-mode plumbing (shared with serve_bench / chaos_smoke) --------


def spawn_replica(port: int, serve_args: List[str], *, host="127.0.0.1",
                  env: Optional[dict] = None) -> subprocess.Popen:
    """One single-worker serve process on an explicit port. Detached
    into its own session so ^C on the router's terminal reaches it
    exactly once, through our forwarding (the serve_cli pool's
    rationale)."""
    argv = [sys.executable, "-m", "dexiraft_tpu", "serve",
            "--host", host, "--port", str(port), *serve_args]
    return subprocess.Popen(argv, env=env, start_new_session=True)


def wait_ready(host: str, port: int, timeout_s: float = 600.0,
               poll_s: float = 0.25) -> bool:
    """Poll /healthz until it answers 200 (restore + warmup compile can
    take minutes on a cold cache). False on timeout."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            conn = http.client.HTTPConnection(host, port, timeout=2.0)
            try:
                conn.request("GET", "/healthz")
                if conn.getresponse().status == 200:
                    return True
            finally:
                conn.close()
        except OSError:
            pass
        time.sleep(poll_s)
    return False


_RESTART_RESET_S = 120.0   # alive this long => the crash streak is over


class _Supervisor:
    """Owns the spawned replica processes: restart-on-death with
    jittered doubling backoff (bounded per crash STREAK — a replica
    that stays up resets its count), the drain hook's respawn, and the
    shutdown fan-out."""

    def __init__(self, args, serve_args: List[str]):
        self.args = args
        self.serve_args = serve_args
        self.procs: Dict[str, subprocess.Popen] = {}
        self.ports: Dict[str, int] = {}
        self.restarts: Dict[str, int] = {}
        self._last_restart: Dict[str, float] = {}
        self._gave_up: set = set()
        self._respawning: set = set()
        self._lock = OrderedLock("serve.router.supervisor")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def spawn_all(self) -> Dict[str, str]:
        urls = {}
        with self._lock:
            # startup runs before _watch exists, but the drain hook can
            # already be wired — keep every procs/ports mutation under
            # the one lock the other writers hold (threadlint JL021)
            for i in range(self.args.spawn):
                rid = f"r{i}"
                port = self.args.port_base + i
                self.ports[rid] = port
                self.restarts[rid] = 0
                self.procs[rid] = spawn_replica(port, self.serve_args,
                                                host=self.args.host)
                urls[rid] = f"{self.args.host}:{port}"
        return urls

    def respawn(self, rid: str) -> None:
        """The drain hook: SIGTERM (replica drains itself — zero-drop),
        reap, spawn fresh. Called with the replica already out of
        assignment and at 0 in-flight. Idempotent under concurrent
        drains of the same rid: the loser of the latch race returns and
        lets the in-flight respawn finish."""
        with self._lock:
            if rid in self._respawning:
                # a second drain of the same replica while the first is
                # still reaping: both would reap the same old child and
                # then BOTH spawn onto the same port (one live orphan +
                # procs[rid] pointing at the bind-race loser)
                return
            # _respawning is ALSO the watcher-suppression latch: _watch
            # skips respawning rids in both its dead-sweep and its
            # backoff-spawn guard, so the watcher cannot double-spawn
            # onto the port while we reap below with no lock held. The
            # latch is self-clearing in the finally — a failed spawn
            # returns the rid to the watcher's care (crash-restart with
            # backoff) instead of stranding it.
            self._respawning.add(rid)
            proc = self.procs.get(rid)
        try:
            if proc is not None and proc.poll() is None:
                # reap OUTSIDE the lock: a drain-wait can take up to
                # 60s, and holding the supervisor lock across it would
                # stall the crash-restart sweep for every OTHER replica
                # (JL023)
                proc.terminate()
                try:
                    proc.wait(timeout=60.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
            with self._lock:
                self.procs[rid] = spawn_replica(self.ports[rid],
                                                self.serve_args,
                                                host=self.args.host)
                self.restarts[rid] = 0  # deliberate restart, not a crash
                self._gave_up.discard(rid)   # a drain respawn revives
        finally:
            with self._lock:
                self._respawning.discard(rid)
        print(f"[router] replica {rid} drained and respawned on port "
              f"{self.ports[rid]}", flush=True)

    def _watch(self) -> None:
        import random

        rng = random.Random()
        while not self._stop.wait(1.0):
            now = time.monotonic()
            with self._lock:
                dead = [(rid, p, p.returncode)
                        for rid, p in self.procs.items()
                        if p.poll() is not None
                        and rid not in self._gave_up
                        and rid not in self._respawning]
                # a replica that stayed up past the reset window ended
                # its crash STREAK: its restart budget refills (the cap
                # bounds consecutive failures, not lifetime restarts)
                for rid, p in self.procs.items():
                    if (p.poll() is None and self.restarts[rid]
                            and now - self._last_restart.get(rid, now)
                            > _RESTART_RESET_S):
                        self.restarts[rid] = 0
            for rid, proc, rc in dead:
                n = self.restarts[rid]
                if n >= self.args.max_restarts:
                    # latch: one give-up line, not one per sweep; a
                    # drain-hook respawn un-latches it
                    with self._lock:
                        self._gave_up.add(rid)
                    print(f"[router] replica {rid} exited rc={rc}; "
                          f"{n} consecutive restarts already — giving up "
                          f"on it (breaker keeps it out of routing; "
                          f"/admin/drain?replica={rid} revives it)",
                          flush=True)
                    continue
                backoff = (self.args.restart_backoff_s * (2 ** n)
                           * (1 + rng.random()))
                print(f"[router] replica {rid} exited rc={rc}; "
                      f"restarting in {backoff:.1f}s "
                      f"(attempt {n + 1}/{self.args.max_restarts})",
                      flush=True)
                if self._stop.wait(backoff):
                    return
                with self._lock:
                    if self._stop.is_set():
                        return
                    if (self.procs[rid] is not proc
                            or proc.poll() is None
                            or rid in self._respawning):
                        # someone (the drain hook) already replaced it —
                        # or is mid-respawn right now — spawning again
                        # would double-bind the port and orphan the
                        # live child
                        continue
                    self.restarts[rid] += 1
                    self._last_restart[rid] = time.monotonic()
                    self.procs[rid] = spawn_replica(self.ports[rid],
                                                    self.serve_args,
                                                    host=self.args.host)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._watch,
                                        name="router-supervisor",
                                        daemon=True)
        self._thread.start()

    def shutdown(self, sig: int = signal.SIGTERM) -> None:
        """Stop respawning, drain every child (their own SIGTERM
        discipline finishes admitted work), reap."""
        self._stop.set()
        with self._lock:
            procs = dict(self.procs)
        for rid, p in procs.items():
            if p.poll() is None:
                try:
                    p.send_signal(sig)
                except OSError:
                    pass
        for rid, p in procs.items():
            try:
                p.wait(timeout=60.0)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


# ---- main ---------------------------------------------------------------


def main(argv=None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    # everything after `--` belongs to the spawned replicas
    serve_args: List[str] = []
    if "--" in argv:
        split = argv.index("--")
        argv, serve_args = argv[:split], argv[split + 1:]
    args = build_parser().parse_args(argv)

    if bool(args.replicas) == bool(args.spawn):
        raise SystemExit("router: exactly one of --replicas or --spawn N "
                         "is required")
    if serve_args and not args.spawn:
        raise SystemExit("router: serve args after `--` only make sense "
                         "with --spawn")

    supervisor = None
    restarts = {}
    if args.spawn:
        if args.spawn < 1:
            raise SystemExit(f"router: --spawn must be >= 1, got "
                             f"{args.spawn}")
        supervisor = _Supervisor(args, serve_args)
        urls = supervisor.spawn_all()
        print(f"[router] spawned {args.spawn} replica(s) on ports "
              f"{args.port_base}..{args.port_base + args.spawn - 1}; "
              f"waiting for first healthy probe", flush=True)
        ok = [rid for rid, url in urls.items()
              if wait_ready(args.host, supervisor.ports[rid],
                            args.boot_timeout_s)]
        if not ok:
            supervisor.shutdown()
            raise SystemExit("router: no spawned replica became healthy "
                             f"within {args.boot_timeout_s:g}s")
        if len(ok) < args.spawn:
            print(f"[router] WARNING: only {len(ok)}/{args.spawn} "
                  f"replicas healthy at boot; breakers cover the rest",
                  flush=True)
        restarts = {rid: (lambda r=rid: supervisor.respawn(r))
                    for rid in urls}
        supervisor.start()
    else:
        urls = {f"r{i}": addr.strip()
                for i, addr in enumerate(args.replicas.split(","))
                if addr.strip()}
        if not urls:
            raise SystemExit("router: --replicas parsed to an empty pool")

    router = Router(
        urls, host=args.host, port=args.port,
        config=RouterConfig(
            fail_threshold=args.fail_threshold,
            cooldown_s=args.cooldown_s,
            probe_interval_s=args.probe_interval_s,
            max_inflight=args.max_inflight,
            deadline_s=args.deadline_s),
        restarts=restarts)
    router.start()
    print(f"[router] listening on {router.url} — "
          f"{len(urls)} replica(s): "
          + ", ".join(f"{rid}={u}" for rid, u in sorted(urls.items())),
          flush=True)

    stop = threading.Event()
    latched = [False]

    def _handle(signum, frame):
        if latched[0]:
            raise KeyboardInterrupt(f"second signal {signum}")
        latched[0] = True
        print(f"[router] signal {signum}: draining fleet", flush=True)
        stop.set()

    for s in (signal.SIGTERM, signal.SIGINT):
        signal.signal(s, _handle)
    try:
        while not stop.wait(1.0):
            pass
    except KeyboardInterrupt:
        pass
    router.stop()
    if supervisor is not None:
        supervisor.shutdown()
    rec = router.stats.record()
    print(f"[router] stopped — {rec['requests']} requests, "
          f"{rec['proxied_ok']} ok, {rec['retries']} retries "
          f"({rec['failovers']} failovers), "
          f"{rec['shed_router'] + rec['shed_upstream']} shed, "
          f"{rec['upstream_errors']} upstream errors; "
          f"affinity {json.dumps(router.pool.affinity_record())}",
          flush=True)


if __name__ == "__main__":
    main(sys.argv[1:])
