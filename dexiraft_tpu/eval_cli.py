"""Evaluation / submission CLI (reference: evaluate.py:212-243).

  python -m dexiraft_tpu eval --model checkpoints/raft-things \
      --dataset sintel --variant v5
  python -m dexiraft_tpu eval --model ... --submission sintel --warm_start
"""

from __future__ import annotations

import argparse
import sys

import jax

from dexiraft_tpu.train_cli import VARIANTS, _VAL_ITERS


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("dexiraft-eval")
    p.add_argument("--model", required=True, help="orbax checkpoint dir")
    p.add_argument("--dataset", choices=["chairs", "sintel", "kitti", "hd1k"])
    p.add_argument("--submission", choices=["sintel", "kitti"])
    p.add_argument("--warm_start", action="store_true")
    p.add_argument("--variant", default="v1", choices=sorted(VARIANTS))
    p.add_argument("--small", action="store_true")
    p.add_argument("--mixed_precision", action="store_true")
    p.add_argument("--corr_impl", default="allpairs",
                   choices=["allpairs", "local", "pallas"],
                   help="'local'/'pallas' = the memory-efficient on-demand "
                        "path (the reference's --alternate_corr)")
    p.add_argument("--iters", type=int, default=None)
    p.add_argument("--output", default=None, help="submission output dir")
    return p


def load_variables(args):
    from dexiraft_tpu.config import TrainConfig
    from dexiraft_tpu.train import checkpoint as ckpt
    from dexiraft_tpu.train.state import create_state

    cfg = VARIANTS[args.variant](small=args.small,
                                 mixed_precision=args.mixed_precision,
                                 corr_impl=args.corr_impl)
    template = create_state(jax.random.PRNGKey(0), cfg, TrainConfig())
    state = ckpt.restore_checkpoint(args.model, template)
    return cfg, state.variables


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    if not args.dataset and not args.submission:
        raise SystemExit("need --dataset or --submission")

    from dexiraft_tpu.train.step import make_eval_step

    cfg, variables = load_variables(args)

    if args.dataset:
        from dexiraft_tpu.eval.validate import VALIDATORS

        iters = args.iters or _VAL_ITERS[args.dataset]
        step = make_eval_step(cfg, iters=iters)
        VALIDATORS[args.dataset](
            lambda im1, im2, flow_init=None: step(variables, im1, im2,
                                                  flow_init=flow_init))

    if args.submission == "sintel":
        from dexiraft_tpu.eval.submission import create_sintel_submission

        step = make_eval_step(cfg, iters=args.iters or 32)
        create_sintel_submission(
            lambda im1, im2, flow_init=None: step(variables, im1, im2,
                                                  flow_init=flow_init),
            output_path=args.output or "sintel_submission",
            warm_start=args.warm_start)
    elif args.submission == "kitti":
        from dexiraft_tpu.eval.submission import create_kitti_submission

        step = make_eval_step(cfg, iters=args.iters or 24)
        create_kitti_submission(
            lambda im1, im2, flow_init=None: step(variables, im1, im2),
            output_path=args.output or "kitti_submission")


if __name__ == "__main__":
    main(sys.argv[1:])
