"""Evaluation / submission CLI (reference: evaluate.py:212-243).

  python -m dexiraft_tpu eval --model checkpoints/raft-things \
      --dataset sintel --variant v5
  python -m dexiraft_tpu eval --model ... --submission sintel --warm_start
"""

from __future__ import annotations

import argparse
import sys

import jax

from dexiraft_tpu.train_cli import VARIANTS, _VAL_ITERS


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("dexiraft-eval")
    p.add_argument("--model", required=True, help="orbax checkpoint dir")
    p.add_argument("--dataset",
                   choices=["chairs", "sintel", "kitti", "hd1k", "edgesum"],
                   help="'edgesum' = the v1-lineage summed-fusion "
                        "validation (alt/evaluate_1.py): chairs val pairs "
                        "+ their edge images from --edge_root, per-iter "
                        "flows of both passes summed before EPE")
    p.add_argument("--edge_root", default=None,
                   help="parallel tree of edge-map PNGs (for "
                        "--dataset edgesum)")
    p.add_argument("--submission", choices=["sintel", "kitti"])
    p.add_argument("--warm_start", action="store_true")
    p.add_argument("--variant", default="v1", choices=sorted(VARIANTS))
    p.add_argument("--small", action="store_true")
    p.add_argument("--mixed_precision", action="store_true")
    p.add_argument("--corr_impl", default="allpairs",
                   choices=["allpairs", "local", "pallas"],
                   help="'local'/'pallas' = the memory-efficient on-demand "
                        "path (the reference's --alternate_corr)")
    p.add_argument("--scan_unroll", type=int, default=1,
                   help="refinement-scan unroll factor (XLA pipelining "
                        "knob; numerically identical)")
    p.add_argument("--dexined_upconv", default="subpixel",
                   choices=["transpose", "subpixel"],
                   help="embedded-DexiNed upsampler implementation "
                        "(numerically identical; see docs/perf.md)")
    p.add_argument("--iters", type=int, default=None)
    p.add_argument("--output", default=None, help="submission output dir")
    return p


def load_variables(args):
    from dexiraft_tpu.config import TrainConfig
    from dexiraft_tpu.train import checkpoint as ckpt
    from dexiraft_tpu.train.state import create_state

    cfg = VARIANTS[args.variant](small=args.small,
                                 mixed_precision=args.mixed_precision,
                                 corr_impl=args.corr_impl,
                                 dexined_upconv=args.dexined_upconv,
                                 scan_unroll=args.scan_unroll)
    template = create_state(jax.random.PRNGKey(0), cfg, TrainConfig())
    state = ckpt.restore_checkpoint(args.model, template)
    return cfg, state.variables


def _edgesum_dataset(edge_root: str):
    """Chairs validation pairs + their edge images from a parallel tree —
    the data side of the v1-lineage summed-fusion validation
    (alt/evaluate_1.py). Uses the same path-mapping convention as
    training-side edge pairing (data.datasets.wrap_with_edge_tree)."""
    from dexiraft_tpu.data.datasets import FlyingChairs, wrap_with_edge_tree

    return wrap_with_edge_tree(FlyingChairs(None, split="validation"),
                               edge_root)


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    if not args.dataset and not args.submission:
        raise SystemExit("need --dataset or --submission")

    from dexiraft_tpu.train.step import make_eval_step

    cfg, variables = load_variables(args)

    if args.dataset:
        from dexiraft_tpu.eval.validate import run_validation

        dataset = None
        if args.dataset == "edgesum":
            if not args.edge_root:
                raise SystemExit("--dataset edgesum needs --edge_root")
            dataset = _edgesum_dataset(args.edge_root)

        iters = args.iters or _VAL_ITERS.get(args.dataset, 24)
        step = make_eval_step(cfg, iters=iters)
        run_validation(
            args.dataset,
            lambda im1, im2, flow_init=None: step(variables, im1, im2,
                                                  flow_init=flow_init),
            dataset)

    if args.submission == "sintel":
        from dexiraft_tpu.eval.submission import create_sintel_submission

        step = make_eval_step(cfg, iters=args.iters or 32)
        create_sintel_submission(
            lambda im1, im2, flow_init=None: step(variables, im1, im2,
                                                  flow_init=flow_init),
            output_path=args.output or "sintel_submission",
            warm_start=args.warm_start)
    elif args.submission == "kitti":
        from dexiraft_tpu.eval.submission import create_kitti_submission

        step = make_eval_step(cfg, iters=args.iters or 24)
        create_kitti_submission(
            lambda im1, im2, flow_init=None: step(variables, im1, im2),
            output_path=args.output or "kitti_submission")


if __name__ == "__main__":
    main(sys.argv[1:])
