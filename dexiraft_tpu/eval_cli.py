"""Evaluation / submission CLI (reference: evaluate.py:212-243).

  python -m dexiraft_tpu eval --model checkpoints/raft-things \
      --dataset sintel --variant v5
  python -m dexiraft_tpu eval --model ... --submission sintel --warm_start
"""

from __future__ import annotations

import argparse
import sys

import jax

from dexiraft_tpu.train_cli import VARIANTS, _VAL_ITERS


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("dexiraft-eval")
    p.add_argument("--model", required=True, help="orbax checkpoint dir")
    p.add_argument("--dataset",
                   choices=["chairs", "sintel", "kitti", "hd1k", "edgesum"],
                   help="'edgesum' = the v1-lineage summed-fusion "
                        "validation (alt/evaluate_1.py): chairs val pairs "
                        "+ their edge images from --edge_root, per-iter "
                        "flows of both passes summed before EPE")
    p.add_argument("--edge_root", default=None,
                   help="parallel tree of edge-map PNGs (for "
                        "--dataset edgesum)")
    p.add_argument("--submission", choices=["sintel", "kitti"])
    p.add_argument("--warm_start", action="store_true")
    p.add_argument("--variant", default="v1", choices=sorted(VARIANTS))
    p.add_argument("--small", action="store_true")
    p.add_argument("--mixed_precision", action="store_true")
    p.add_argument("--corr_impl", default="auto",
                   choices=["auto", "allpairs", "local", "pallas", "flash"],
                   help="'local'/'pallas'/'flash' = the memory-efficient "
                        "on-demand paths (the reference's "
                        "--alternate_corr); 'auto' (default) = the "
                        "production config: flash-blocked fused step on "
                        "TPU, allpairs off-chip (Pallas kernels only run "
                        "off-TPU in debug-speed interpreter mode)")
    p.add_argument("--corr_dtype", default="fp32",
                   choices=["fp32", "bf16", "int8"],
                   help="storage precision of the correlation pyramid "
                        "(bf16 halves / int8 quarters the refinement "
                        "loop's HBM traffic; docs/perf.md has the "
                        "accuracy bounds)")
    p.add_argument("--fused_update", action="store_true",
                   help="fuse lookup + motion-encoder corr conv into one "
                        "Pallas kernel per iteration (requires "
                        "--corr_impl pallas; same checkpoints)")
    p.add_argument("--scan_unroll", type=int, default=1,
                   help="refinement-scan unroll factor (XLA pipelining "
                        "knob; numerically identical)")
    p.add_argument("--dexined_upconv", default="subpixel",
                   choices=["transpose", "subpixel"],
                   help="embedded-DexiNed upsampler implementation "
                        "(numerically identical; see docs/perf.md)")
    p.add_argument("--iters", type=int, default=None)
    p.add_argument("--output", default=None, help="submission output dir")
    # engine knobs via the ONE shared surface (serve.engine
    # add_engine_args / ServeConfig.from_args) so the batch-eval and
    # persistent-service (serve_cli) batching paths cannot drift; eval
    # keeps batch_size=1 / reference pad shapes (the metric-parity
    # defaults)
    from dexiraft_tpu.serve.engine import add_engine_args

    add_engine_args(p, batch_size=1, bucket_multiple=None)
    p.add_argument("--serve", action="store_true",
                   help="route through the inference engine even at "
                        "batch_size 1 (async in-flight dispatch, bucket "
                        "accounting)")
    p.add_argument("--data_parallel", type=int, default=0,
                   help="shard each inference batch over this many "
                        "chips (0 = single chip); batch_size must "
                        "divide by it")
    # runtime guard mode (analysis/guards.py, docs/static_analysis.md)
    p.add_argument("--strict", action="store_true",
                   help="run evaluation inside guards.strict_mode: "
                        "implicit host<->device transfers raise and any "
                        "recompile beyond the expected one-per-geometry "
                        "warmup fails the run")
    return p


def load_variables(args):
    from dexiraft_tpu.config import TrainConfig
    from dexiraft_tpu.train import checkpoint as ckpt
    from dexiraft_tpu.train.state import create_state

    # a missing/empty --model dir is an operator typo, not a program
    # bug: fail as ONE actionable line (path + nearest candidate dirs)
    # instead of the orbax traceback it used to produce
    try:
        ckpt.require_checkpoints(args.model)
    except FileNotFoundError as e:
        raise SystemExit(f"eval: {e}")
    from dexiraft_tpu.config import resolve_corr_impl_args

    impl, fused = resolve_corr_impl_args(args, jax.devices()[0].platform,
                                         "eval")
    cfg = VARIANTS[args.variant](small=args.small,
                                 mixed_precision=args.mixed_precision,
                                 corr_impl=impl,
                                 corr_dtype=args.corr_dtype,
                                 fused_update=fused,
                                 dexined_upconv=args.dexined_upconv,
                                 scan_unroll=args.scan_unroll)
    template = create_state(jax.random.PRNGKey(0), cfg, TrainConfig())
    state = ckpt.restore_checkpoint(args.model, template)
    return cfg, state.variables


def _edgesum_dataset(edge_root: str):
    """Chairs validation pairs + their edge images from a parallel tree —
    the data side of the v1-lineage summed-fusion validation
    (alt/evaluate_1.py). Uses the same path-mapping convention as
    training-side edge pairing (data.datasets.wrap_with_edge_tree)."""
    from dexiraft_tpu.data.datasets import FlyingChairs, wrap_with_edge_tree

    return wrap_with_edge_tree(FlyingChairs(None, split="validation"),
                               edge_root)


def _serving(args) -> bool:
    return args.serve or args.batch_size > 1 or args.data_parallel > 0


def _make_eval_fn(args, cfg, variables, iters):
    """Uniform eval-fn: (im1, im2, flow_init) — POSITIONAL-safe for the
    engine (the mesh path pins in_shardings, which rejects kwargs) and
    kwarg-friendly for the per-image loops. Sintel and KITTI now share
    one signature: flow_init=None is always accepted (the KITTI model
    simply never receives a warm start)."""
    from dexiraft_tpu.train.step import make_eval_step

    mesh = None
    if args.data_parallel > 0:
        from dexiraft_tpu.parallel.layout import make_serve_mesh, replicate

        mesh = make_serve_mesh(args.data_parallel)
        # replicate once up front — the pinned replicated in_sharding
        # would otherwise re-transfer the params on every dispatch
        variables = replicate(variables, mesh)
    step = make_eval_step(cfg, iters=iters, mesh=mesh)
    if mesh is None:
        # explicit H2D put (jaxlint/guards): callers hand numpy frames;
        # device_put keeps the transfer visible and legal under the
        # strict transfer guard (the put is async — dispatch overlap is
        # preserved). Variables go up ONCE here — restored checkpoints
        # are host numpy, and re-transferring them per call would be an
        # implicit (guard-tripping) put on every frame.
        variables = jax.device_put(variables)
        put = jax.device_put
        return (lambda im1, im2, flow_init=None:
                step(variables, put(im1), put(im2),
                     flow_init=(None if flow_init is None
                                else put(flow_init)))), None
    return (lambda im1, im2, flow_init=None:
            step(variables, im1, im2, None, None, flow_init)), mesh


def _make_engine(args, eval_fn, mesh, mode, warm_start=False, watch=None):
    from dexiraft_tpu.serve import InferenceEngine, ServeConfig

    engine = InferenceEngine(
        eval_fn,
        ServeConfig.from_args(args, mode=mode, warm_start=warm_start),
        mesh=mesh)
    if watch is not None:
        # share the CLI's strict_mode watch: the engine's expected
        # bucket compiles re-baseline it, so the region's exit check
        # only fires on genuinely unplanned recompiles
        engine.watch = watch
    return engine


def _strict_wrap(eval_fn, watch):
    """Per-geometry compile absorption for the per-image eval loops.

    The first call on a new input-shape signature is an EXPECTED compile
    (re-baselines the watch); a repeat signature must ride the compiled
    executable — if it compiled anyway, that is shape/dtype drift and
    the watch raises.
    """
    import numpy as np

    seen = set()

    def wrapped(im1, im2, flow_init=None):
        sig = (np.shape(im1), np.shape(im2),
               None if flow_init is None else np.shape(flow_init))
        out = eval_fn(im1, im2, flow_init=flow_init)
        if sig in seen:
            watch.check()
        else:
            seen.add(sig)
            watch.mark_warm()
        return out

    return wrapped


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    if not args.dataset and not args.submission:
        raise SystemExit("need --dataset or --submission")
    if args.data_parallel and args.batch_size % max(args.data_parallel, 1):
        raise SystemExit(f"--batch_size {args.batch_size} must divide by "
                         f"--data_parallel {args.data_parallel}")

    cfg, variables = load_variables(args)

    import contextlib

    region = contextlib.ExitStack()
    watch = None
    if args.strict:
        from dexiraft_tpu.analysis import guards

        # ONE strict region over every eval/submission below: implicit
        # host<->device transfers raise at the offending call, and the
        # region's exit check fails the run on any compile the
        # per-geometry absorption (_strict_wrap / the engine's
        # mark_warm) did not expect. docs/static_analysis.md.
        # The data-parallel path keeps the pinned in_shardings' own
        # transfer semantics (the jitted step ingests host numpy frames
        # by design — same carve-out as serve_bench), so only the
        # recompile sentinel is armed there.
        watch = region.enter_context(guards.strict_mode(
            label="eval",
            transfer="allow" if args.data_parallel else "disallow"))

    with region:
        _run_eval(args, cfg, variables, watch)


def _run_eval(args, cfg, variables, watch) -> None:
    if args.dataset:
        from dexiraft_tpu.eval.validate import run_validation

        dataset = None
        if args.dataset == "edgesum":
            if not args.edge_root:
                raise SystemExit("--dataset edgesum needs --edge_root")
            dataset = _edgesum_dataset(args.edge_root)

        iters = args.iters or _VAL_ITERS.get(args.dataset, 24)
        eval_fn, mesh = _make_eval_fn(args, cfg, variables, iters)
        engine = None
        if _serving(args):
            mode = "kitti" if args.dataset in ("kitti", "hd1k") else "sintel"
            engine = _make_engine(args, eval_fn, mesh, mode, watch=watch)
        elif watch is not None:
            eval_fn = _strict_wrap(eval_fn, watch)
        run_validation(args.dataset, eval_fn, dataset,
                       batch_size=args.batch_size, engine=engine)
        if engine is not None:
            print(f"engine: {engine.stats.summary()}")

    if args.submission == "sintel":
        from dexiraft_tpu.eval.submission import create_sintel_submission

        eval_fn, mesh = _make_eval_fn(args, cfg, variables, args.iters or 32)
        engine = (_make_engine(args, eval_fn, mesh, "sintel",
                               warm_start=args.warm_start, watch=watch)
                  if _serving(args) else None)
        if engine is None and watch is not None:
            eval_fn = _strict_wrap(eval_fn, watch)
        create_sintel_submission(
            eval_fn,
            output_path=args.output or "sintel_submission",
            warm_start=args.warm_start,
            batch_size=args.batch_size,
            engine=engine)
    elif args.submission == "kitti":
        from dexiraft_tpu.eval.submission import create_kitti_submission

        eval_fn, mesh = _make_eval_fn(args, cfg, variables, args.iters or 24)
        engine = (_make_engine(args, eval_fn, mesh, "kitti", watch=watch)
                  if _serving(args) else None)
        if engine is None and watch is not None:
            eval_fn = _strict_wrap(eval_fn, watch)
        create_kitti_submission(
            eval_fn,
            output_path=args.output or "kitti_submission",
            batch_size=args.batch_size,
            engine=engine)


if __name__ == "__main__":
    main(sys.argv[1:])
