"""Evaluation / submission CLI (reference: evaluate.py:212-243).

  python -m dexiraft_tpu eval --model checkpoints/raft-things \
      --dataset sintel --variant v5
  python -m dexiraft_tpu eval --model ... --submission sintel --warm_start
"""

from __future__ import annotations

import argparse
import sys

import jax

from dexiraft_tpu.train_cli import VARIANTS, _VAL_ITERS


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("dexiraft-eval")
    p.add_argument("--model", required=True, help="orbax checkpoint dir")
    p.add_argument("--dataset",
                   choices=["chairs", "sintel", "kitti", "hd1k", "edgesum"],
                   help="'edgesum' = the v1-lineage summed-fusion "
                        "validation (alt/evaluate_1.py): chairs val pairs "
                        "+ their edge images from --edge_root, per-iter "
                        "flows of both passes summed before EPE")
    p.add_argument("--edge_root", default=None,
                   help="parallel tree of edge-map PNGs (for "
                        "--dataset edgesum)")
    p.add_argument("--submission", choices=["sintel", "kitti"])
    p.add_argument("--warm_start", action="store_true")
    p.add_argument("--variant", default="v1", choices=sorted(VARIANTS))
    p.add_argument("--small", action="store_true")
    p.add_argument("--mixed_precision", action="store_true")
    p.add_argument("--corr_impl", default="auto",
                   choices=["auto", "allpairs", "local", "pallas", "flash"],
                   help="'local'/'pallas'/'flash' = the memory-efficient "
                        "on-demand paths (the reference's "
                        "--alternate_corr); 'auto' (default) = the "
                        "production config: flash-blocked fused step on "
                        "TPU, allpairs off-chip (Pallas kernels only run "
                        "off-TPU in debug-speed interpreter mode)")
    p.add_argument("--corr_dtype", default="fp32",
                   choices=["fp32", "bf16", "int8"],
                   help="storage precision of the correlation pyramid "
                        "(bf16 halves / int8 quarters the refinement "
                        "loop's HBM traffic; docs/perf.md has the "
                        "accuracy bounds)")
    p.add_argument("--fused_update", action="store_true",
                   help="fuse lookup + motion-encoder corr conv into one "
                        "Pallas kernel per iteration (requires "
                        "--corr_impl pallas; same checkpoints)")
    p.add_argument("--scan_unroll", type=int, default=1,
                   help="refinement-scan unroll factor (XLA pipelining "
                        "knob; numerically identical)")
    p.add_argument("--dexined_upconv", default="subpixel",
                   choices=["transpose", "subpixel"],
                   help="embedded-DexiNed upsampler implementation "
                        "(numerically identical; see docs/perf.md)")
    p.add_argument("--iters", type=int, default=None)
    p.add_argument("--adaptive", action="store_true",
                   help="convergence-gated adaptive inference: the "
                        "refinement runs a while_loop that freezes each "
                        "item once its flow-delta norm drops below "
                        "converge_tol (docs/serving.md \"Adaptive "
                        "iterations\"); --iters becomes the budget CAP")
    p.add_argument("--converge_tol", type=float, default=None,
                   help="override RAFTConfig.converge_tol (mean 1/8-res "
                        "flow-delta norm below which an item stops "
                        "refining; 0 disables the gate — bit-exact "
                        "fixed-iteration parity)")
    p.add_argument("--adaptive_iters", default=None,
                   help="comma-separated iteration budgets (e.g. "
                        "4,8,16,32): runs the fixed baseline at --iters "
                        "plus the adaptive driver at each budget and "
                        "emits ONE EPE-vs-latency frontier JSON record "
                        "(docs/perf.md \"Adaptive-iteration frontier\")")
    p.add_argument("--frontier_out", default=None,
                   help="also write the --adaptive_iters frontier "
                        "record to this path")
    p.add_argument("--output", default=None, help="submission output dir")
    # engine knobs via the ONE shared surface (serve.engine
    # add_engine_args / ServeConfig.from_args) so the batch-eval and
    # persistent-service (serve_cli) batching paths cannot drift; eval
    # keeps batch_size=1 / reference pad shapes (the metric-parity
    # defaults)
    from dexiraft_tpu.serve.engine import add_engine_args

    add_engine_args(p, batch_size=1, bucket_multiple=None)
    p.add_argument("--serve", action="store_true",
                   help="route through the inference engine even at "
                        "batch_size 1 (async in-flight dispatch, bucket "
                        "accounting)")
    p.add_argument("--data_parallel", type=int, default=0,
                   help="shard each inference batch over this many "
                        "chips (0 = single chip); batch_size must "
                        "divide by it")
    # runtime guard mode (analysis/guards.py, docs/static_analysis.md)
    p.add_argument("--strict", action="store_true",
                   help="run evaluation inside guards.strict_mode: "
                        "implicit host<->device transfers raise and any "
                        "recompile beyond the expected one-per-geometry "
                        "warmup fails the run")
    return p


def load_variables(args):
    from dexiraft_tpu.config import TrainConfig
    from dexiraft_tpu.train import checkpoint as ckpt
    from dexiraft_tpu.train.state import create_state

    # a missing/empty --model dir is an operator typo, not a program
    # bug: fail as ONE actionable line (path + nearest candidate dirs)
    # instead of the orbax traceback it used to produce
    try:
        ckpt.require_checkpoints(args.model)
    except FileNotFoundError as e:
        raise SystemExit(f"eval: {e}")
    from dexiraft_tpu.config import resolve_corr_impl_args

    impl, fused = resolve_corr_impl_args(args, jax.devices()[0].platform,
                                         "eval")
    cfg = VARIANTS[args.variant](small=args.small,
                                 mixed_precision=args.mixed_precision,
                                 corr_impl=impl,
                                 corr_dtype=args.corr_dtype,
                                 fused_update=fused,
                                 dexined_upconv=args.dexined_upconv,
                                 scan_unroll=args.scan_unroll)
    if getattr(args, "converge_tol", None) is not None:
        import dataclasses

        # checkpoint-compatible: the gate threshold shapes no params,
        # only the adaptive driver's exit condition
        cfg = dataclasses.replace(cfg, converge_tol=args.converge_tol)
    template = create_state(jax.random.PRNGKey(0), cfg, TrainConfig())
    state = ckpt.restore_checkpoint(args.model, template)
    return cfg, state.variables


def _edgesum_dataset(edge_root: str):
    """Chairs validation pairs + their edge images from a parallel tree —
    the data side of the v1-lineage summed-fusion validation
    (alt/evaluate_1.py). Uses the same path-mapping convention as
    training-side edge pairing (data.datasets.wrap_with_edge_tree)."""
    from dexiraft_tpu.data.datasets import FlyingChairs, wrap_with_edge_tree

    return wrap_with_edge_tree(FlyingChairs(None, split="validation"),
                               edge_root)


def _serving(args) -> bool:
    return args.serve or args.batch_size > 1 or args.data_parallel > 0


def _make_eval_fn(args, cfg, variables, iters, adaptive=None):
    """Uniform eval-fn: (im1, im2, flow_init) — POSITIONAL-safe for the
    engine (the mesh path pins in_shardings, which rejects kwargs) and
    kwarg-friendly for the per-image loops. Sintel and KITTI now share
    one signature: flow_init=None is always accepted (the KITTI model
    simply never receives a warm start).

    Adaptive (default: args.adaptive) grows the trailing ``iter_budget``
    and the 4-tuple return (the ADAPTIVE engine contract in
    serve/engine.py): a ``None`` budget resolves to the full ``iters``
    HERE, normalized to the same np.int32 aval the engine's scheduler
    dispatches use — every budget value rides ONE traced scalar, so one
    executable per bucket serves them all."""
    import numpy as np

    from dexiraft_tpu.train.step import make_eval_step

    if adaptive is None:
        adaptive = getattr(args, "adaptive", False)
    mesh = None
    if args.data_parallel > 0:
        from dexiraft_tpu.parallel.layout import make_serve_mesh, replicate

        mesh = make_serve_mesh(args.data_parallel)
        # replicate once up front — the pinned replicated in_sharding
        # would otherwise re-transfer the params on every dispatch
        variables = replicate(variables, mesh)
    step = make_eval_step(cfg, iters=iters, mesh=mesh, adaptive=adaptive)
    if mesh is None:
        # explicit H2D put (jaxlint/guards): callers hand numpy frames;
        # device_put keeps the transfer visible and legal under the
        # strict transfer guard (the put is async — dispatch overlap is
        # preserved). Variables go up ONCE here — restored checkpoints
        # are host numpy, and re-transferring them per call would be an
        # implicit (guard-tripping) put on every frame.
        variables = jax.device_put(variables)
        put = jax.device_put
        if adaptive:
            return (lambda im1, im2, flow_init=None, iter_budget=None:
                    step(variables, put(im1), put(im2),
                         flow_init=(None if flow_init is None
                                    else put(flow_init)),
                         iter_budget=np.int32(
                             iters if iter_budget is None
                             else iter_budget))), None
        return (lambda im1, im2, flow_init=None:
                step(variables, put(im1), put(im2),
                     flow_init=(None if flow_init is None
                                else put(flow_init)))), None
    if adaptive:
        return (lambda im1, im2, flow_init=None, iter_budget=None:
                step(variables, im1, im2, None, None, flow_init,
                     np.int32(iters if iter_budget is None
                              else iter_budget))), mesh
    return (lambda im1, im2, flow_init=None:
            step(variables, im1, im2, None, None, flow_init)), mesh


# ---- adaptive frontier record schema, pinned by
# tests/test_zzzadaptive.py -----------------------------------------------
FRONTIER_RECORD_KEYS = {
    "record", "dataset", "iters", "converge_tol", "fixed", "sweep",
}
# every sweep leg carries the dataset's metric keys plus these
FRONTIER_LEG_KEYS = {
    "budget", "wall_s", "mean_iters_used", "p99_iters_used",
    "mean_final_delta",
}


def _adaptive_pair_view(eval_fn, sink=None):
    """Adapt the adaptive 4-tuple eval fn to the (flow_low, flow_up)
    contract of the per-image loops (eval.validate/_run unpacks exactly
    two). iters_used/final_delta land in ``sink`` (a list of per-call
    (iters_used, final_delta) host arrays) when one is given."""

    def fn(im1, im2, flow_init=None):
        flow_low, flow_up, iters_used, final_delta = eval_fn(
            im1, im2, flow_init)
        if sink is not None:
            # explicit D2H (jaxlint JL007) — (B,) scalars per call
            sink.append((jax.device_get(iters_used),
                         jax.device_get(final_delta)))
        return flow_low, flow_up

    return fn


def _sink_summary(sink) -> dict:
    import numpy as np

    used = np.concatenate([np.atleast_1d(iu) for iu, _ in sink])
    deltas = np.concatenate([np.atleast_1d(fd) for _, fd in sink])
    return {
        "mean_iters_used": round(float(used.mean()), 2),
        "p99_iters_used": round(float(np.percentile(used, 99)), 2),
        "mean_final_delta": round(float(deltas.mean()), 6),
    }


def _make_engine(args, eval_fn, mesh, mode, warm_start=False, watch=None):
    from dexiraft_tpu.serve import InferenceEngine, ServeConfig

    engine = InferenceEngine(
        eval_fn,
        ServeConfig.from_args(args, mode=mode, warm_start=warm_start),
        mesh=mesh)
    if watch is not None:
        # share the CLI's strict_mode watch: the engine's expected
        # bucket compiles re-baseline it, so the region's exit check
        # only fires on genuinely unplanned recompiles
        engine.watch = watch
    return engine


def _strict_wrap(eval_fn, watch):
    """Per-geometry compile absorption for the per-image eval loops.

    The first call on a new input-shape signature is an EXPECTED compile
    (re-baselines the watch); a repeat signature must ride the compiled
    executable — if it compiled anyway, that is shape/dtype drift and
    the watch raises.
    """
    import numpy as np

    seen = set()

    def wrapped(im1, im2, flow_init=None):
        sig = (np.shape(im1), np.shape(im2),
               None if flow_init is None else np.shape(flow_init))
        out = eval_fn(im1, im2, flow_init=flow_init)
        if sig in seen:
            watch.check()
        else:
            seen.add(sig)
            watch.mark_warm()
        return out

    return wrapped


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    if not args.dataset and not args.submission:
        raise SystemExit("need --dataset or --submission")
    if args.data_parallel and args.batch_size % max(args.data_parallel, 1):
        raise SystemExit(f"--batch_size {args.batch_size} must divide by "
                         f"--data_parallel {args.data_parallel}")

    cfg, variables = load_variables(args)

    import contextlib

    region = contextlib.ExitStack()
    watch = None
    if args.strict:
        from dexiraft_tpu.analysis import guards

        # ONE strict region over every eval/submission below: implicit
        # host<->device transfers raise at the offending call, and the
        # region's exit check fails the run on any compile the
        # per-geometry absorption (_strict_wrap / the engine's
        # mark_warm) did not expect. docs/static_analysis.md.
        # The data-parallel path keeps the pinned in_shardings' own
        # transfer semantics (the jitted step ingests host numpy frames
        # by design — same carve-out as serve_bench), so only the
        # recompile sentinel is armed there.
        watch = region.enter_context(guards.strict_mode(
            label="eval",
            transfer="allow" if args.data_parallel else "disallow"))

    with region:
        _run_eval(args, cfg, variables, watch)


def _run_eval(args, cfg, variables, watch) -> None:
    if args.adaptive_iters:
        _adaptive_sweep(args, cfg, variables)
        return
    if args.dataset:
        from dexiraft_tpu.eval.validate import run_validation

        dataset = None
        if args.dataset == "edgesum":
            if not args.edge_root:
                raise SystemExit("--dataset edgesum needs --edge_root")
            dataset = _edgesum_dataset(args.edge_root)

        iters = args.iters or _VAL_ITERS.get(args.dataset, 24)
        eval_fn, mesh = _make_eval_fn(args, cfg, variables, iters)
        engine = None
        sink: list = []
        if _serving(args):
            mode = "kitti" if args.dataset in ("kitti", "hd1k") else "sintel"
            engine = _make_engine(args, eval_fn, mesh, mode, watch=watch)
        else:
            if args.adaptive:
                eval_fn = _adaptive_pair_view(eval_fn, sink)
            if watch is not None:
                eval_fn = _strict_wrap(eval_fn, watch)
        run_validation(args.dataset, eval_fn, dataset,
                       batch_size=args.batch_size, engine=engine)
        if engine is not None:
            print(f"engine: {engine.stats.summary()}")
            if engine.config.adaptive:
                print(f"adaptive: mean iters_used "
                      f"{engine.stats.iters_used_mean():.1f} / "
                      f"p99 {engine.stats.iters_used_pctl(99):.0f} "
                      f"(budget {iters})")
        elif sink:
            s = _sink_summary(sink)
            print(f"adaptive: mean iters_used {s['mean_iters_used']} / "
                  f"p99 {s['p99_iters_used']} (budget {iters}), "
                  f"mean final delta {s['mean_final_delta']}")

    if args.submission == "sintel":
        from dexiraft_tpu.eval.submission import create_sintel_submission

        eval_fn, mesh = _make_eval_fn(args, cfg, variables, args.iters or 32)
        engine = (_make_engine(args, eval_fn, mesh, "sintel",
                               warm_start=args.warm_start, watch=watch)
                  if _serving(args) else None)
        if engine is None and args.adaptive:
            eval_fn = _adaptive_pair_view(eval_fn)
        if engine is None and watch is not None:
            eval_fn = _strict_wrap(eval_fn, watch)
        create_sintel_submission(
            eval_fn,
            output_path=args.output or "sintel_submission",
            warm_start=args.warm_start,
            batch_size=args.batch_size,
            engine=engine)
    elif args.submission == "kitti":
        from dexiraft_tpu.eval.submission import create_kitti_submission

        eval_fn, mesh = _make_eval_fn(args, cfg, variables, args.iters or 24)
        engine = (_make_engine(args, eval_fn, mesh, "kitti", watch=watch)
                  if _serving(args) else None)
        if engine is None and args.adaptive:
            eval_fn = _adaptive_pair_view(eval_fn)
        if engine is None and watch is not None:
            eval_fn = _strict_wrap(eval_fn, watch)
        create_kitti_submission(
            eval_fn,
            output_path=args.output or "kitti_submission",
            batch_size=args.batch_size,
            engine=engine)


def _adaptive_sweep(args, cfg, variables) -> None:
    """The EPE-vs-latency frontier protocol (docs/perf.md): ONE fixed
    baseline at --iters plus the adaptive driver at each budget in
    --adaptive_iters, all over the same dataset in the same process.
    Emits one self-describing JSON record (stdout, and --frontier_out).

    Per-image loop on purpose (no engine/batching): the legs differ
    only in the refinement driver, so their wall-clocks are directly
    comparable and the per-item iters_used samples are exact.
    """
    import json
    import time

    from dexiraft_tpu.eval.validate import run_validation

    if not args.dataset:
        raise SystemExit("--adaptive_iters needs --dataset")
    budgets = [int(tok) for tok in args.adaptive_iters.split(",")
               if tok.strip()]
    if not budgets:
        raise SystemExit(f"--adaptive_iters parsed to no budgets: "
                         f"{args.adaptive_iters!r}")
    dataset = None
    if args.dataset == "edgesum":
        if not args.edge_root:
            raise SystemExit("--dataset edgesum needs --edge_root")
        dataset = _edgesum_dataset(args.edge_root)
    iters = args.iters or _VAL_ITERS.get(args.dataset, 24)

    fixed_fn, _ = _make_eval_fn(args, cfg, variables, iters,
                                adaptive=False)
    t0 = time.perf_counter()
    fixed_metrics = run_validation(args.dataset, fixed_fn, dataset)
    fixed_wall = time.perf_counter() - t0

    adaptive_fn, _ = _make_eval_fn(args, cfg, variables, iters,
                                   adaptive=True)
    record = {
        "record": "adaptive_frontier",
        "dataset": args.dataset,
        "iters": iters,
        "converge_tol": cfg.converge_tol,
        "fixed": {**fixed_metrics, "wall_s": round(fixed_wall, 2)},
        "sweep": [],
    }
    for budget in budgets:
        sink: list = []
        fn = _adaptive_pair_view(
            lambda im1, im2, flow_init=None, _b=budget:
            adaptive_fn(im1, im2, flow_init, _b), sink)
        t0 = time.perf_counter()
        metrics = run_validation(args.dataset, fn, dataset)
        wall = time.perf_counter() - t0
        leg = {**metrics, "budget": budget, "wall_s": round(wall, 2)}
        if sink:
            leg.update(_sink_summary(sink))
        # the frontier's decision metric: quality cost of THIS budget
        # relative to the fixed anchor, per dataset key
        for k, v in fixed_metrics.items():
            if isinstance(v, float) and k in metrics:
                leg[f"{k}_delta"] = round(metrics[k] - v, 4)
        assert FRONTIER_LEG_KEYS <= set(leg), \
            sorted(FRONTIER_LEG_KEYS - set(leg))
        record["sweep"].append(leg)
    assert set(record) == FRONTIER_RECORD_KEYS, \
        sorted(set(record) ^ FRONTIER_RECORD_KEYS)
    line = json.dumps(record)
    print(line)
    if args.frontier_out:
        with open(args.frontier_out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main(sys.argv[1:])
