"""DexiNed standalone train/test CLI (reference core/DexiNed/main.py).

  python -m dexiraft_tpu dexined --train --data_root /data/BIPED/edges
  python -m dexiraft_tpu dexined --test --checkpoint ckpts/dexined \
      --data_root /data/CLASSIC

Training: Adam on the per-scale weighted bdcn_loss2 (weights
[0.7,0.7,1.1,1.1,0.3,0.3,1.3], main.py:29,39), per-epoch checkpoint and
edge-map dump (main.py:427-436). Testing: fused-output PNGs via
sigmoid -> invert -> resize-back (utils/image.py:29-80) with per-image
timing (main.py:133-147).
"""

from __future__ import annotations

import argparse
import os
import os.path as osp
import sys
import time
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dexiraft_tpu.dexined.data import DATASET_INFO, BipedDataset, TestDataset
from dexiraft_tpu.dexined.losses import weighted_multiscale_loss
from dexiraft_tpu.models.dexined import DexiNed
from dexiraft_tpu.train import step as step_lib


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("dexiraft-dexined")
    p.add_argument("--train", action="store_true")
    p.add_argument("--test", action="store_true")
    p.add_argument("--data_root", required=True)
    p.add_argument("--dataset", default="BIPED", choices=sorted(DATASET_INFO))
    p.add_argument("--checkpoint", default="checkpoints/dexined")
    p.add_argument("--output_dir", default="dexined_results")
    p.add_argument("--epochs", type=int, default=17)
    p.add_argument("--batch_size", type=int, default=8)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--wd", type=float, default=0.0)
    p.add_argument("--img_size", type=int, default=352)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--steps_per_epoch", type=int, default=None,
                   help="cap batches per epoch (default: full dataset)")
    p.add_argument("--no_guard", action="store_true",
                   help="disable the epoch-end divergence guard "
                        "(non-finite loss -> roll back to the last "
                        "epoch checkpoint instead of saving the "
                        "poisoned state; see train_cli for rationale)")
    p.add_argument("--max_rollbacks", type=int, default=3)
    p.add_argument("--gt_root", default=None,
                   help="ground-truth edge-map dir: --test additionally "
                        "reports ODS/OIS/AP (dexined.metrics)")
    p.add_argument("--matching", default="assignment",
                   choices=("assignment", "dilation"),
                   help="TP matching rule: 'assignment' is the exact "
                        "one-to-one correspondPixels protocol; 'dilation' "
                        "is the fast surrogate (scores trend higher, "
                        "docs/parity.md)")
    p.add_argument("--upconv", default="subpixel",
                   choices=("transpose", "subpixel"),
                   help="upsampler implementation (numerically "
                        "identical; subpixel avoids input-dilated "
                        "convs on TPU)")
    p.add_argument("--stall_timeout", type=float, default=0.0,
                   help="hang watchdog: a training step making no "
                        "progress for this many seconds dumps live "
                        "stacks and exits nonzero instead of hanging "
                        "(0 = disabled; see docs/resilience.md)")
    p.add_argument("--test_pich", action="store_true",
                   help="channel-swap ensemble test (reference testPich, "
                        "main.py:149-187): second forward on the BGR-swapped "
                        "image, merged where it is more edge-confident")
    return p


def save_edge_maps(fused_probs: np.ndarray, names, shapes, out_dir: str) -> None:
    """sigmoid output -> inverted uint8 edge PNG at original resolution."""
    import cv2

    os.makedirs(out_dir, exist_ok=True)
    for prob, name, shape in zip(fused_probs, names, shapes):
        img = (255.0 * (1.0 - prob[..., 0])).clip(0, 255).astype(np.uint8)
        img = cv2.resize(img, (int(shape[1]), int(shape[0])))
        cv2.imwrite(osp.join(out_dir, osp.splitext(name)[0] + ".png"), img)


def _normalize_invert(prob: np.ndarray) -> np.ndarray:
    """min-max normalize to [0,255] then invert (utils/image.py:9-26,90-91)."""
    lo, hi = float(prob.min()), float(prob.max())
    img = (prob - lo) * 255.0 / (hi - lo + 1e-12)
    return 255 - img.astype(np.uint8)


def save_test_outputs(probs: np.ndarray, probs2, names, shapes,
                      out_dir: str) -> None:
    """The reference's full test-mode save protocol (utils/image.py:29-133).

    probs: (7, B, H, W, 1) sigmoid outputs.  Each of the 7 maps is min-max
    normalized, inverted, and resized to the source resolution; `fused/` gets
    scale 7 (the block_cat output), `avg/` the mean over all 7.  With a
    channel-swap second pass (probs2, testPich) the directories are named
    `fusedCH`/`avgCH` and each map is merged with its swapped twin where the
    twin is more edge-confident (pixels where map>128 but twin<128 take the
    twin — utils/image.py:106-121).
    """
    import cv2

    fuse_name, av_name = ("fusedCH", "avgCH") if probs2 is not None \
        else ("fused", "avg")
    dir_f = osp.join(out_dir, fuse_name)
    dir_a = osp.join(out_dir, av_name)
    os.makedirs(dir_f, exist_ok=True)
    os.makedirs(dir_a, exist_ok=True)
    for b, (name, shape) in enumerate(zip(names, shapes)):
        size = (int(shape[1]), int(shape[0]))
        preds, fuse = [], None
        for s in range(probs.shape[0]):
            img = cv2.resize(_normalize_invert(probs[s, b, ..., 0]), size)
            if probs2 is not None:
                img2 = cv2.resize(
                    _normalize_invert(probs2[s, b, ..., 0]), size)
                img = np.where((img > 128) & (img2 < 128), img2, img)
            preds.append(img)
            if s == probs.shape[0] - 1:
                fuse = img.astype(np.uint8)
        average = np.mean(np.asarray(preds, np.float32), axis=0).astype(
            np.uint8)
        stem = osp.splitext(name)[0] + ".png"
        cv2.imwrite(osp.join(dir_f, stem), fuse)
        cv2.imwrite(osp.join(dir_a, stem), average)


def train(args) -> None:
    import optax

    from dexiraft_tpu.train import checkpoint as ckpt_io

    info = DATASET_INFO[args.dataset]
    dataset = BipedDataset(args.data_root, img_size=args.img_size,
                           mean_bgr=info.mean_bgr,
                           train_list=info.train_list)
    print(f"Training DexiNed on {args.dataset}: {len(dataset)} pairs")

    model = DexiNed(upconv=args.upconv)
    rng = jax.random.PRNGKey(args.seed)
    dummy = jnp.zeros((1, args.img_size, args.img_size, 3), jnp.float32)
    variables = jax.jit(
        lambda r, x: model.init(r, x, train=True))(rng, dummy)
    params, batch_stats = variables["params"], variables.get("batch_stats", {})

    tx = optax.adamw(args.lr, weight_decay=args.wd)
    opt_state = tx.init(params)

    # donate the threaded state: without it the pre-update params/opt
    # moments stay resident across the call and double the step's HBM
    # (jaxlint JL006)
    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(params, batch_stats, opt_state, images, labels):
        def loss_fn(p):
            preds, mut = model.apply(
                {"params": p, "batch_stats": batch_stats}, images,
                train=True, mutable=["batch_stats"])
            return (weighted_multiscale_loss(preds, labels),
                    mut.get("batch_stats", batch_stats))

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # post-update verdict: the loss certifies the PRE-update params
        # only; the epoch checkpoint saves THIS state (see train.step)
        ok = step_lib.all_finite(params, new_stats, opt_state)
        return params, new_stats, opt_state, loss, ok

    from dexiraft_tpu.train.state import TrainState

    from dexiraft_tpu.train.guard import DivergenceGuard

    n = len(dataset)
    steps_per_epoch = args.steps_per_epoch or max(n // args.batch_size, 1)
    # finiteness-only: healthy BDCN multiscale losses run in the
    # thousands (logs/dexined_demo_cpu.log), so no magnitude threshold
    guard = DivergenceGuard(threshold=float("inf"),
                            max_rollbacks=args.max_rollbacks)
    # hang watchdog (resilience.watchdog): same contract as train_cli —
    # a stalled step dumps live stacks and exits nonzero (inert at 0)
    from dexiraft_tpu.resilience import HangWatchdog

    wd = HangWatchdog(args.stall_timeout,
                      label=f"dexined[{args.dataset}]").start()
    # only checkpoints written by THIS run are valid rollback targets —
    # --checkpoint defaults to a constant dir, and splicing a previous
    # experiment's weights into this one would be silent corruption
    last_saved = None
    try:
        _train_epochs(args, dataset, guard, wd, step, ckpt_io, rng,
                      n, steps_per_epoch, params, batch_stats, opt_state,
                      last_saved)
    finally:
        # stop WITH the loop, also on the error path: a still-armed
        # watchdog firing during teardown would replace the real
        # traceback with a bogus stall report
        wd.stop()


def _train_epochs(args, dataset, guard, wd, step, ckpt_io, rng, n,
                  steps_per_epoch, params, batch_stats, opt_state,
                  last_saved) -> None:
    from dexiraft_tpu.train.state import TrainState

    for epoch in range(args.epochs):
        # periodic reseed like the reference's per-epoch reshuffle
        # (main.py:403-410)
        order_rng = np.random.default_rng((args.seed, epoch))
        order = order_rng.permutation(n)
        for b in range(steps_per_epoch):
            ids = order[(b * args.batch_size) % n:][:args.batch_size]
            if len(ids) < args.batch_size:
                ids = order[:args.batch_size]
            if epoch or b:
                # never armed over the first step: it contains the XLA
                # compile, which a step-sized timeout would misread.
                # Released across the frame boundary: main()'s
                # `finally: wd.stop()` retires the arm on any unwind
                wd.arm(epoch * steps_per_epoch + b + 1)  # jaxlint: disable=JL034 caller's finally stops it
            samples = [dataset.sample(int(i), np.random.default_rng(
                (args.seed, epoch, int(i)))) for i in ids]
            images = np.stack([s["images"] for s in samples])
            labels = np.stack([s["labels"] for s in samples])
            params, batch_stats, opt_state, loss, state_ok = step(
                params, batch_stats, opt_state, images, labels)
            if b % 5 == 0:
                print(f"{time.ctime()} Epoch: {epoch} Sample {b}/"
                      f"{steps_per_epoch} Loss: "
                      f"{float(jax.device_get(loss)):.4f}")
            # disarm AFTER the cadence sync above: step() returns at
            # dispatch (async), so the device_get is where a wedged
            # computation actually blocks — it must happen inside the
            # armed region or the watchdog guards nothing
            wd.disarm()

        state = TrainState(step=jnp.int32((epoch + 1) * steps_per_epoch),
                           params=params, batch_stats=batch_stats,
                           opt_state=opt_state, rng=rng)
        # epoch-end divergence guard: the last-batch loss catches poison
        # introduced BEFORE that batch's update; state_ok (computed on
        # the post-update state inside the step) catches the final
        # batch's own update poisoning the state the save would persist
        # ONE explicit epoch-end fetch (jaxlint JL007: device_get makes
        # the sync visible and transfer-guard-clean), reused everywhere
        loss_h = float(jax.device_get(loss))
        ok_h = bool(jax.device_get(state_ok))
        if not args.no_guard and guard.poisoned(loss_h, ok_h):
            rollback_msg = guard.consume_rollback(
                loss_h, ok_h, f"epoch {epoch}", last_saved,
                ckpt_dir=args.checkpoint)
            prev = ckpt_io.restore_checkpoint(args.checkpoint, state,
                                              step=last_saved)
            params, batch_stats, opt_state = (
                prev.params, prev.batch_stats, prev.opt_state)
            print(f"[guard] poisoned epoch {epoch} (loss {loss_h:.4g}, "
                  f"state_finite={ok_h}); {rollback_msg}")
            continue
        ckpt_io.save_checkpoint(args.checkpoint, state)
        last_saved = int(state.step)
        print(f"Epoch {epoch}: checkpoint -> {args.checkpoint}")


def test(args) -> None:
    from dexiraft_tpu.train import checkpoint as ckpt_io

    info = DATASET_INFO[args.dataset]
    # registry eval resolutions: one static shape -> one jit compile, and
    # the reference's per-dataset test protocol (datasets.py:9-149)
    dataset = TestDataset(args.data_root, img_height=info.img_height,
                          img_width=info.img_width, mean_bgr=info.mean_bgr,
                          test_list=info.test_list)

    model = DexiNed(upconv=args.upconv)
    step = ckpt_io.latest_step(args.checkpoint)
    if step is None:
        raise FileNotFoundError(f"no checkpoint under {args.checkpoint}")
    # restore the raw tree (params + batch_stats suffice for inference)
    # through the cached-manager path the trainers use: a fresh ad-hoc
    # CheckpointManager cannot infer the saved item's handler (orbax
    # KeyError on 'default') and would race a pending async flush
    restored = ckpt_io.restore_raw(args.checkpoint, step)
    variables = {"params": restored["params"],
                 "batch_stats": restored.get("batch_stats", {})}

    @jax.jit
    def forward(images):
        preds = model.apply(variables, images, train=False)
        return jnp.stack([jax.nn.sigmoid(p) for p in preds])  # (7,B,H,W,1)

    total, times = 0, []
    counts, gt_missing = [], []
    for i in range(len(dataset)):
        s = dataset.sample(i)
        t0 = time.perf_counter()
        probs = np.asarray(jax.block_until_ready(
            forward(s["images"][None])))
        probs2 = None
        if args.test_pich:
            # second forward on the channel-swapped image (main.py:172-174)
            probs2 = np.asarray(jax.block_until_ready(
                forward(s["images"][None][..., ::-1])))
        dt = time.perf_counter() - t0
        times.append(dt)
        fused = probs[-1]
        save_test_outputs(probs, probs2, [s["file_name"]],
                          [s["image_shape"]],
                          osp.join(args.output_dir, args.dataset))
        save_edge_maps(fused, [s["file_name"]], [s["image_shape"]],
                       osp.join(args.output_dir, args.dataset))
        if args.gt_root:
            import cv2

            from dexiraft_tpu.dexined.metrics import edge_counts

            stem = osp.splitext(s["file_name"])[0]
            gt = cv2.imread(osp.join(args.gt_root, stem + ".png"),
                            cv2.IMREAD_GRAYSCALE)
            if gt is None:
                gt_missing.append(s["file_name"])
            else:
                # score at the GT's native resolution: upsample the
                # probability map rather than downscaling the GT, which
                # would interpolate away its 1-px edges
                pred_full = cv2.resize(fused[0, ..., 0],
                                       (gt.shape[1], gt.shape[0]))
                # streaming: only the (T, 4) counts are kept per image
                counts.append(edge_counts(pred_full, gt > 127,
                                          matching=args.matching))
        total += 1
        print(f"{s['file_name']}: {dt * 1e3:.1f} ms")
    if times:
        print(f"Mean inference time over {total} images "
              f"(first excluded): {np.mean(times[1:] or times) * 1e3:.1f} ms")
    if args.gt_root:
        if gt_missing:
            print(f"[metrics] WARNING: no GT found for {len(gt_missing)}/"
                  f"{total} images (e.g. {gt_missing[0]!r}) under "
                  f"{args.gt_root}")
        if counts:
            from dexiraft_tpu.dexined.metrics import evaluate_from_counts

            res = evaluate_from_counts(counts)
            print(f"ODS: {res['ODS']:.4f}  OIS: {res['OIS']:.4f}  "
                  f"AP: {res['AP']:.4f}  ({len(counts)} images)")
        else:
            print(f"[metrics] no GT matched under {args.gt_root}; "
                  "expected <gt_root>/<image stem>.png")


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    if not (args.train or args.test):
        raise SystemExit("need --train or --test")
    if args.train:
        train(args)
    if args.test:
        test(args)


if __name__ == "__main__":
    main(sys.argv[1:])
