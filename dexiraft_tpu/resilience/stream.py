"""Data-stream position: the missing half of an exact resume.

The full TrainState already round-trips through checkpoints (params,
optimizer, schedule step, PRNG key), but the DATA stream restarted from
epoch 0 on every --resume: `Loader.batches(start_epoch=)` existed and
was never wired, and there was no intra-epoch offset at all. Because the
loader's shuffle and augmentation are counter-based PRNG streams keyed
on (seed, epoch, index), the whole sample sequence is a pure function of
(seed, epoch, batch-offset) — so resuming the exact sequence only needs
these two integers saved next to each checkpoint.

The position is stored as a JSON sidecar `<ckpt_dir>/stream/<step>.json`
rather than inside the orbax pytree: it must stay readable by humans and
by older/newer code, must not change the checkpoint tree structure (old
checkpoints keep restoring), and is deleted in lockstep by the retention
GC. A checkpoint without a sidecar resumes from epoch 0 — exactly the
pre-sidecar behavior, so old checkpoint dirs keep working.
"""

from __future__ import annotations

import dataclasses
import json
import os
import os.path as osp
from typing import Optional


class LoaderKindMismatch(ValueError):
    """--resume would swap the data plane under a run: the sidecar was
    written by one loader kind (raw files vs packed records) and the
    resuming process is using the other — or the same records kind but
    a DIFFERENT pack (manifest fingerprint changed: repacked tree,
    different mixture selector, different crop recipe). Refused loudly;
    a silent swap is exactly the kind of sequence divergence
    exact-resume exists to prevent."""


@dataclasses.dataclass(frozen=True)
class StreamPosition:
    """Position of the NEXT global batch to consume."""

    epoch: int = 0
    offset: int = 0  # global-batch index within the epoch

    def advance(self, batches: int, batches_per_epoch: int) -> "StreamPosition":
        """Position after consuming `batches` more global batches."""
        if batches_per_epoch <= 0:
            raise ValueError(
                f"batches_per_epoch must be positive, got {batches_per_epoch}")
        absolute = self.epoch * batches_per_epoch + self.offset + batches
        return StreamPosition(absolute // batches_per_epoch,
                              absolute % batches_per_epoch)


def _sidecar_path(directory: str, step: int) -> str:
    return osp.join(directory, "stream", f"{int(step)}.json")


def save_position(directory: str, step: int, pos: StreamPosition,
                  seed: Optional[int] = None,
                  loader_kind: Optional[str] = None,
                  fingerprint: Optional[str] = None) -> str:
    """Atomically write the position sidecar for checkpoint `step`.

    loader_kind ("raw" | "records") records which data plane produced
    the stream, so --resume can refuse a raw<->records swap;
    fingerprint (the pack's manifest fingerprint, records runs only)
    additionally refuses a records-to-DIFFERENT-records swap."""
    path = _sidecar_path(directory, step)
    os.makedirs(osp.dirname(path), exist_ok=True)
    record = {"epoch": int(pos.epoch), "offset": int(pos.offset)}
    if seed is not None:
        record["seed"] = int(seed)
    if loader_kind is not None:
        record["loader_kind"] = str(loader_kind)
    if fingerprint is not None:
        record["fingerprint"] = str(fingerprint)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f)
    os.replace(tmp, path)
    return path


def load_position(directory: str, step: int,
                  seed: Optional[int] = None,
                  loader_kind: Optional[str] = None,
                  fingerprint: Optional[str] = None
                  ) -> Optional[StreamPosition]:
    """Read the sidecar for `step`; None when absent/unreadable (resume
    then starts at epoch 0, the pre-sidecar behavior). A seed recorded
    at save time that differs from the current one gets a loud warning —
    the sequence being resumed is then NOT the one that was running.
    A loader_kind recorded at save time that differs from the current
    one raises LoaderKindMismatch: a raw<->records swap mid-run is an
    operator error, not a degradation to absorb. Old sidecars without
    the field (pre-records checkpoints) resume unconditionally."""
    try:
        with open(_sidecar_path(directory, step)) as f:
            record = json.load(f)
        pos = StreamPosition(int(record["epoch"]), int(record["offset"]))
    except (OSError, ValueError, KeyError):
        return None
    saved_kind = record.get("loader_kind")
    if (loader_kind is not None and saved_kind is not None
            and saved_kind != loader_kind):
        fix = ("pass the matching --records_dir"
               if saved_kind == "records" else "drop --records_dir")
        raise LoaderKindMismatch(
            f"checkpoint step {step} was saved by the {saved_kind!r} "
            f"loader but this run uses the {loader_kind!r} loader — "
            f"resuming would follow a different sample sequence; {fix} "
            f"or start fresh without --resume")
    saved_fp = record.get("fingerprint")
    if (fingerprint is not None and saved_fp is not None
            and saved_fp != fingerprint):
        raise LoaderKindMismatch(
            f"checkpoint step {step} was saved from a records pack with "
            f"fingerprint {saved_fp[:12]} but --records_dir points at a "
            f"pack with fingerprint {fingerprint[:12]} — a repacked or "
            f"different dataset would follow a different sample "
            f"sequence; point --records_dir at the original pack or "
            f"start fresh without --resume")
    saved_seed = record.get("seed")
    if seed is not None and saved_seed is not None and saved_seed != seed:
        print(f"[resilience] WARNING: checkpoint step {step} was saved with "
              f"data seed {saved_seed}, resuming with seed {seed} — the "
              f"sample sequence will differ from the interrupted run")
    return pos


def delete_position(directory: str, step: int) -> None:
    """Drop the sidecar (retention GC calls this next to the step delete)."""
    try:
        os.remove(_sidecar_path(directory, step))
    except OSError:
        pass
