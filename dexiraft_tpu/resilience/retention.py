"""Checkpoint retention: bounded disk growth without losing the best run.

val_freq=5000 over a 100k-step stage leaves 20 full-state checkpoints
(~1 GB each for the big model) per stage, unboundedly across stages —
the reference had the same behavior and nobody GC'd by hand. The policy
here is the standard pair:

  * keep the newest `keep` steps (0 = keep everything, the old
    behavior and the default);
  * with `keep_best`, ALSO keep the step with the best (lowest)
    recorded validation score (EPE) even when it ages out of the window.

`apply` never deletes a protected step (the trainer protects its
current rollback target: the guard must always have somewhere to land),
and deletes the stream-position sidecar in lockstep with each step.

Scores must outlive the process: --keep_best is a promise about a
MULTI-restart run (that's what preemption recovery means), so a policy
bound to a checkpoint directory persists its scores to
`<dir>/retention.json` on every update and reloads them on
construction — a resumed run still knows which old step was the best.
"""

from __future__ import annotations

import json
import os
import os.path as osp
from typing import Dict, Iterable, List, Optional

from dexiraft_tpu.resilience.stream import delete_position
from dexiraft_tpu.train import checkpoint as ckpt


class RetentionPolicy:
    def __init__(self, keep: int = 0, keep_best: bool = False,
                 directory: Optional[str] = None):
        if keep < 0:
            raise ValueError(f"keep must be >= 0, got {keep}")
        self.keep = keep
        self.keep_best = keep_best
        self.directory = directory
        self.scores: Dict[int, float] = self._load()

    def _scores_path(self) -> str:
        return osp.join(self.directory, "retention.json")

    def _load(self) -> Dict[int, float]:
        if self.directory is None:
            return {}
        try:
            with open(self._scores_path()) as f:
                return {int(k): float(v) for k, v in json.load(f).items()}
        except (OSError, ValueError):
            return {}

    def _persist(self) -> None:
        if self.directory is None:
            return
        path = self._scores_path()
        os.makedirs(osp.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({str(k): v for k, v in self.scores.items()}, f)
        os.replace(tmp, path)

    def note_score(self, step: int, score: float) -> None:
        """Record a validation score (lower = better) for `step`."""
        self.scores[int(step)] = float(score)
        self._persist()

    def best_step(self) -> Optional[int]:
        if not self.scores:
            return None
        return min(self.scores, key=self.scores.get)

    def apply(self, directory: str,
              protect: Iterable[int] = ()) -> List[int]:
        """GC steps outside the policy; returns the deleted steps."""
        if self.keep <= 0:
            return []
        steps = ckpt.all_steps(directory)
        keep_set = set(steps[-self.keep:]) | {int(s) for s in protect
                                              if s is not None}
        if self.keep_best:
            best = self.best_step()
            if best is not None:
                keep_set.add(best)
        doomed = [s for s in steps if s not in keep_set]
        for s in doomed:
            ckpt.delete_step(directory, s)
            delete_position(directory, s)
            self.scores.pop(s, None)
        if doomed:
            self._persist()
            print(f"[retention] deleted step(s) {doomed} from {directory} "
                  f"(keep={self.keep}"
                  + (f", best={self.best_step()}" if self.keep_best else "")
                  + ")")
        return doomed
