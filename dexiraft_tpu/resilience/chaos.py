"""Fault-injection harness: prove the recovery paths recover.

Every resilience claim in this package is tested by injecting the real
fault, not a mock of its symptom: corrupt samples RAISE from decode,
worker death actually `os._exit`s a pool process (breaking the
executor), SIGTERM is a real signal through the real handler, and
checkpoint truncation damages the real files orbax wrote. Used by
tests/test_zzresilience*.py and scripts/chaos_smoke.py.

Module constraints: importable without jax (dataset wrappers are
shipped to SPAWNED process-pool workers, which must not pay a jax init
just to decode numpy batches) and everything picklable from module
scope for the same reason.
"""

from __future__ import annotations

import os
import os.path as osp
import signal
from typing import Callable, Dict, Iterable, Optional

import numpy as np


class ChaosError(RuntimeError):
    """The injected decode failure (distinct type, so tests can tell an
    injected fault from a genuine bug in the recovery path)."""


class SyntheticFlowDataset:
    """Tiny in-memory FlowDataset stand-in: deterministic samples from
    counter-based PRNG keyed on (seed, index) — no files, no augmentor,
    picklable, so loader-level chaos tests stay CPU-cheap."""

    def __init__(self, n: int = 16, size=(32, 48), seed: int = 0):
        self.n = n
        self.size = tuple(size)
        self.seed = seed

    def __len__(self) -> int:
        return self.n

    def sample(self, index: int,
               rng: Optional[np.random.Generator] = None) -> Dict[str, np.ndarray]:
        h, w = self.size
        gen = np.random.default_rng((self.seed, int(index)))
        img1 = gen.uniform(0, 255, (h, w, 3)).astype(np.float32)
        img2 = gen.uniform(0, 255, (h, w, 3)).astype(np.float32)
        flow = gen.normal(size=(h, w, 2)).astype(np.float32)
        return {"image1": img1, "image2": img2, "flow": flow,
                "valid": np.ones((h, w), np.float32)}

    __getitem__ = sample


class CorruptSampleDataset:
    """Decode of the chosen indices raises — a corrupt PNG/flo in spirit.

    fail_times bounds failures PER WORKER (attempt counters live in the
    decoding process): None = the sample is permanently corrupt (the
    skip-and-count path); k = transient, succeeds on retry k+1 (the
    retry-with-backoff path — use thread workers, where one counter sees
    every attempt).
    """

    def __init__(self, base, bad_indices: Iterable[int],
                 fail_times: Optional[int] = None):
        self.base = base
        self.bad = set(int(i) for i in bad_indices)
        self.fail_times = fail_times
        self._attempts: Dict[int, int] = {}

    def __len__(self) -> int:
        return len(self.base)

    def sample(self, index: int, rng=None):
        index = int(index)
        if index in self.bad:
            n = self._attempts.get(index, 0)
            if self.fail_times is None or n < self.fail_times:
                self._attempts[index] = n + 1
                raise ChaosError(f"chaos: corrupt sample {index} "
                                 f"(attempt {n + 1})")
        return self.base.sample(index, rng)

    __getitem__ = sample


class WorkerDeathDataset:
    """Decoding the chosen indices hard-kills the decode process.

    PROCESS worker_mode only: in thread mode os._exit would take the
    whole trainer down (which is the point — this simulates a pool
    worker segfaulting/OOM-killed, not a decode exception). Each index
    kills at most once, coordinated through a sentinel file in
    `sentinel_dir` (worker processes share no memory and are REBUILT
    after the pool breaks, so in-process counters cannot carry the
    "already died" fact across the rebuild).
    """

    def __init__(self, base, die_indices: Iterable[int], sentinel_dir: str):
        self.base = base
        self.die = set(int(i) for i in die_indices)
        self.sentinel_dir = sentinel_dir

    def __len__(self) -> int:
        return len(self.base)

    def sample(self, index: int, rng=None):
        index = int(index)
        if index in self.die:
            try:
                # atomic claim: exactly one attempt per index dies
                fd = os.open(osp.join(self.sentinel_dir, f"die_{index}"),
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.close(fd)
                os._exit(3)
            except FileExistsError:
                pass  # this index already killed a worker once; decode
        return self.base.sample(index, rng)

    __getitem__ = sample


def parse_spec(spec: str) -> Callable[[int], None]:
    """Parse a --chaos spec into a per-step callback for the train loop.

    Grammar:
      "sigterm@N" — after step N completes, send the process a real
      SIGTERM (once). The signal flows through the installed
      PreemptionHandler exactly as an external `kill -TERM` would, which
      is what makes the emergency-save tests deterministic: the stop
      step is pinned without racing a timer against compile time.
      "kill_mid_flush@N" — after step N completes, arm the checkpoint
      module so the NEXT async save os._exit()s while its flush is in
      flight: a real crash mid-serialize, leaving an uncommitted
      orbax tmp dir. The step's save never commits; the run's previous
      committed step must remain the verified-restorable latest
      (scripts/chaos_smoke.py kill-during-flush phase).
    """
    kind, _, arg = spec.partition("@")
    if kind == "sigterm":
        at = int(arg)
        fired = [False]

        def fire(step: int) -> None:
            if not fired[0] and step >= at:
                fired[0] = True
                os.kill(os.getpid(), signal.SIGTERM)

        return fire
    if kind == "kill_mid_flush":
        at = int(arg)
        armed = [False]

        def arm(step: int) -> None:
            if not armed[0] and step >= at:
                armed[0] = True
                # deferred import: this module ships to jax-free decode
                # workers; the trainer process firing the spec has jax
                from dexiraft_tpu.train import checkpoint as ckpt_io

                ckpt_io.chaos_kill_next_flush()

        return arm
    raise ValueError(f"unknown chaos spec {spec!r} "
                     f"(supported: sigterm@N, kill_mid_flush@N)")


def truncate_checkpoint(directory: str, step: int) -> "list[str]":
    """Damage a saved step the way a mid-write preemption does: the
    largest file under <directory>/<step>/ is truncated to half. Returns
    the damaged paths (empty = nothing large enough to damage)."""
    step_dir = osp.join(directory, str(int(step)))
    if not osp.isdir(step_dir):
        raise FileNotFoundError(f"no step dir {step_dir}")
    files = []
    for root, _, names in os.walk(step_dir):
        for name in names:
            p = osp.join(root, name)
            files.append((os.path.getsize(p), p))
    files.sort(reverse=True)
    damaged = []
    for size, path in files[:1]:
        if size < 2:
            continue
        with open(path, "r+b") as f:
            f.truncate(size // 2)
        damaged.append(path)
    return damaged
