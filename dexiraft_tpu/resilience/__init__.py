"""Resilience layer: the runtime survives what the reference died from.

The reference's documented failure mode was SILENT (SURVEY.md §5): a
diverged run kept logging, and outages killed runs that were restarted
by hand with a cold optimizer. PR 1-3 fixed divergence (guard +
rollback + full-state checkpoints); this package closes the remaining
ways a multi-day run dies or silently degrades:

  * preemption.PreemptionHandler — SIGTERM/SIGINT become ONE graceful
    emergency checkpoint instead of losing up to val_freq steps.
  * stream.StreamPosition — the data-stream position (epoch, batch
    offset) is checkpointed alongside the train state, so --resume
    continues the EXACT sample sequence instead of replaying epoch 0.
    The sidecar also records which data plane was feeding the run
    (loader_kind raw|records): resuming under the other one raises
    LoaderKindMismatch instead of silently changing the sequence. On
    the packed-record plane (data.records) the resumed position is an
    O(1) shard-index seek, not a re-decode.
  * verify.restore_verified — restore-time integrity check (tree
    structure + leaf shapes + finiteness sample) with fallback to the
    previous step: a truncated or poisoned checkpoint degrades to an
    older one with a clear message, never a crash or silent garbage.
  * retention.RetentionPolicy — --keep N / --keep_best GC so
    checkpoints stop accumulating unboundedly.
  * chaos — fault-injection harness (corrupt samples, worker death,
    SIGTERM mid-step, truncated checkpoints) that the tests and
    scripts/chaos_smoke.py use to prove every recovery path recovers.

Pod-grade additions (multi-host failure handling):

  * coord.Coordinator — host-consensus primitives (any_flag / min_int /
    agree_step): a NaN or preemption notice on ANY host becomes the
    SAME verdict (and the same rollback/emergency-save step) on ALL
    hosts; single-process runs degrade to the identity.
  * watchdog.HangWatchdog — armed around each step/collective region; a
    stall past the timeout dumps step index + live stacks and exits
    nonzero instead of hanging a pod forever. Step-time EWMA straggler
    warnings ride the same timer. Under --elastic the first stall
    verdict is handed to the membership runtime (one reconfiguration
    attempt) before the exit-98 fallback.
  * analysis.collective_trace (re-exported: CollectiveDivergence) —
    the collective flight recorder: every consensus round, membership
    epoch, and checkpoint barrier is stamped (namespace, round, op,
    digest) into a bounded per-host ring, and the Coordinator's in-band
    lockstep check raises CollectiveDivergence naming the FIRST
    divergent (host, round, op) the moment two hosts' sequences split —
    a one-line diagnosis in seconds instead of a CoordinatorTimeout
    after the full window. The watchdog dumps the ring's tail next to
    its faulthandler stacks; distlint (JL030+) is the static half.
  * membership.MembershipRuntime — elastic pod membership: epoch-
    numbered worlds over the KV store with per-host heartbeat leases.
    A lost host becomes a shrink-and-continue reconfiguration (new
    epoch, smaller mesh, agreed-step restore, re-sliced data stream)
    instead of a job restart; a replacement host posts a join intent
    on the FileBoard and is absorbed at the next checkpoint boundary.
    ElasticFallback marks the cases that still need the old exit-98
    contract (rank-0 loss, cascade below --min_hosts).

The data-pipeline half (bounded retry-with-backoff, skip-and-count,
decode-pool rebuild) lives in data.loader — PipelineStats is re-exported
here for the one-stop import.
"""

from dexiraft_tpu.analysis.collective_trace import CollectiveDivergence
from dexiraft_tpu.data.loader import PipelineStats
from dexiraft_tpu.resilience.coord import Coordinator, CoordinatorTimeout
from dexiraft_tpu.resilience.membership import (
    ElasticConfig,
    ElasticFallback,
    EpochInfo,
    FileBoard,
    MembershipRuntime,
    ReconfigureNeeded,
)
from dexiraft_tpu.resilience.preemption import PreemptionHandler
from dexiraft_tpu.resilience.watchdog import STALL_EXIT_CODE, HangWatchdog
from dexiraft_tpu.resilience.retention import RetentionPolicy
from dexiraft_tpu.resilience.stream import (
    LoaderKindMismatch,
    StreamPosition,
    delete_position,
    load_position,
    save_position,
)
from dexiraft_tpu.resilience.verify import (
    CheckpointIntegrityError,
    clean_uncommitted,
    prune_steps_above,
    restore_verified,
    uncommitted_flushes,
    verify_state,
)

__all__ = [
    "CheckpointIntegrityError",
    "CollectiveDivergence",
    "Coordinator",
    "CoordinatorTimeout",
    "ElasticConfig",
    "ElasticFallback",
    "EpochInfo",
    "FileBoard",
    "HangWatchdog",
    "MembershipRuntime",
    "ReconfigureNeeded",
    "LoaderKindMismatch",
    "PipelineStats",
    "PreemptionHandler",
    "RetentionPolicy",
    "STALL_EXIT_CODE",
    "StreamPosition",
    "clean_uncommitted",
    "delete_position",
    "load_position",
    "prune_steps_above",
    "restore_verified",
    "save_position",
    "uncommitted_flushes",
    "verify_state",
]
