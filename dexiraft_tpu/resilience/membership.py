"""Elastic pod membership: shrink-and-continue, grow-at-checkpoint.

Losing 1/64 hosts used to cost the whole job: the watchdog bounded the
hang and exited 98, the orchestrator cold-restarted every host, and the
pod re-paid init + compile + restore. Every mechanism needed to do
better already exists in this tree — KV-store consensus
(resilience.coord), template-driven sharded restore that reshards
across mesh shapes (train.checkpoint), centralized mesh construction
(parallel.layout.make_train_mesh), the pinned epoch_permutation
data-order contract, and the exact-resume stream sidecars
(resilience.stream). This module composes them into the standard
large-pod resilience pattern: membership EPOCHS.

An epoch is one fixed world: (epoch number, member set, coordinator
address, jax.distributed runtime at that size). All coordination state
is namespaced by epoch — leases under ``dexiraft/elastic/e{E}/``,
consensus under ``dexiraft/coord/e{E}`` (:meth:`MembershipRuntime
.coord_namespace`) — so a straggler's stale keys from epoch E can
never pollute epoch E+1's rounds. Within an epoch each host holds a
heartbeat LEASE: a tiny monotonic counter re-published to the KV store
every ``lease_interval_s`` by a daemon thread that also probes every
peer's counter. A counter that stops advancing for ``lease_timeout_s``
is a missed lease — the host is dead, wedged, or partitioned — and
:meth:`MembershipRuntime.poll` turns it into a typed verdict:

  * :class:`ReconfigureNeeded` — survivors can re-form without the
    suspect(s): run :meth:`MembershipRuntime.reconfigure`.
  * :class:`ElasticFallback` — reconfiguration is impossible (the
    epoch's rank 0 — the host carrying the coordination service — is
    the casualty, the surviving set would fall below ``min_hosts``, or
    the new world cannot slice the global batch): exit 98 and let the
    orchestrator restart, exactly the pre-elastic behavior.

Reconfiguration (shrink) runs entirely over the OLD epoch's still-live
KV store: every survivor posts an ``alive`` key, waits bounded-time for
every non-suspect peer (a peer can be stuck in a collective against
the dead host until its own op timeout — set ``reconfig_timeout_s``
above ``--coord_timeout_s``), and the tentative member set is then
CONFIRMED by a consensus round (coord.min_int/any_flag over a hash of
the sorted plan, in a plan-sized Coordinator under the epoch's
``confirm`` namespace): any disagreement — a straggler that revived
late, a partition that healed mid-round — downgrades to
ElasticFallback rather than risking split-brain. Only then does the
irreversible part start: checkpoint machinery abandoned without
barriers (train.checkpoint.reset_managers — a zombie flush against the
dead host must not be waited on), the distributed runtime torn down
dead-peer-safe (parallel.distributed.elastic_teardown), and epoch E+1
initialized at the new size on a NEW port (``port_base + E+1`` on the
new rank 0's host, so a half-dead straggler still bound to the old
port can never be mistaken for a member). The caller (train_cli's
elastic segment loop, or the test child) then re-forms the mesh from
layout.make_train_mesh over the new world, re-restores the agreed step
through coord.agree_step onto the NEW template's resolved shardings,
prunes any step a zombie flush may commit above the agreement
(resilience.verify.prune_steps_above), re-slices the data stream at
the new host count from the agreed (epoch, offset) sidecar, and
continues. Seconds, not a job restart.

Growth is symmetric and cheaper: a replacement host posts a join
intent on the :class:`FileBoard` (a filesystem rendezvous under the
shared checkpoint directory — the one channel that exists BEFORE a
joiner has any KV access), incumbents observe it at the next
checkpoint boundary (a collective any_flag decision, so every
incumbent reconfigures at the same step), rank 0 assigns the joiners
ranks above the incumbents and announces epoch E+1 on the board, and
everyone — incumbents gracefully torn down, joiners fresh — meets in
the new, larger world. The joiner restores the same agreed checkpoint
step as everyone else; nothing restarts.

Why the board AND the KV store: the KV store dies with its epoch (and
with rank 0), so it cannot carry cross-epoch state; the board is
durable but has no ordering guarantees, so it carries only rendezvous
facts (the latest epoch announcement, pending join intents) — never
votes. Votes happen in exactly one place, the confirm round.

The jax.distributed runtime this rides must never beat the leases to a
verdict: elastic worlds are initialized with effectively-disabled
coordination-service heartbeats (parallel.distributed.elastic_initialize
has the full story, including why the missed-heartbeat callback cannot
be used on this jaxlib), making the lease the ONLY failure detector —
one detector, one timeout, one reconfiguration policy.
"""

from __future__ import annotations

import dataclasses
import json
import os
import os.path as osp
import threading
import time
import zlib
from typing import Dict, List, Optional, Set, Tuple

from dexiraft_tpu.analysis import collective_trace
from dexiraft_tpu.analysis.locks import OrderedLock
from dexiraft_tpu.resilience.coord import Coordinator

_ELASTIC_NS = "dexiraft/elastic"


class ReconfigureNeeded(RuntimeError):
    """A membership change is required and possible: ``dead`` holds the
    suspected member indices (empty for a stall-verdict re-form at the
    same size). Raised by :meth:`MembershipRuntime.poll`; the caller
    pauses at the step boundary and runs
    :meth:`MembershipRuntime.reconfigure`."""

    def __init__(self, reason: str, dead: Optional[Set[int]] = None):
        self.reason = reason
        self.dead = set(dead or ())
        super().__init__(
            f"membership reconfiguration needed: {reason}"
            + (f" (suspect member(s) {sorted(self.dead)})"
               if self.dead else ""))


class ElasticFallback(RuntimeError):
    """Elastic recovery is not possible from here; the caller falls back
    to the watchdog's exit-98-and-restart contract (the orchestrator
    restarts the whole pod)."""


@dataclasses.dataclass(frozen=True)
class EpochInfo:
    """One installed membership epoch — what the caller re-forms from."""

    epoch: int
    size: int
    index: int
    coordinator_address: str


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """Knobs for the membership runtime.

    ``host`` is THIS host's address as peers should dial it (the new
    coordination service binds here when this host becomes an epoch's
    rank 0). ``board_dir`` must be on storage every member AND every
    future joiner can reach — the checkpoint directory's filesystem is
    the natural choice. ``global_batch`` (when known) lets shrink
    refuse a world that cannot slice the batch BEFORE tearing anything
    down. ``reconfig_timeout_s`` must exceed the consensus timeout
    (``--coord_timeout_s``): a survivor may legitimately arrive at the
    reconfiguration round only after its in-flight consensus op times
    out against the dead peer."""

    host: str
    board_dir: str
    min_hosts: int = 1
    global_batch: Optional[int] = None
    lease_interval_s: float = 0.5
    lease_timeout_s: float = 4.0
    probe_timeout_s: float = 1.0
    reconfig_timeout_s: float = 30.0
    join_poll_s: float = 0.5
    join_timeout_s: float = 300.0
    stall_grace_s: float = 60.0
    init_timeout_s: int = 60


# --------------------------------------------------------------------------
# FileBoard — the cross-epoch rendezvous (see module docstring)
# --------------------------------------------------------------------------


class FileBoard:
    """Filesystem rendezvous: epoch announcements + join intents.

    Every write is atomic (tmp + rename on the same filesystem), every
    read tolerates absence — the board carries FACTS a reader polls
    for, never votes. Lives under a directory all members and joiners
    share (conventionally ``<ckpt_dir>/membership``)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(osp.join(directory, "join"), exist_ok=True)

    # -- epoch announcements (rank 0 writes, everyone reads) ------------
    def announce_epoch(self, epoch: int, coordinator_address: str,
                       size: int, join_ranks: Dict[str, int]) -> None:
        record = {"epoch": int(epoch),
                  "coordinator_address": coordinator_address,
                  "size": int(size),
                  "join_ranks": {str(k): int(v)
                                 for k, v in join_ranks.items()}}
        path = osp.join(self.directory, "epoch.json")
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, path)

    def read_epoch(self) -> Optional[dict]:
        try:
            with open(osp.join(self.directory, "epoch.json")) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    # -- join intents (joiners write, incumbents read/clear) ------------
    def post_join(self, name: str, host: str) -> None:
        path = osp.join(self.directory, "join", f"{name}.json")
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"name": str(name), "host": str(host)}, f)
        os.replace(tmp, path)

    def list_joins(self) -> List[dict]:
        """Pending join intents, sorted by name (the rank-assignment
        order, so every incumbent derives the same plan)."""
        join_dir = osp.join(self.directory, "join")
        try:
            names = sorted(n for n in os.listdir(join_dir)
                           if n.endswith(".json"))
        except OSError:
            return []
        records = []
        for n in names:
            try:
                with open(osp.join(join_dir, n)) as f:
                    records.append(json.load(f))
            except (OSError, ValueError):
                continue  # half-written intent: next boundary picks it up
        return records

    def clear_joins(self, names: List[str]) -> None:
        for name in names:
            try:
                os.remove(osp.join(self.directory, "join", f"{name}.json"))
            except OSError:
                pass


# --------------------------------------------------------------------------
# MembershipRuntime
# --------------------------------------------------------------------------


class MembershipRuntime:
    """Epoch-numbered membership over the jax.distributed KV store.

    Lifecycle: :meth:`bootstrap` (initial members) or :meth:`join`
    (replacement hosts) installs epoch 0 / the announced epoch; the
    training loop calls :meth:`poll` at its consensus cadence and
    :meth:`reconfigure` when poll (or a CoordinatorTimeout from a
    consensus op) says the world changed; :meth:`absorb_joins` runs at
    checkpoint boundaries. ``events`` accumulates one record per
    reconfiguration — kind, epoch, member plan, and ``recovery_s``
    (verdict-to-new-world wall time, the number the chaos-smoke phase
    compares against the exit-98-and-restart baseline)."""

    def __init__(self, config: ElasticConfig):
        self.config = config
        self.board = FileBoard(config.board_dir)
        self.epoch = -1
        self.size = 0
        self.index = -1
        self.coordinator_address = ""
        self._port_base: int = 0
        self.events: "list[dict]" = []
        self._lock = OrderedLock("resilience.membership.state")
        self._suspects: Set[int] = set()
        self._coordinator_lost: Optional[str] = None
        self._lease_stop = threading.Event()
        self._lease_thread: Optional[threading.Thread] = None
        self._stall_verdict: Optional[Tuple[int, str]] = None

    # -- lifecycle -------------------------------------------------------
    def bootstrap(self, coordinator_address: str, size: int,
                  index: int) -> EpochInfo:
        """Install epoch 0 for an initial member. The epoch-0 address
        doubles as the port base: epoch E's coordination service binds
        ``port_base + E`` so no stale listener is ever redialed."""
        self._port_base = int(coordinator_address.rsplit(":", 1)[1])
        return self._install_epoch(0, coordinator_address, size, index,
                                   announce_joins={})

    def join(self, name: str) -> EpochInfo:
        """Replacement-host entry: post the intent, wait for the epoch
        announcement that assigns this name a rank, and enter that
        world. The checkpoint-boundary cadence of absorption is the
        incumbents' side (:meth:`absorb_joins`)."""
        self.board.post_join(name, self.config.host)
        deadline = time.monotonic() + self.config.join_timeout_s
        while True:
            record = self.board.read_epoch()
            if record and name in record.get("join_ranks", {}):
                break
            if time.monotonic() > deadline:
                raise ElasticFallback(
                    f"join intent '{name}' was not absorbed within "
                    f"{self.config.join_timeout_s:.0f}s — no incumbent "
                    f"reached a checkpoint boundary (or none is running "
                    f"--elastic)")
            time.sleep(self.config.join_poll_s)
        addr = record["coordinator_address"]
        self._port_base = (int(addr.rsplit(":", 1)[1])
                           - int(record["epoch"]))
        return self._install_epoch(
            int(record["epoch"]), addr, int(record["size"]),
            int(record["join_ranks"][name]), announce_joins=None)

    def close(self) -> None:
        """Stop the lease thread (teardown of the runtime itself is the
        caller's shutdown path — membership only ever replaces worlds,
        it does not own the final exit)."""
        self._stop_leases()

    # -- verdicts --------------------------------------------------------
    def poll(self) -> None:
        """Raise the current membership verdict, if any (called at the
        training loop's consensus cadence — cheap: one lock, no RPC;
        the RPCs live on the lease thread)."""
        with self._lock:
            lost = self._coordinator_lost
            suspects = set(self._suspects)
        if lost:
            raise ElasticFallback(
                f"epoch {self.epoch}: coordination KV store unreachable "
                f"({lost}) — the epoch's rank 0 host is gone and the "
                f"member set cannot be renegotiated without it")
        if suspects:
            if 0 in suspects:
                raise ElasticFallback(
                    f"epoch {self.epoch}: rank 0 (the coordination "
                    f"service host) missed its lease — survivors have "
                    f"no KV store to agree a new member set over")
            raise ReconfigureNeeded(
                f"epoch {self.epoch}: missed lease", dead=suspects)

    def notify_stall(self, step: int, region: str) -> float:
        """Watchdog handoff (HangWatchdog.on_stall): record the verdict
        and grant one grace window. The stalled main thread is expected
        to unblock via its own op timeout (CoordinatorTimeout at
        --coord_timeout_s) and reach reconfigure(); if it never does,
        the watchdog's second fire exits 98 as before."""
        with self._lock:
            self._stall_verdict = (int(step), str(region))
        print(f"[elastic] watchdog stall verdict at step {step} in "
              f"armed region '{region}' (epoch {self.epoch}) — holding "
              f"exit for one reconfiguration attempt", flush=True)
        return self.config.stall_grace_s

    def pending_joins(self) -> List[dict]:
        """Join intents awaiting absorption (checkpoint boundaries gate
        on any_flag(bool(...)) of this, so absorption is collective)."""
        return self.board.list_joins()

    def coord_namespace(self) -> str:
        """The consensus namespace for the CURRENT epoch: a fresh
        Coordinator namespace per epoch means stale round keys from a
        previous world can never collide with the new one's rounds."""
        return f"dexiraft/coord/e{self.epoch}"

    # -- reconfiguration -------------------------------------------------
    def reconfigure(self, dead: Optional[Set[int]] = None,
                    reason: str = "missed lease") -> EpochInfo:
        """Shrink (or same-size re-form) into epoch+1 without the dead
        members. Runs the full protocol from the module docstring;
        raises ElasticFallback when the new world is not viable or the
        survivors cannot agree. On return the jax.distributed runtime
        IS the new world — the caller re-forms mesh/state/stream."""
        t0 = time.monotonic()
        with self._lock:
            dead = set(dead or ()) | set(self._suspects)
            stall = self._stall_verdict
            self._stall_verdict = None
        if stall is not None:
            reason = (f"{reason}; stall in region '{stall[1]}' at step "
                      f"{stall[0]}")
        self._stop_leases()
        plan = self._agree_survivors(dead)
        self._check_viable(plan)
        new_rank = plan.index(self.index)
        new_host = self._host_of(plan[0])
        new_epoch = self.epoch + 1
        new_addr = f"{new_host}:{self._port_base + new_epoch}"
        print(f"[elastic] epoch {self.epoch} -> {new_epoch}: shrinking "
              f"{self.size} -> {len(plan)} members ({reason}); survivors "
              f"{plan}, new coordinator {new_addr}", flush=True)
        # flight-recorder stamp BEFORE the teardown: every survivor
        # records the same (epoch, plan) digest, so a host that agreed
        # a different plan shows up as the first divergent op
        collective_trace.record(
            _ELASTIC_NS, "reconfigure", round_id=new_epoch,
            digest=collective_trace.args_digest(new_epoch, tuple(plan)))
        self._teardown(graceful=False)
        info = self._install_epoch(new_epoch, new_addr, len(plan),
                                   new_rank, announce_joins={})
        recovery_s = time.monotonic() - t0
        self.events.append({"kind": "shrink", "epoch": new_epoch,
                            "members": plan, "reason": reason,
                            "recovery_s": recovery_s})
        print(f"[elastic] epoch {new_epoch} up: {len(plan)} member(s), "
              f"rank {new_rank}, recovery {recovery_s:.2f}s", flush=True)
        return info

    def absorb_joins(self) -> EpochInfo:
        """Grow into epoch+1 with every pending join intent (checkpoint
        boundary, ALL incumbents — the caller has already agreed
        collectively that joins are pending and all async saves are
        committed, so the graceful teardown's barriers are safe)."""
        t0 = time.monotonic()
        self._stop_leases()
        client = self._client()
        ens = self._ens()
        if self.index == 0:
            joins = self.board.list_joins()
            join_ranks = {j["name"]: self.size + k
                          for k, j in enumerate(joins)}
            plan_record = {"size": self.size + len(joins),
                           "join_ranks": join_ranks}
            client.key_value_set(f"{ens}/grow_plan",
                                 json.dumps(plan_record),
                                 allow_overwrite=True)
        else:
            # non-rank-0 incumbents take rank 0's plan verbatim: board
            # reads race with late intents, a KV value does not
            plan_record = json.loads(client.blocking_key_value_get(
                f"{ens}/grow_plan",
                int(self.config.reconfig_timeout_s * 1000)))
        new_size = int(plan_record["size"])
        join_ranks = plan_record["join_ranks"]
        self._check_viable(list(range(new_size)))
        new_epoch = self.epoch + 1
        new_addr = (f"{self._host_of(0)}:{self._port_base + new_epoch}")
        print(f"[elastic] epoch {self.epoch} -> {new_epoch}: growing "
              f"{self.size} -> {new_size} members (absorbing "
              f"{sorted(join_ranks)}), new coordinator {new_addr}",
              flush=True)
        # same digest on every incumbent: the grow plan is rank 0's KV
        # record verbatim, so a divergent absorption names itself
        collective_trace.record(
            _ELASTIC_NS, "absorb_joins", round_id=new_epoch,
            digest=collective_trace.args_digest(
                new_epoch, new_size, tuple(sorted(join_ranks))))
        self._teardown(graceful=True)
        info = self._install_epoch(new_epoch, new_addr, new_size,
                                   self.index, announce_joins=join_ranks)
        if info.index == 0:
            self.board.clear_joins(sorted(join_ranks))
        recovery_s = time.monotonic() - t0
        self.events.append({"kind": "grow", "epoch": new_epoch,
                            "members": list(range(new_size)),
                            "join_ranks": join_ranks,
                            "recovery_s": recovery_s})
        print(f"[elastic] epoch {new_epoch} up: {new_size} member(s), "
              f"rank {info.index}, recovery {recovery_s:.2f}s",
              flush=True)
        return info

    # -- internals -------------------------------------------------------
    def _ens(self, epoch: Optional[int] = None) -> str:
        return f"{_ELASTIC_NS}/e{self.epoch if epoch is None else epoch}"

    def _client(self):
        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:
            raise ElasticFallback(
                "no live distributed runtime (torn down but never "
                "re-initialized?) — cannot run membership protocol")
        return client

    def _host_of(self, member: int) -> str:
        """A member's dialable host, published at epoch install."""
        if member == self.index:
            return self.config.host
        return self._client().blocking_key_value_get(
            f"{self._ens()}/host/{member}",
            int(self.config.reconfig_timeout_s * 1000))

    def _check_viable(self, plan: List[int]) -> None:
        if len(plan) < self.config.min_hosts:
            raise ElasticFallback(
                f"new member set {plan} is below --min_hosts "
                f"{self.config.min_hosts} — cascading loss; restarting "
                f"the pod is the right call")
        gb = self.config.global_batch
        if gb is not None and gb % len(plan):
            raise ElasticFallback(
                f"global batch {gb} does not divide over {len(plan)} "
                f"host(s) — the data plane cannot re-slice to this "
                f"world (pick a batch size divisible by every member "
                f"count down to --min_hosts)")

    def _agree_survivors(self, dead: Set[int]) -> List[int]:
        """The shrink agreement round over the OLD epoch's KV store:
        post alive, collect peers bounded-time, confirm the plan hash
        by consensus. Returns the sorted agreed member list (old
        indices)."""
        client = self._client()
        ens = self._ens()
        try:
            client.key_value_set(f"{ens}/alive/{self.index}", "1",
                                 allow_overwrite=True)
        except Exception as e:
            raise ElasticFallback(
                f"cannot reach the epoch {self.epoch} KV store to post "
                f"liveness ({type(e).__name__}) — rank 0 is gone") \
                from None
        plan = [self.index]
        for i in range(self.size):
            if i == self.index:
                continue
            # suspects get one probe interval to contradict the lease
            # verdict; non-suspects may be stuck in a collective against
            # the dead host until their own op timeout, so they get the
            # full reconfiguration window to arrive
            timeout_s = (self.config.probe_timeout_s if i in dead
                         else self.config.reconfig_timeout_s)
            try:
                client.blocking_key_value_get(f"{ens}/alive/{i}",
                                              int(timeout_s * 1000))
                plan.append(i)
            except Exception as e:
                if "DEADLINE_EXCEEDED" not in str(e):
                    raise ElasticFallback(
                        f"epoch {self.epoch} KV store failed mid-"
                        f"agreement ({type(e).__name__}: "
                        f"{str(e)[:120]})") from None
        plan.sort()
        # confirm: every survivor must hold the IDENTICAL plan before
        # anything irreversible happens. min_int of the plan hash plus
        # any_flag of disagreement is exactly coord's primitives — run
        # in a plan-shaped Coordinator under the epoch's confirm
        # namespace so only planned members vote.
        digest = zlib.crc32(json.dumps(plan).encode())
        confirm = Coordinator(
            size=len(plan), index=plan.index(self.index),
            namespace=f"{ens}/confirm",
            timeout_s=self.config.reconfig_timeout_s)
        try:
            agreed = confirm.min_int(digest)
            mismatch = confirm.any_flag(agreed != digest)
        except Exception as e:
            raise ElasticFallback(
                f"survivor confirmation round failed "
                f"({type(e).__name__}: {str(e)[:160]}) — a planned "
                f"survivor died during reconfiguration") from None
        if mismatch:
            raise ElasticFallback(
                f"survivors computed different member sets (mine: "
                f"{plan}) — a suspect revived mid-round or the "
                f"partition is asymmetric; refusing to risk split-brain")
        return plan

    def _teardown(self, graceful: bool) -> None:
        from dexiraft_tpu.parallel.distributed import elastic_teardown
        from dexiraft_tpu.train.checkpoint import reset_managers

        reset_managers(abandon_pending=not graceful)
        elastic_teardown(graceful=graceful)

    def _install_epoch(self, epoch: int, addr: str, size: int, index: int,
                       announce_joins: Optional[Dict[str, int]]
                       ) -> EpochInfo:
        """Bring up one world: announce (rank 0, before its own connect
        blocks — joiners dial off the announcement and retry until the
        service is up), initialize the elastic runtime, publish this
        host's address, start the lease thread.

        announce_joins=None marks a JOINER entering an already-announced
        epoch (it must not re-announce)."""
        from dexiraft_tpu.parallel.distributed import elastic_initialize

        if index == 0 and announce_joins is not None:
            self.board.announce_epoch(epoch, addr, size, announce_joins)
        elastic_initialize(addr, size, index, start_service=(index == 0),
                           init_timeout_s=self.config.init_timeout_s)
        # every member of every epoch passes through here in lockstep:
        # the (addr, size) digest is identical across the world, so a
        # member installing a different world is the first divergence
        collective_trace.record(
            _ELASTIC_NS, "install_epoch", round_id=epoch,
            digest=collective_trace.args_digest(addr, size))
        self.epoch = epoch
        self.size = size
        self.index = index
        self.coordinator_address = addr
        with self._lock:
            self._suspects = set()
            self._coordinator_lost = None
            self._stall_verdict = None
        self._client().key_value_set(f"{self._ens()}/host/{index}",
                                     self.config.host,
                                     allow_overwrite=True)
        self._start_leases()
        return EpochInfo(epoch, size, index, addr)

    # -- leases ----------------------------------------------------------
    def _start_leases(self) -> None:
        if self.size <= 1:
            return  # a solo world has nobody to suspect
        self._lease_stop = threading.Event()
        self._lease_thread = threading.Thread(
            target=self._lease_loop, name=f"lease[e{self.epoch}]",
            daemon=True,
            args=(self._lease_stop, self._client(), self._ens(),
                  self.size, self.index))
        self._lease_thread.start()

    def _stop_leases(self) -> None:
        self._lease_stop.set()
        if self._lease_thread is not None:
            self._lease_thread.join(
                timeout=self.config.probe_timeout_s * 2 + 1)
            self._lease_thread = None

    def _lease_loop(self, stop: threading.Event, client, ens: str,
                    size: int, index: int) -> None:
        """Publish this host's lease counter; probe every peer's. A
        counter unchanged past lease_timeout_s is a missed lease. Runs
        against one epoch's client and dies with it (reconfiguration
        stops it first)."""
        seq = 0
        t_start = time.monotonic()
        last_change: Dict[int, Tuple[Optional[str], float]] = {
            i: (None, t_start) for i in range(size) if i != index}
        probe_ms = int(self.config.probe_timeout_s * 1000)
        while not stop.wait(self.config.lease_interval_s):
            try:
                client.key_value_set(f"{ens}/lease/{index}", str(seq),
                                     allow_overwrite=True)
            except Exception as e:
                self._mark_coordinator_lost(e)
                return
            seq += 1
            now = time.monotonic()
            for i in list(last_change):
                if stop.is_set():
                    return
                try:
                    value = client.blocking_key_value_get(
                        f"{ens}/lease/{i}", probe_ms)
                except Exception as e:
                    if "DEADLINE_EXCEEDED" not in str(e):
                        self._mark_coordinator_lost(e)
                        return
                    value = None  # never posted yet: stale since epoch
                prev, since = last_change[i]
                if value is not None and value != prev:
                    last_change[i] = (value, now)
                elif now - since > self.config.lease_timeout_s:
                    with self._lock:
                        if i not in self._suspects:
                            self._suspects.add(i)
                            print(f"[elastic] epoch {self.epoch}: member "
                                  f"{i} missed its lease (no heartbeat "
                                  f"for {now - since:.1f}s > "
                                  f"{self.config.lease_timeout_s:.0f}s)",
                                  flush=True)

    def _mark_coordinator_lost(self, exc: BaseException) -> None:
        with self._lock:
            if self._coordinator_lost is None:
                self._coordinator_lost = \
                    f"{type(exc).__name__}: {str(exc)[:120]}"
        print(f"[elastic] epoch {self.epoch}: KV store unreachable "
              f"({self._coordinator_lost})", flush=True)
