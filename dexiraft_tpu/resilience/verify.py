"""Restore-time checkpoint integrity: verify, and fall back, loudly.

A checkpoint can be bad in two distinct ways and the stock restore path
handled neither: a TRUNCATED/corrupt step (preemption mid-write, disk
trouble) crashes deep inside orbax, and a step that restores cleanly but
holds non-finite leaves (saved by a guard-less run, or poisoned storage)
loads silently and wastes a relaunch before the divergence guard fires.

``restore_verified`` walks the saved steps newest-first: each candidate
must (a) restore at all, (b) match the template's tree structure and
leaf shapes/dtypes — orbax's StandardRestore enforces most of this, the
explicit check catches drift in what it tolerates — and (c) pass a
finiteness sample over the float leaves. The first step that passes
wins; everything skipped is reported in one line each, so "resumed from
step 40000 because 45000 was truncated" is visible in the log instead of
being silently wrong.

Once a good step restores, the skipped bad steps are DELETED (dir and
sidecar): orbax's CheckpointManager.save() to an existing step dir is a
silent no-op, so a damaged step left in place would swallow the very
re-save that retraining toward that step number performs — the run
would "finish" with its newest checkpoint still the truncated one.
When every candidate fails, nothing is deleted (forensics beat tidiness
on a total loss) and CheckpointIntegrityError carries the skip list.

Async saves add a third way a directory can be dirty: a crash MID-FLUSH
(kill -9, chaos kill_mid_flush, node loss) abandons an uncommitted
``<step>.orbax-checkpoint-tmp-*`` directory. The atomic-commit rename
never happened, so the step is invisible to every restore path — the
previous committed step is still the newest restorable one, which is
the whole point — but the debris would accumulate and (same silent
no-op hazard as above, on the tmp namespace) confuse a later flush of
the same step. ``restore_verified`` always reports it, and removes it
when the caller declares itself the directory's writer
(``clean_debris=True`` — the recovering trainer; readers such as
serve/eval must never delete another process's possibly-live flush).
"""

from __future__ import annotations

import os
import os.path as osp
import shutil
from typing import Optional, Tuple

import jax
import numpy as np

from dexiraft_tpu.resilience.stream import delete_position
from dexiraft_tpu.train import checkpoint as ckpt
from dexiraft_tpu.train.state import TrainState

# leaves sampled for the finiteness check: every Nth float leaf plus the
# largest one (the big encoder kernels are where storage corruption is
# most likely to land by mass)
_SAMPLE_EVERY = 7


class CheckpointIntegrityError(RuntimeError):
    """No saved step under the directory passed verification."""


def uncommitted_flushes(directory: str) -> "list[str]":
    """Leftover orbax tmp dirs from flushes that never committed (the
    process died mid-write). Sorted names, not paths."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return sorted(n for n in names if ".orbax-checkpoint-tmp" in n
                  and osp.isdir(osp.join(directory, n)))


def clean_uncommitted(directory: str, verbose: bool = True) -> "list[str]":
    """Remove crashed-flush debris (see module docstring). Only call
    when this process owns the directory's writes — the barrier
    discipline in train.checkpoint guarantees no in-flight flush of our
    own, and the single-writer checkpoint model means nobody else's."""
    debris = uncommitted_flushes(directory)
    for name in debris:
        shutil.rmtree(osp.join(directory, name), ignore_errors=True)
    if debris and verbose:
        print(f"[resilience] removed {len(debris)} uncommitted flush(es) "
              f"under {directory} (crash mid-save; the committed steps "
              f"are unaffected): {debris}", flush=True)
    return debris


def verify_state(state, template, sample_every: int = _SAMPLE_EVERY) -> None:
    """Raise CheckpointIntegrityError unless `state` matches `template`'s
    tree structure and leaf shapes and passes a finiteness sample."""
    got = jax.tree_util.tree_structure(state)
    want = jax.tree_util.tree_structure(template)
    if got != want:
        raise CheckpointIntegrityError(
            f"tree structure mismatch: restored {got} != expected {want}")

    flat_got = jax.tree_util.tree_flatten_with_path(state)[0]
    flat_want = jax.tree_util.tree_flatten_with_path(template)[0]
    for (kp, leaf), (_, ref) in zip(flat_got, flat_want):
        if tuple(np.shape(leaf)) != tuple(np.shape(ref)):
            raise CheckpointIntegrityError(
                f"leaf {jax.tree_util.keystr(kp)}: shape "
                f"{tuple(np.shape(leaf))} != expected {tuple(np.shape(ref))}")

    # .dtype/.size are attributes on numpy and jax arrays alike — never
    # np.asarray() here: that would copy the WHOLE model device->host
    # just to pick the sample (asarray is reserved for sampled leaves)
    floats = [(kp, leaf) for kp, leaf in flat_got
              if np.issubdtype(getattr(leaf, "dtype", np.dtype(object)),
                               np.floating)]
    sample = floats[::max(1, sample_every)]
    if floats:
        largest = max(floats, key=lambda e: e[1].size)
        if all(largest[0] != kp for kp, _ in sample):
            sample.append(largest)
    for kp, leaf in sample:
        # |x|.sum() is finite iff every element is (inf and nan both
        # survive the reduction) — one scalar readback per sampled leaf
        if not np.isfinite(np.abs(np.asarray(leaf)).sum()):
            raise CheckpointIntegrityError(
                f"leaf {jax.tree_util.keystr(kp)} contains non-finite "
                f"values")


def prune_steps_above(directory: str, step: int,
                      verbose: bool = True) -> "list[int]":
    """Delete every committed step NEWER than ``step`` — the elastic
    reconfiguration's zombie-flush guard (resilience.membership).

    A shrink abandons the old world's in-flight async flush without
    waiting (its commit barriers against a dead host). That flush
    thread may still COMMIT its step after the survivors have agreed to
    resume from an older one — leaving a directory whose newest step
    the new world never agreed on, which a later restore would happily
    land on (divergence) and whose dir would swallow the re-save when
    training reaches that number again (orbax's silent no-op on
    existing step dirs). The new epoch's writer calls this right after
    ``agree_step`` settles the resume point.

    Deliberately bypasses the checkpoint manager: this runs between
    membership epochs, when per-step manager deletes would barrier
    across hosts that may hold DIFFERENT step lists (the dead host's
    flush landed on one disk only) — a deadlock, not a cleanup. Pure
    filesystem listing + rmtree of committed step dirs and their
    stream sidecars, safe because the caller is the directory's only
    writer and its own flush machinery was already abandoned/reset.
    """
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    pruned = []
    for name in sorted(names):
        if not name.isdigit() or int(name) <= step:
            continue
        if not osp.isdir(osp.join(directory, name)):
            continue
        shutil.rmtree(osp.join(directory, name), ignore_errors=True)
        delete_position(directory, int(name))
        pruned.append(int(name))
    if pruned and verbose:
        print(f"[resilience] pruned step(s) {pruned} above the agreed "
              f"resume step {step} under {directory} (zombie flush from "
              f"a previous membership epoch — never part of the agreed "
              f"history)", flush=True)
    return pruned


def restore_verified(
    directory: str,
    template: TrainState,
    step: Optional[int] = None,
    verbose: bool = True,
    clean_debris: bool = False,
) -> Tuple[TrainState, int]:
    """Restore the newest step (<= `step` if given) that passes
    verification, falling back step by step. Returns (state, step).

    clean_debris=True additionally sweeps uncommitted-flush tmp dirs —
    pass it ONLY from the directory's writer (the trainer recovering
    its own run): a reader (serve/eval booting off a live trainer's
    dir) must never delete what may be another process's in-flight
    flush. Readers still get the debris REPORTED, so a crashed run's
    leftovers are visible wherever they are seen.

    Raises CheckpointIntegrityError when every candidate fails —
    crashing with the full skip list beats silently training from a
    fresh init under a name that has checkpoints.
    """
    # barrier FIRST: an in-flight async flush of our own must commit
    # before the debris sweep below — its live tmp dir is not debris
    ckpt.wait_pending(directory)
    if clean_debris:
        clean_uncommitted(directory, verbose=verbose)
    elif verbose:
        debris = uncommitted_flushes(directory)
        if debris:
            print(f"[resilience] {len(debris)} uncommitted flush(es) "
                  f"under {directory} (crash mid-save; left in place — "
                  f"only the writing trainer cleans them): {debris}",
                  flush=True)
    steps = sorted(ckpt.all_steps(directory), reverse=True)
    if step is not None:
        steps = [s for s in steps if s <= step]
    if not steps:
        raise FileNotFoundError(f"no checkpoints under {directory}")

    skipped = []
    for s in steps:
        try:
            state = ckpt.restore_checkpoint(directory, template, step=s)
            verify_state(state, template)
        except Exception as e:  # orbax raises many types on corrupt input
            skipped.append((s, e))
            if verbose:
                print(f"[resilience] checkpoint {directory} step {s} failed "
                      f"verification ({type(e).__name__}: {e}); trying the "
                      f"previous step", flush=True)
            continue
        for bad, _ in skipped:
            # remove what failed verification: orbax silently no-ops a
            # save() onto an existing step dir, so a damaged step left
            # behind would eat the re-save when training reaches this
            # step number again (see module docstring)
            ckpt.delete_step(directory, bad)
            delete_position(directory, bad)
        if skipped and verbose:
            print(f"[resilience] restored step {s} after skipping "
                  f"{len(skipped)} bad step(s) (now deleted): "
                  f"{[b for b, _ in skipped]}", flush=True)
        return state, s
    raise CheckpointIntegrityError(
        f"no restorable checkpoint under {directory}: all of "
        f"{[b for b, _ in skipped]} failed verification "
        f"(last error: {skipped[-1][1]})")
