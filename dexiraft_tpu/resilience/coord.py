"""Multi-host failure consensus: tiny primitives, one shared verdict.

On a multi-host mesh every failure decision used to be LOCAL: the
divergence guard's verdict, the SIGTERM latch, and the verified-restore
fallback each decided per-process — so one host could roll back (or
emergency-save, or land on an older checkpoint) while its peers kept
stepping, turning a recoverable fault into a hung collective. The
primitives here make every such decision collective:

  * ``any_flag``   — OR over hosts: a NaN/divergence verdict on ANY host
    (or one host's preemption notice) becomes the SAME verdict on ALL
    hosts at the same step.
  * ``min_int``    — min over hosts: the agreed rollback/resume step, so
    a restart never straddles two checkpoints (a host whose disk lost
    the newest step pulls everyone to the newest step ALL hosts have).
  * ``agree_step`` — min_int iterated against what each host actually
    restored, bounded, so per-host verified-restore fallbacks converge.

Single-process runs degrade to the identity — no collective, no RPC —
so every existing CLI invocation and test runs unchanged. Multi-host,
each primitive is one tiny exchange over the jax.distributed KV store
(the coordination service orbax's own barriers ride): pure host gRPC,
no XLA computation and no compile, so it works on any backend —
including the multiprocess CPU mesh the tests run on, which implements
no cross-process XLA collectives at all — and never interacts with
strict mode's transfer/recompile guards. ``warmup()`` performs one
exchange up front so connectivity failures surface at startup, not at
the first rollback.

A DEAD peer makes these exchanges block until their timeout — that is
the hang watchdog's job (resilience.watchdog): consensus makes verdicts
global, the watchdog bounds the wait when a peer can no longer vote.
"""

from __future__ import annotations

import os
import tempfile
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np

from dexiraft_tpu.analysis import collective_trace as _trace
from dexiraft_tpu.analysis.collective_trace import CollectiveDivergence


class CoordinatorTimeout(RuntimeError):
    """A peer's consensus value never arrived within ``timeout_s``.

    Raised instead of the raw gRPC DEADLINE_EXCEEDED traceback so the
    operator (and the elastic membership runtime) sees WHICH peer of
    WHICH round went silent in one line. Under ``--elastic`` this is a
    reconfiguration trigger (resilience.membership); otherwise it is
    fatal with an actionable message. ``trace_path`` points at the
    local collective flight-recorder dump (analysis.collective_trace)
    written when the timeout fired: its tail names the round this host
    died waiting in.
    """

    def __init__(self, namespace: str, round_id: int, peer: int,
                 timeout_s: float, trace_path: Optional[str] = None):
        super().__init__(
            f"consensus timeout: peer {peer} posted no value for round "
            f"{round_id} of namespace '{namespace}' within "
            f"{timeout_s:.0f}s — the host is dead, stalled, or "
            f"partitioned (elastic runs reconfigure; others should "
            f"restart the pod)"
            + (f"; local collective trace: {trace_path}"
               if trace_path else ""))
        self.namespace = namespace
        self.round_id = round_id
        self.peer = peer
        self.timeout_s = timeout_s
        self.trace_path = trace_path


def _is_deadline(exc: BaseException) -> bool:
    """DEADLINE_EXCEEDED from the coordination service (vs a real
    transport/coordinator failure, which must keep its own traceback)."""
    return "DEADLINE_EXCEEDED" in str(exc)


class Coordinator:
    """Host-consensus primitives over the jax.distributed KV store.

    Constructed once per process; ``size``/``index`` default to the jax
    process topology. Tests inject allgather_fn to exercise the
    consensus logic without a live multi-process runtime. Peers must
    construct their Coordinators with the same ``namespace`` and call
    the primitives in the same order (every call is collective).
    """

    def __init__(self, size: Optional[int] = None,
                 index: Optional[int] = None, allgather_fn=None,
                 namespace: str = "dexiraft/coord",
                 timeout_s: float = 600.0):
        import jax

        self.size = int(jax.process_count() if size is None else size)
        self.index = int(jax.process_index() if index is None else index)
        self._allgather_fn = allgather_fn
        self.namespace = namespace
        self.timeout_s = float(timeout_s)
        self._round = 0
        self._pool: Optional[ThreadPoolExecutor] = None

    def _readers(self) -> ThreadPoolExecutor:
        """Lazy per-Coordinator pool for the concurrent peer reads (one
        blocking gRPC get per peer; capped so a 6000-host pod does not
        spawn 6000 idle threads per process)."""
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=min(self.size, 16),
                thread_name_prefix=f"coord[{self.namespace}]")
        return self._pool

    def _allgather(self, value: np.ndarray,
                   op: str = "exchange") -> np.ndarray:
        """(size, 1) array of every host's scalar.

        Rides the jax.distributed KV store (the coordination service
        orbax's own barriers use): each host publishes its value under a
        per-call round id and blocking-reads every peer's. Pure host
        gRPC — no XLA computation, no compile, no transfer — so it
        works identically on TPU pods and on the multiprocess CPU mesh
        the tests run on (whose backend implements no cross-process
        collectives at all), and it never interacts with strict mode's
        transfer/recompile guards. Round ids advance in lockstep
        because every consensus call is itself collective — the same
        discipline that makes the calls deadlock-free.

        Lockstep is also VERIFIED, not just assumed: every round is
        stamped into the collective flight recorder
        (analysis.collective_trace) and the stamp (op + args digest)
        piggybacks on the posted value — zero extra read RPCs — so a
        peer whose round counter skewed (an identity branch, a
        mid-protocol bail, a swallowed error) raises
        CollectiveDivergence naming the first divergent (host, round,
        op) the moment its mismatched key arrives, instead of pairing
        mismatched rounds until a timeout.

        A dead peer leaves the blocking read waiting until timeout_s —
        the hang watchdog (armed around the step loop) bounds that wait
        long before the timeout does."""
        tr = _trace.recorder()
        rid = self._round
        self._round += 1
        if self._allgather_fn is not None:
            tr.record(self.namespace, op, round_id=rid)
            return np.asarray(self._allgather_fn(value))
        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "multi-host consensus needs jax.distributed.initialize "
                "(parallel.distributed.initialize) before the first "
                "Coordinator call")
        dig = _trace.args_digest(self.namespace, rid, op)
        tr.record(self.namespace, op, round_id=rid, digest=dig)
        v = int(np.asarray(value).ravel()[0])
        # publish the recorder tail BEFORE the value (peers diagnosing
        # a wedge can read it even if this host dies before posting),
        # then the value stamped with this round's op|digest.
        # Diagnostics only: never fail the round for the recorder.
        try:
            client.key_value_set(
                f"{self.namespace}/trace/{rid}/{self.index}",
                tr.encode_tail())
        except Exception:
            pass
        client.key_value_set(f"{self.namespace}/{rid}/{self.index}",
                             f"{v}|{op}|{dig}")
        timeout_ms = max(1000, int(self.timeout_s * 1000))

        # concurrent peer reads: the sequential scan made a slow peer at
        # index 0 serialize detection of everything behind it (the worst
        # case paid size x timeout_s); concurrently every peer gets the
        # SAME timeout_s window and the slowest single peer bounds the
        # round. Index order is preserved in the gathered array.
        def read(i: int) -> int:
            try:
                raw = str(client.blocking_key_value_get(
                    f"{self.namespace}/{rid}/{i}", timeout_ms))
            except Exception as e:
                if _is_deadline(e):
                    raise CoordinatorTimeout(
                        self.namespace, rid, i, self.timeout_s,
                        trace_path=self._dump_trace()) from None
                raise
            parts = raw.split("|")
            if len(parts) == 3 and i != self.index:
                peer_op, peer_dig = parts[1], parts[2]
                if (peer_op, peer_dig) != (op, dig):
                    tr.note_divergence()
                    self._dump_trace()
                    raise CollectiveDivergence(
                        self.namespace, rid, i,
                        expected=f"{op}[{dig}]",
                        seen=f"{peer_op}[{peer_dig}]")
            return int(parts[0])

        if self.size <= 1:
            vals = [read(0)]
        else:
            vals = list(self._readers().map(read, range(self.size)))
            tr.note_verified()
        # bounded KV footprint over multi-day runs: completing round
        # rid proves every host finished READING round rid-1 (the calls
        # are lockstep), so each host's own rid-1 key is globally
        # consumed and safe to drop. Best-effort: stale keys are only
        # memory, never correctness.
        if rid > 0:
            try:
                client.key_value_delete(
                    f"{self.namespace}/{rid - 1}/{self.index}")
                client.key_value_delete(
                    f"{self.namespace}/trace/{rid - 1}/{self.index}")
            except Exception:
                pass
        return np.asarray(vals).reshape(self.size, 1)

    def _dump_trace(self) -> str:
        """Write the local flight-recorder ring next to the system tmp
        dir; the CoordinatorTimeout message points here so a hung
        consensus names the round it died in without a debugger."""
        path = os.path.join(
            tempfile.gettempdir(),
            f"dexiraft_collective_trace_h{self.index}.log")
        try:
            return _trace.recorder().dump(path)
        except Exception:
            return "<trace dump failed>"

    def warmup(self) -> None:
        """One throwaway exchange at startup: a misconfigured or
        unreachable coordination service fails HERE, loudly, instead of
        at the first rollback or preemption broadcast mid-run."""
        if self.size > 1:
            self.any_flag(False)

    def any_flag(self, flag: bool) -> bool:
        """True iff ANY host raised the flag (identity single-process)."""
        if self.size == 1:
            return bool(flag)
        return bool(self._allgather(np.asarray([bool(flag)]),
                                    op="any_flag").any())

    def min_int(self, value: int) -> int:
        """Min over hosts (identity single-process). Callers encode
        "I have nothing" as a sentinel smaller than any real value
        (e.g. -1 for checkpoint steps): the poorest host then pulls the
        agreement down to a step everyone has — or to the sentinel,
        which the caller must treat as "no agreed target"."""
        if self.size == 1:
            return int(value)
        return int(self._allgather(np.asarray([int(value)]),
                                   op="min_int").min())

    def agree_step(self, restore_fn, step: Optional[int],
                   max_rounds: int = 4):
        """Restore the SAME checkpoint step on every host.

        restore_fn(step_or_None) -> (state, restored_step) is the host's
        verified restore (resilience.verify.restore_verified bound to its
        directory/template). Each host restores its best candidate at or
        below the agreed bound, hosts exchange what they actually landed
        on, and any host above the global min re-restores at that min —
        converging because the agreed bound is monotonically decreasing.
        Returns (state, step). Raises RuntimeError if hosts still
        disagree after max_rounds (disks have diverged beyond repair —
        a human problem, not a retry problem).

        Every host runs every round in lockstep — restore_fn (orbax
        restores barrier internally in multiprocess mode) and both
        consensus ops are collectives, so a host that already sits on
        the agreed step re-restores it rather than exiting early and
        leaving its peers blocked in a collective it no longer joins."""
        bound = step
        state = restored = None
        for _ in range(max_rounds):
            state, raw = restore_fn(bound)
            # restore_fn returns a host int step (restore_verified's
            # contract), not a device scalar — no hidden sync here
            restored = int(raw)  # jaxlint: disable=JL007
            agreed = self.min_int(restored)
            if not self.any_flag(restored != agreed):
                return state, restored
            bound = agreed
        raise RuntimeError(
            f"host {self.index}: no checkpoint step agreement after "
            f"{max_rounds} rounds (last restored {restored}); the hosts' "
            f"checkpoint directories have diverged — inspect them")
