"""Hang watchdog: a stalled pod exits loudly instead of hanging forever.

The failure mode consensus (resilience.coord) cannot cover: a peer dies
(or the interconnect wedges) INSIDE a collective, and every surviving
host blocks forever in a psum/allgather with nothing scheduled to time
out for hours. On a pod that is the most expensive way to do nothing —
the job looks alive to the orchestrator while every chip idles.

``HangWatchdog`` is a daemon monitor thread armed around each step (and
any other region that must make progress — checkpoint barriers,
emergency saves). If an armed region exceeds ``timeout_s`` the watchdog
dumps the step index, the region name, and the LIVE stack traces of
every thread (faulthandler — the collective the process is stuck in is
right there in the dump), then ``os._exit``s with STALL_EXIT_CODE so the
orchestrator restarts the job instead of billing a hung one. Exit —
not an exception: the stalled thread cannot raise, it is blocked in C.

Straggler detection rides the same timer: the watchdog keeps an EWMA of
completed region durations, and an in-flight region exceeding
``straggler_factor`` x the EWMA gets a one-line warning (once per
region) long before the hard timeout — the early signature of a slow
host, a thermal chip, or a degrading disk.

The clock and the exit are injectable so tests drive the whole protocol
with a fake clock instead of real multi-second sleeps.
"""

from __future__ import annotations

import faulthandler
import os
import sys
import threading
import time
from typing import Callable, Optional

from dexiraft_tpu.analysis.locks import OrderedLock

STALL_EXIT_CODE = 98


class HangWatchdog:
    """Arm/disarm around regions that must make progress; see module doc.

    timeout_s <= 0 constructs an inert watchdog (arm/disarm are no-ops,
    no thread) so callers can wire it unconditionally.
    """

    def __init__(self, timeout_s: float, straggler_factor: float = 10.0,
                 ewma_alpha: float = 0.1, label: str = "train",
                 poll_s: Optional[float] = None,
                 slow_region_factor: float = 10.0,
                 clock: Callable[[], float] = time.monotonic,
                 exit_fn: Callable[[int], None] = os._exit,
                 stream=None):
        self.timeout_s = float(timeout_s)
        self.straggler_factor = float(straggler_factor)
        # sanctioned slow regions (steady=False: checkpoint barrier,
        # validation, restore) are legitimately much longer than a
        # step; they get timeout_s x this factor before the stall
        # fires, so a step-sized --stall_timeout never kills a healthy
        # validation sweep
        self.slow_region_factor = float(slow_region_factor)
        self.ewma_alpha = float(ewma_alpha)
        self.label = label
        self.poll_s = (poll_s if poll_s is not None
                       else max(0.05, min(1.0, self.timeout_s / 20)))
        self._clock = clock
        self._exit = exit_fn
        self._stream = stream
        self.ewma_s: Optional[float] = None
        self.fired = False
        self.straggler_warnings = 0
        # elastic handoff (resilience.membership): when set, the FIRST
        # stall verdict is handed to this callback instead of exiting.
        # on_stall(step, region) returns a grace window in seconds —
        # the region is re-armed once so the membership runtime can run
        # ONE reconfiguration attempt (the stuck thread unblocks via
        # its own op timeout, sees the verdict, and reconfigures) — or
        # None/0 to decline. A second stall (grace exhausted, or the
        # reconfiguration itself wedged) exits 98 as before.
        self.on_stall: Optional[Callable[[int, str], Optional[float]]] = None
        self._stall_handed = False
        self._lock = OrderedLock("resilience.watchdog.armed")
        self._armed: Optional[tuple] = None  # (step, region, t0, warned)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def enabled(self) -> bool:
        return self.timeout_s > 0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "HangWatchdog":
        if self.enabled and self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name=f"watchdog[{self.label}]",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "HangWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- arming ------------------------------------------------------------
    def arm(self, step: int, region: str = "step",
            steady: bool = True) -> None:
        """The region begins now; the monitor times it from this call.

        steady=False marks a sanctioned slow region (checkpoint
        barrier, validation, rollback restore): the hard stall timeout
        still applies, but the region neither feeds the step-time EWMA
        nor gets compared against it for straggler warnings — a
        legitimately slow validation window is not a slow host."""
        if not self.enabled:
            return
        with self._lock:
            self._armed = (int(step), region, self._clock(), False,
                           bool(steady))

    def disarm(self, feed_ewma: bool = True) -> Optional[float]:
        """The region completed; returns its duration. The duration
        feeds the straggler EWMA only for steady regions (and
        feed_ewma=False opts a steady region out, e.g. a partial
        iteration)."""
        if not self.enabled:
            return None
        with self._lock:
            if self._armed is None:
                return None
            _, _, t0, _, steady = self._armed
            self._armed = None
        dt = self._clock() - t0
        if not (feed_ewma and steady):
            return dt
        with self._lock:
            # under the lock: the monitor thread reads ewma_s for the
            # straggler floor every poll, and an unlocked read-blend-
            # write here can resurrect a stale EWMA over a fresh one
            if self.ewma_s is None:
                self.ewma_s = dt
            else:
                a = self.ewma_alpha
                self.ewma_s = (1 - a) * self.ewma_s + a * dt
        return dt

    def reset_stall_handoff(self) -> None:
        """Re-enable the one-shot elastic handoff after a COMPLETED
        reconfiguration: the new epoch gets its own single attempt, while
        a reconfiguration that never finished keeps the latch so the
        second fire still exits."""
        self._stall_handed = False

    # -- monitor -----------------------------------------------------------
    def check_once(self) -> Optional[str]:
        """One monitor poll (the thread's body; tests call it directly).
        Returns "stall" / "straggler" / None for what it observed."""
        with self._lock:
            armed = self._armed
        if armed is None:
            return None
        step, region, t0, warned, steady = armed
        dt = self._clock() - t0
        limit = self.timeout_s * (1.0 if steady
                                  else self.slow_region_factor)
        if dt > limit:
            self._fire(step, region, dt, limit)
            return "stall"
        if not steady:
            return None  # sanctioned slow region: (scaled) stall bound only
        floor = self.straggler_factor * self.ewma_s if self.ewma_s else None
        if floor is not None and dt > floor and not warned:
            with self._lock:
                # re-check under the lock: disarm/arm may have raced
                if self._armed == armed:
                    self._armed = (step, region, t0, True, steady)
                    self.straggler_warnings += 1
                    print(f"[watchdog:{self.label}] straggler: {region} at "
                          f"step {step} running {dt:.1f}s "
                          f"(EWMA {self.ewma_s:.2f}s, warn at "
                          f"{self.straggler_factor:.0f}x); stall timeout "
                          f"at {self.timeout_s:.0f}s",
                          file=self._stream or sys.stderr, flush=True)
            return "straggler"
        return None

    def _fire(self, step: int, region: str, dt: float,
              limit: Optional[float] = None) -> None:
        out = self._stream or sys.stderr
        if self.on_stall is not None and not self._stall_handed:
            # one elastic reconfiguration attempt before the exit: the
            # verdict (armed region named, so the membership runtime
            # knows WHICH collective wedged) goes to on_stall, and the
            # granted grace re-arms the region exactly once. If the
            # reconfiguration itself stalls, the next fire exits.
            self._stall_handed = True
            try:
                grace = self.on_stall(step, region)
            except Exception as e:
                print(f"[watchdog:{self.label}] on_stall handler failed "
                      f"({type(e).__name__}: {e}); falling through to "
                      f"exit", file=out, flush=True)
                grace = None
            if grace:
                print(f"[watchdog:{self.label}] STALL: {region} at step "
                      f"{step} has made no progress for {dt:.1f}s — "
                      f"verdict handed to the elastic membership runtime "
                      f"({grace:.0f}s grace for one reconfiguration "
                      f"attempt before exit {STALL_EXIT_CODE})",
                      file=out, flush=True)
                with self._lock:
                    armed = self._armed
                    if armed is not None:
                        s, r, _, warned, _ = armed
                        # re-arm from now as a sanctioned slow region
                        # sized so the grace window elapses before the
                        # next fire (timeout_s * slow_region_factor)
                        self._armed = (s, r, self._clock() + max(
                            0.0, grace - self.timeout_s
                            * self.slow_region_factor), warned, False)
                return
        self.fired = True
        print(f"[watchdog:{self.label}] STALL: {region} at step {step} "
              f"has made no progress for {dt:.1f}s "
              f"(timeout {limit if limit is not None else self.timeout_s:.0f}s)"
              f" — dumping live stacks "
              f"and exiting {STALL_EXIT_CODE} instead of hanging the pod"
              f" (a host lost mid-collective? --elastic lets the "
              f"membership runtime shrink and continue instead)",
              file=out, flush=True)
        # the collective flight-recorder tail FIRST: a stall inside a
        # consensus/barrier names the namespace+round it died in (the
        # faulthandler stacks below then show WHERE it is blocked)
        try:
            from dexiraft_tpu.analysis import collective_trace

            print(collective_trace.recorder().render_tail(),
                  file=out, flush=True)
        except Exception:
            pass
        try:
            faulthandler.dump_traceback(file=out)
            out.flush()
        except Exception:
            pass
        self._exit(STALL_EXIT_CODE)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            self.check_once()
