"""Graceful-preemption signal handling for the training loop.

TPU preemption (and any orchestrator teardown) arrives as SIGTERM with a
grace window; an interactive operator sends SIGINT. Both previously
killed the run wherever it stood, losing up to val_freq steps of work
and — worse — any data-stream position. The handler converts the FIRST
signal into a flag the train loop polls at step boundaries, where it
performs one final atomic emergency save (still guard-checked: a
poisoned state is never saved, preempted or not) and exits cleanly.

A SECOND signal raises KeyboardInterrupt immediately: if the emergency
save itself wedges (hung filesystem), the operator can still get out.

Installation is a context manager so nested/sequential uses restore the
previous handlers, and it degrades to an inert no-op off the main thread
(Python only allows signal handlers there) — library callers embedding
the trainer in a worker thread keep the old die-on-signal behavior
rather than getting a crash at install time.
"""

from __future__ import annotations

import signal
from typing import Optional, Tuple


class PreemptionHandler:
    """Latch SIGTERM/SIGINT into a poll-able flag (see module docstring)."""

    def __init__(self, signums: Tuple[int, ...] = (signal.SIGTERM,
                                                   signal.SIGINT)):
        self.signums = signums
        self.triggered = False
        self.signum: Optional[int] = None
        self._previous: dict = {}

    @property
    def signal_name(self) -> str:
        if self.signum is None:
            return "none"
        try:
            return signal.Signals(self.signum).name
        except ValueError:
            return str(self.signum)

    def _handle(self, signum, frame) -> None:
        if self.triggered:
            # second signal: the graceful path is stuck — bail hard
            raise KeyboardInterrupt(
                f"second {self.signal_name} during preemption handling")
        self.triggered = True
        self.signum = signum
        print(f"[preempt] received {self.signal_name}; finishing the "
              f"current step, then saving an emergency checkpoint "
              f"(signal again to abort immediately)", flush=True)

    def __enter__(self) -> "PreemptionHandler":
        for signum in self.signums:
            try:
                self._previous[signum] = signal.signal(signum, self._handle)
            except ValueError:
                # not the main thread: signals can't be installed; stay inert
                self._previous.pop(signum, None)
        return self

    def __exit__(self, *exc) -> None:
        for signum, prev in self._previous.items():
            try:
                signal.signal(signum, prev)
            except ValueError:
                pass
        self._previous.clear()
        return None
