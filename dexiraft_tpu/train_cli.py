"""Training CLI — one resolved config tree instead of argparse x3 + .sh files.

Reference surface: train.py:220-250 (flags, seeds, checkpoint dir) and the
curriculum scripts train_standard.sh / train_mixed.sh. One invocation runs
one stage; presets supply the per-stage hyperparameters:

  python -m dexiraft_tpu train --stage chairs --name raft-chairs \
      --variant v1 --validation chairs
  python -m dexiraft_tpu train --preset standard --stage things \
      --restore_ckpt checkpoints/raft-chairs

The loop is the reference's (train.py:163-215) re-shaped for TPU: one
jitted sharded step (forward + loss + backward + optimizer), batches
sharded over the data mesh axis, VAL_FREQ checkpoint+validate, final save.
"""

from __future__ import annotations

import argparse
import sys
from typing import Dict, Optional

import jax
import numpy as np

from dexiraft_tpu import config as cfglib
from dexiraft_tpu.config import VARIANTS, RAFTConfig, TrainConfig

# reference in-training validation iteration counts (evaluate.py:81-210)
_VAL_ITERS = {"chairs": 24, "sintel": 32, "kitti": 24, "hd1k": 24}


def fsdp_arg(value: str):
    """argparse type= for --fsdp: 'auto' or a positive integer, refused
    at parse time with usage text (not a raw int() traceback after the
    dataset/import setup has already run). Shared by train_bench."""
    if value == "auto":
        return value
    try:
        n = int(value)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected 'auto' or an integer, got {value!r}")
    if n < 1:
        raise argparse.ArgumentTypeError(f"expected >= 1, got {n}")
    return n


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("dexiraft-train")
    p.add_argument("--name", default=None,
                   help="experiment name (default: preset's per-stage name, "
                        "else 'raft')")
    p.add_argument("--stage", required=True,
                   choices=["chairs", "things", "sintel", "kitti"])
    p.add_argument("--preset", choices=["standard", "mixed", "none"],
                   default="none", help="stage hyperparameter preset")
    p.add_argument("--variant", default="v1", choices=sorted(VARIANTS))
    p.add_argument("--small", action="store_true")
    p.add_argument("--mixed_precision", action="store_true")
    p.add_argument("--corr_impl", default="allpairs",
                   choices=["allpairs", "local", "pallas", "flash"])
    p.add_argument("--corr_dtype", default="fp32", choices=["fp32", "bf16"],
                   help="storage precision of the correlation pyramid "
                        "(halves HBM traffic of the refinement loop at "
                        "bf16; int8 is inference-only — eval/serve)")
    p.add_argument("--fused_update", action="store_true",
                   help="fuse each iteration's 4-level lookup with the "
                        "motion encoder's corr conv into one Pallas "
                        "kernel (requires --corr_impl flash or pallas; "
                        "identical param tree, checkpoints interchange)")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize refinement iterations in backward "
                        "(HBM savings at ~1 extra forward of FLOPs)")
    p.add_argument("--remat_lookup", action="store_true",
                   help="rematerialize only the correlation lookup — "
                        "drops the per-iteration hat matrices (the "
                        "dominant training-memory term) far cheaper than "
                        "full --remat")
    p.add_argument("--dexined_upconv", default="subpixel",
                   choices=["transpose", "subpixel"],
                   help="embedded-DexiNed upsampler implementation "
                        "(numerically identical; see docs/perf.md)")
    p.add_argument("--dropout", type=float, default=0.0)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--num_steps", type=int, default=None)
    p.add_argument("--batch_size", type=int, default=None)
    p.add_argument("--image_size", type=int, nargs=2, default=None)
    p.add_argument("--wdecay", type=float, default=None)
    p.add_argument("--gamma", type=float, default=None)
    p.add_argument("--clip", type=float, default=1.0)
    p.add_argument("--iters", type=int, default=12)
    p.add_argument("--add_noise", action="store_true")
    p.add_argument("--precision", choices=["fp32", "bf16"], default="fp32",
                   help="training precision policy: bf16 = bf16 "
                        "compute/activations with fp32 master weights "
                        "and fp32 loss/optimizer math")
    p.add_argument("--accum_steps", type=int, default=1,
                   help="gradient accumulation: batch_size = accum * "
                        "microbatch; the microbatches run as a lax.scan "
                        "inside the ONE jitted step")
    p.add_argument("--fsdp", default=None, type=fsdp_arg,
                   help="shard params + optimizer state over the mesh's "
                        "fsdp axis: 'auto' grows the axis over every "
                        "device left after data-parallelism takes the "
                        "largest batch divisor (host-count-aware), an "
                        "integer forces that many ways; default/1 keeps "
                        "the replicated layout. Storage-only sharding "
                        "(docs/perf.md): per-device state HBM drops "
                        "~fsdp-fold, checkpoints flush per shard, the "
                        "step gathers at entry so the math is the "
                        "replicated step's")
    p.add_argument("--prefetch_depth", type=int, default=2,
                   help="device-side prefetch depth (batches device_put "
                        "ahead with the step's input shardings while the "
                        "current step runs; 0 disables)")
    p.add_argument("--compile_cache", action="store_true",
                   help="persistent XLA compilation cache — repeat "
                        "launches skip the multi-minute compile")
    p.add_argument("--compile_cache_dir", default=None,
                   help="cache location (default logs/xla_cache); "
                        "implies --compile_cache")
    p.add_argument("--validation", nargs="*", default=None,
                   choices=sorted(_VAL_ITERS),
                   help="default: the preset's per-stage validation sets")
    p.add_argument("--records_dir", default=None,
                   help="train from a packed-record directory "
                        "(scripts/pack_records.py) instead of decoding "
                        "raw dataset files: same sample sequence, O(1) "
                        "resume seeks, per-host shard reads "
                        "(docs/data_plane.md); the raw-file loader "
                        "remains the default")
    p.add_argument("--edge_root", default=None,
                   help="parallel tree of precomputed edge-map PNGs for the "
                        "v2/v3 data-edge contract (core/datasets_seperate.py)")
    p.add_argument("--edge_sum_fusion", action="store_true",
                   help="v1-lineage fusion (alt/train_1.py:173-176): run the "
                        "model on the image pair AND the edge pair, sum the "
                        "per-iter flows; needs --edge_root")
    p.add_argument("--restore_ckpt", default=None,
                   help="orbax dir for partial (strict=False-style) restore")
    p.add_argument("--resume", action="store_true",
                   help="restore FULL state (incl. optimizer/schedule) from "
                        "--output/<name> and continue")
    p.add_argument("--output", default="checkpoints")
    p.add_argument("--seed", type=int, default=1234)
    p.add_argument("--val_freq", type=int, default=5000)
    p.add_argument("--sum_freq", type=int, default=100)
    p.add_argument("--num_workers", type=int, default=4)
    p.add_argument("--worker_mode", choices=["thread", "process"],
                   default="thread",
                   help="decode pool kind; 'process' sidesteps the GIL "
                   "on many-core hosts (spawned, not forked: the CLI "
                   "initializes jax before the loader exists)")
    p.add_argument("--log_dir", default="runs")
    p.add_argument("--profile_steps", type=int, nargs=2, default=None,
                   metavar=("START", "STOP"),
                   help="capture a jax.profiler trace for steps "
                        "[START, STOP) into <log_dir>/<name>/profile")
    # failure detection / elastic recovery — absent in the reference
    # (SURVEY.md §5): its v3 run diverged from EPE 8.4 to 347 and kept
    # logging (logs/raft_3_train_chairs_log*.out), and outages killed
    # runs that were restarted by hand. Here a non-finite or exploding
    # loss rolls the full state back to the last checkpoint and training
    # continues on the data stream's current position (the divergent
    # batch window is naturally skipped, not replayed).
    p.add_argument("--no_guard", action="store_true",
                   help="disable the divergence guard")
    p.add_argument("--guard_every", type=int, default=100,
                   help="check the loss every N steps (a host sync; the "
                        "logger already syncs at --sum_freq, so matching "
                        "it costs nothing extra)")
    p.add_argument("--guard_threshold", type=float, default=1e4,
                   help="loss above this (or non-finite) triggers a "
                        "rollback to the last checkpoint")
    p.add_argument("--max_rollbacks", type=int, default=3,
                   help="abort after this many rollbacks (persistent "
                        "divergence needs a human: lower the lr)")
    # fault-tolerant runtime (docs/resilience.md): preemption becomes one
    # guard-checked emergency save; --resume continues the EXACT sample
    # sequence via the stream-position sidecar saved with every
    # checkpoint; restores verify integrity and fall back a step instead
    # of crashing on (or silently loading) a truncated checkpoint
    p.add_argument("--keep", type=int, default=0,
                   help="retention: keep only the newest N checkpoints "
                        "(0 = keep all); the current rollback target is "
                        "never deleted")
    p.add_argument("--keep_best", action="store_true",
                   help="retention also keeps the checkpoint with the "
                        "best validation EPE even once it ages out of "
                        "the --keep window")
    p.add_argument("--on_preempt", choices=["save", "abort"],
                   default="save",
                   help="SIGTERM/SIGINT response: 'save' finishes the "
                        "current step and writes one emergency "
                        "checkpoint + data-stream position (a second "
                        "signal aborts immediately); 'abort' stops "
                        "without saving (the reference behavior)")
    p.add_argument("--chaos", default=None, metavar="SPEC",
                   help="fault injection for tests/scripts/chaos_smoke "
                        "(resilience.chaos.parse_spec), e.g. "
                        "'sigterm@30': real SIGTERM after step 30, "
                        "'kill_mid_flush@30': hard-kill during the next "
                        "async checkpoint flush")
    # pod-grade failure handling (docs/resilience.md "Multi-host"):
    # checkpoint flushes are async (the loop only pays the host
    # snapshot; wait_pending barriers sit before the next save /
    # validation / rollback / GC / exit), failure verdicts are
    # host-collective, and a hang is bounded by a watchdog
    p.add_argument("--stall_timeout", type=float, default=0.0,
                   help="hang watchdog: a step/collective region making "
                        "no progress for this many seconds dumps the "
                        "step index + live stack traces and exits "
                        "nonzero instead of hanging the pod "
                        "(0 = disabled; sanctioned slow windows — "
                        "checkpoint, validation, restore — get 10x "
                        "this bound)")
    p.add_argument("--straggler_factor", type=float, default=10.0,
                   help="warn when a step runs this many times the "
                        "step-time EWMA (same watchdog timer; needs "
                        "--stall_timeout > 0)")
    p.add_argument("--coord_every", type=int, default=10,
                   help="multi-host: poll the coordinated preemption "
                        "flag every N steps (one tiny allgather; "
                        "divergence verdicts coordinate on "
                        "--guard_every; single-process runs never "
                        "issue a collective)")
    p.add_argument("--coord_timeout_s", type=float, default=600.0,
                   help="consensus-op timeout: a peer posting no value "
                        "for this long raises the one-line "
                        "CoordinatorTimeout naming the peer and round "
                        "instead of waiting forever (under --elastic "
                        "this is also how a stuck survivor unblocks "
                        "into reconfiguration — set it to seconds, "
                        "not minutes)")
    # elastic pod membership (docs/resilience.md "Elastic membership"):
    # a lost host becomes a shrink-and-continue reconfiguration inside
    # the SAME process — new membership epoch, smaller mesh, agreed-step
    # restore, re-sliced data stream — instead of an exit-98 pod
    # restart; replacement hosts join at the next checkpoint boundary
    p.add_argument("--elastic", action="store_true",
                   help="survive host loss by reconfiguring the pod "
                        "membership (resilience.membership) instead of "
                        "exiting: needs JAX_COORDINATOR_ADDRESS (+ "
                        "JAX_NUM_PROCESSES/JAX_PROCESS_ID on pods; a "
                        "solo incumbent may omit them), and exits 98 "
                        "only when recovery is impossible (--min_hosts, "
                        "rank-0 loss, reconfiguration timeout)")
    p.add_argument("--min_hosts", type=int, default=1,
                   help="elastic: refuse to shrink below this many "
                        "hosts — a deeper cascade falls back to the "
                        "exit-98 restart contract")
    p.add_argument("--join", default=None, metavar="NAME",
                   help="enter a running --elastic job as a replacement "
                        "host under this name: posts a join intent on "
                        "the membership board and is absorbed at the "
                        "incumbents' next checkpoint boundary "
                        "(implies --elastic and --resume)")
    # runtime guard mode (analysis/guards.py, docs/static_analysis.md):
    # the dynamic half of the jaxlint story. Off, drift still surfaces
    # as a one-line warning on the guard cadence.
    p.add_argument("--strict", action="store_true",
                   help="arm guards.strict_mode after warmup: implicit "
                        "host<->device transfers raise immediately and "
                        "any post-warmup recompile fails the run "
                        "(checkpoint/validation windows are exempt — "
                        "they are sanctioned host I/O)")
    return p


def resolve_configs(args) -> "tuple[RAFTConfig, TrainConfig]":
    if args.fused_update and args.corr_impl not in ("pallas", "flash"):
        raise SystemExit("train: --fused_update requires --corr_impl "
                         "flash (the blocked HBM-streaming kernel) or "
                         "pallas (the per-pixel VMEM formulation)")
    cfg = VARIANTS[args.variant](
        small=args.small,
        mixed_precision=args.mixed_precision,
        dropout=args.dropout,
        corr_impl=args.corr_impl,
        corr_dtype=args.corr_dtype,
        fused_update=args.fused_update,
        remat=args.remat,
        remat_lookup=args.remat_lookup,
        dexined_upconv=args.dexined_upconv,
    )

    if args.preset != "none":
        stages = (cfglib.STANDARD_STAGES if args.preset == "standard"
                  else cfglib.MIXED_STAGES)
        base = next(tc for tc in stages if tc.stage == args.stage)
    else:
        base = TrainConfig(stage=args.stage)

    import dataclasses
    overrides: Dict = dict(
        stage=args.stage,
        clip=args.clip,
        iters=args.iters,
        add_noise=args.add_noise,
        precision=args.precision,
        accum_steps=args.accum_steps,
        prefetch_depth=args.prefetch_depth,
        edge_sum_fusion=args.edge_sum_fusion,
        # freeze BN for every post-chairs stage (train.py:149-150)
        freeze_bn=args.stage != "chairs",
        val_freq=args.val_freq,
        sum_freq=args.sum_freq,
        seed=args.seed,
    )
    # None = "not given": keep the preset's per-stage name/validation
    if args.name is not None:
        overrides["name"] = args.name
    if args.validation is not None:
        overrides["validation"] = tuple(args.validation)
    for field, value in [("lr", args.lr), ("num_steps", args.num_steps),
                         ("batch_size", args.batch_size),
                         ("wdecay", args.wdecay), ("gamma", args.gamma)]:
        if value is not None:
            overrides[field] = value
    if args.image_size is not None:
        overrides["image_size"] = tuple(args.image_size)
    return cfg, dataclasses.replace(base, **overrides)


def _make_validators(cfg: RAFTConfig, names, variables_fn):
    """Jitted eval fns per validation set, built once, reading the CURRENT
    variables through variables_fn at call time."""
    from dexiraft_tpu.eval.validate import VALIDATORS
    from dexiraft_tpu.train.step import make_eval_step

    steps = {n: make_eval_step(cfg, iters=_VAL_ITERS[n]) for n in names}

    def run(name: str) -> Dict[str, float]:
        fn = steps[name]
        variables = variables_fn()
        # explicit H2D put: validators hand numpy frames straight to the
        # jitted step; device_put keeps the transfer visible and strict-
        # transfer-guard-clean (analysis.guards)
        return VALIDATORS[name](
            lambda im1, im2, flow_init=None: fn(
                variables, jax.device_put(im1), jax.device_put(im2),
                flow_init=(None if flow_init is None
                           else jax.device_put(flow_init))))

    return run


class _GrowBoundary(Exception):
    """Internal control flow: a checkpoint boundary collectively agreed
    that join intents are pending. The segment loop (_elastic_main)
    absorbs them and re-enters train() in the grown world."""

    def __init__(self, step: int):
        self.step = step
        super().__init__(f"grow at checkpoint boundary (step {step})")


def train(cfg: RAFTConfig, tc: TrainConfig, args, elastic=None,
          prune_above_restore: bool = False) -> None:
    """One training segment. Non-elastic runs: the whole job. Under
    --elastic: one membership epoch — a ReconfigureNeeded /
    CoordinatorTimeout / _GrowBoundary raise unwinds this function
    (closing loader, watchdog, guards on the way), the segment loop
    reconfigures the world, and re-enters with resume semantics; every
    world-derived object (mesh, loader slice, coordinator namespace,
    jitted step) is rebuilt here against the new world."""
    import os.path as osp

    from dexiraft_tpu.data.datasets import fetch_dataset
    from dexiraft_tpu.data.loader import Loader
    from dexiraft_tpu.data.prefetch import prefetch_to_device
    from dexiraft_tpu.parallel import layout
    from dexiraft_tpu.parallel.layout import make_train_mesh
    from dexiraft_tpu.resilience import (
        Coordinator,
        HangWatchdog,
        LoaderKindMismatch,
        PreemptionHandler,
        RetentionPolicy,
        StreamPosition,
        load_position,
        restore_verified,
        save_position,
    )
    from dexiraft_tpu.train import checkpoint as ckpt
    from dexiraft_tpu.train.logger import Logger
    from dexiraft_tpu.train.state import create_state, param_count
    from dexiraft_tpu.train.step import make_train_step

    np.random.seed(tc.seed)
    ckpt_dir = osp.join(args.output, tc.name)

    # mesh policy lives in the canonical layout (parallel/layout.py):
    # data over the largest device count dividing the batch, plus an
    # fsdp axis over the leftover devices when --fsdp asks for one
    # (already 'auto'/int — the fsdp_arg parse-time type)
    mesh = make_train_mesh(tc.batch_size, fsdp=args.fsdp)
    if mesh.size < len(jax.devices()) or len(mesh.shape) > 1:
        print(f"[mesh] {dict(mesh.shape)} over {len(jax.devices())} "
              f"devices (batch {tc.batch_size})")

    if args.compile_cache or args.compile_cache_dir:
        if layout.LAYOUT.has_fsdp(mesh):
            # a persistent-cache HIT of the donated fsdp step crashes
            # this backend (deserialized executable segfault, jax
            # 0.4.37 CPU — bisected in the fsdp PR; cold writes are
            # fine, which makes the crash land on the SECOND launch);
            # refuse loudly rather than let a relaunch die mid-warmup.
            # docs/perf.md "Sharded state (fsdp)" has the story.
            print("[cache] persistent compile cache DISABLED: "
                  "cache-hit fsdp executables crash this backend "
                  "(docs/perf.md 'Sharded state (fsdp)')")
        else:
            from dexiraft_tpu.profiling import enable_persistent_cache

            print(f"[cache] persistent XLA compile cache: "
                  f"{enable_persistent_cache(args.compile_cache_dir)}")
    state = create_state(jax.random.PRNGKey(tc.seed), cfg, tc)
    print(f"Parameter Count: {param_count(state.params)}")
    fsdp_live = layout.LAYOUT.has_fsdp(mesh)
    if fsdp_live:
        # storage layout from step one: params/opt_state land sharded,
        # so every restore below (resume, rollback, partial) restores
        # per shard into the template's resolved shardings
        state = layout.shard_state(state, mesh)

    # last checkpoint that belongs to THIS trajectory — the only valid
    # rollback target. A stale dir from a previous experiment must never
    # be spliced into a fresh run by the guard.
    last_saved = None
    # which data plane feeds this run; stamped into every stream sidecar
    # (kind + pack fingerprint) so --resume refuses a raw<->records swap
    # AND a records-to-different-pack swap (LoaderKindMismatch)
    loader_kind = "records" if args.records_dir else "raw"
    records_ds = None
    pack_fingerprint = None
    if args.records_dir:
        # packed-record data plane (docs/data_plane.md), opened BEFORE
        # the resume path so its provenance gates both the dataset
        # selection and the stream-sidecar check. Same sample sequence
        # as the raw loader; decode is an O(1) indexed shard read and
        # each host touches only its slice's records.
        if args.edge_root:
            sys.exit("--records_dir cannot be combined with --edge_root: "
                     "edge-paired stages are not packable "
                     "(scripts/pack_records.py) — use the raw loader")
        from dexiraft_tpu.data.datasets import DEFAULT_TRAIN_DS
        from dexiraft_tpu.data.records import open_records

        records_ds = open_records(args.records_dir)
        man = records_ds.manifest
        if man.stage is not None and man.stage != tc.stage:
            sys.exit(f"--records_dir {args.records_dir} was packed from "
                     f"stage {man.stage!r} but this run trains stage "
                     f"{tc.stage!r} — pack the right stage or drop "
                     f"--records_dir")
        # the raw path always trains sintel with the default mixture
        # selector; a pack of a reduced mixture is a DIFFERENT epoch
        if (tc.stage == "sintel" and man.train_ds is not None
                and man.train_ds != DEFAULT_TRAIN_DS):
            sys.exit(f"--records_dir {args.records_dir} was packed with "
                     f"train_ds={man.train_ds!r} but the sintel stage "
                     f"trains the {DEFAULT_TRAIN_DS!r} mixture — repack "
                     f"with the default selector or use the raw loader")
        if (man.image_size is not None
                and tuple(man.image_size) != tuple(tc.image_size)):
            print(f"[records] WARNING: pack was made at image_size "
                  f"{tuple(man.image_size)}, run requests "
                  f"{tuple(tc.image_size)}; the pack-time crop recipe "
                  f"wins (repack to change it)")
        pack_fingerprint = man.fingerprint
    # position of the NEXT global batch to consume (resilience.stream):
    # checkpointed as a sidecar with every save, so --resume continues
    # the exact sample sequence instead of replaying from epoch 0
    stream_pos = StreamPosition()
    # host-consensus primitives (resilience.coord): identity on a single
    # process, one tiny allgather per decision on a multi-host mesh —
    # every failure verdict below (divergence, preemption, resume step)
    # is the SAME on every host, so no host ever rolls back or exits
    # alone into a hung collective. Elastic worlds get a per-epoch
    # namespace (stale rounds from a previous epoch can never collide)
    # and the CLI's consensus timeout, which doubles as the unblock
    # path into reconfiguration when a peer dies mid-exchange.
    coord = (Coordinator(namespace=elastic.coord_namespace(),
                         timeout_s=args.coord_timeout_s)
             if elastic is not None
             else Coordinator(timeout_s=args.coord_timeout_s))
    # hang watchdog (resilience.watchdog): created and started BEFORE
    # the first consensus exchange below, so a peer dying during the
    # startup restore is bounded and stack-dumped like any other hang.
    # Inert at timeout 0.
    wd = HangWatchdog(args.stall_timeout,
                      straggler_factor=args.straggler_factor,
                      label=f"train[{tc.name}]").start()
    if elastic is not None:
        # first stall verdict is handed to the membership runtime (one
        # reconfiguration attempt under a grace window) before the
        # watchdog's exit-98 fallback fires
        wd.on_stall = elastic.notify_stall
    # one throwaway consensus exchange FIRST: coordination-service
    # breakage surfaces here, loudly, before any real verdict depends
    # on it (no-op single-process)
    wd.arm(0, "coord-warmup", steady=False)
    try:
        coord.warmup()
    finally:
        # disarm on the error path too: a raise here skips the loop's
        # finally, and an armed region left over an exception teardown
        # would fire a bogus stall over the real traceback
        wd.disarm()
    # the resume decision must be COLLECTIVE: agree_step is a lockstep
    # exchange, so a host skipping it while peers enter would strand
    # them mid-round. All-hosts-have gates the restore; a MIXED mesh
    # (some hosts have checkpoints, some lost theirs) refuses: starting
    # fresh over a stale directory would silently collide with the old
    # run's step numbers (orbax no-ops a save onto an existing step
    # dir), splicing old state into the new run at the first rollback.
    # short-circuit on args.resume: latest_step constructs a cached
    # manager with create=True, and a non-resume run must not turn the
    # probe into a mkdir (checkpoint._fs_steps documents the hazard)
    have_ckpt = args.resume and ckpt.latest_step(ckpt_dir) is not None
    all_have = args.resume and not coord.any_flag(not have_ckpt)
    have_any = args.resume and coord.any_flag(have_ckpt)
    if have_any and not all_have:
        sys.exit(f"[resume] checkpoints under {ckpt_dir} exist on "
                 f"{'this host' if have_ckpt else 'a peer host'} but "
                 f"not on every host — resuming would desync the mesh, "
                 f"and training fresh over a stale directory would "
                 f"splice the old run's checkpoints into this one; "
                 f"restore or clear the checkpoint directories so all "
                 f"hosts agree, or drop --resume and use a fresh "
                 f"--name/--output")
    if all_have:
        # verified restore: a truncated/poisoned newest step falls back
        # to the previous one with a message instead of crashing here.
        # Multi-host: agree_step pins every host to the SAME restored
        # step (min over hosts of what each disk verifiably holds), so
        # a restart never straddles two checkpoints. clean_debris: the
        # trainer owns this directory's writes — crashed-flush tmp
        # dirs are swept here.
        wd.arm(0, "resume-restore", steady=False)
        try:
            state, last_saved = coord.agree_step(
                lambda bound: restore_verified(ckpt_dir, state, step=bound,
                                               clean_debris=True),
                None)
        finally:
            wd.disarm()
        try:
            pos = load_position(ckpt_dir, last_saved, seed=tc.seed,
                                loader_kind=loader_kind,
                                fingerprint=pack_fingerprint)
        except LoaderKindMismatch as e:
            sys.exit(f"[resume] {e}")
        if pos is not None:
            stream_pos = pos
        if prune_above_restore:
            # elastic re-entry after a reconfiguration: a zombie flush
            # from the lost world may still commit a step ABOVE this
            # agreement; later restores must never land on it, and the
            # new segment's own saves must not no-op onto stale dirs
            from dexiraft_tpu.resilience import prune_steps_above

            prune_steps_above(ckpt_dir, last_saved)
        print(f"Resumed full state at step "
              f"{int(jax.device_get(state.step))} "
              f"(data stream: epoch {stream_pos.epoch}, "
              f"batch {stream_pos.offset})")
    elif args.restore_ckpt:
        ckpt.require_checkpoints(args.restore_ckpt)
        prev = ckpt.restore_checkpoint(args.restore_ckpt, state)
        merged, skipped = ckpt.restore_params_into(
            state.params, prev.params, verbose=True,
            skipped_report_dir=osp.join(args.log_dir, tc.name))
        state = state.replace(params=merged, batch_stats=prev.batch_stats)
        print(f"Partial restore from {args.restore_ckpt} "
              f"({len(skipped)} leaves fresh)")

    loader_kwargs = dict(
        seed=tc.seed, num_workers=args.num_workers,
        worker_mode=args.worker_mode, mp_start_method="spawn",
        process_index=jax.process_index(), process_count=jax.process_count())
    if records_ds is not None:
        from dexiraft_tpu.data.records import RecordLoader

        man = records_ds.manifest
        print(f"Training with {len(records_ds)} packed samples "
              f"({man.num_records} records in {len(man.shards)} shard(s), "
              f"fingerprint {man.fingerprint[:12]})")
        loader = RecordLoader(records_ds, tc.batch_size, **loader_kwargs)
    else:
        dataset = fetch_dataset(tc.stage, tc.image_size,
                                edge_root=args.edge_root)
        print(f"Training with {len(dataset)} image pairs")
        loader = Loader(dataset, tc.batch_size, **loader_kwargs)
    batches_per_epoch = max(len(loader), 1)

    step_fn = make_train_step(cfg, tc, mesh=mesh)
    logger = Logger(tc.sum_freq, log_dir=osp.join(args.log_dir, tc.name),
                    model_iters=tc.iters, pipeline_stats=loader.stats)
    # fsdp: validation's eval step compiles WITHOUT the train step's
    # gather fences, so it must never see fsdp-sharded params — gather
    # explicitly (sanctioned host window; layout.gather_state is a
    # no-op on replicated leaves / non-fsdp meshes)
    validate = _make_validators(
        cfg, tc.validation,
        (lambda: layout.gather_state(state.variables, mesh)) if fsdp_live
        else (lambda: state.variables))

    prof_start, prof_stop = args.profile_steps or (-1, -1)
    prof_dir = osp.join(args.log_dir, tc.name, "profile")
    prof_active = False

    from dexiraft_tpu.train.guard import DivergenceGuard

    total_steps = int(jax.device_get(state.step))
    guard = DivergenceGuard(args.guard_threshold, args.max_rollbacks)

    # runtime guard mode (analysis/guards.py): --strict arms the
    # transfer guard + recompile sentinel AFTER the first step — warmup's
    # compile (and its constant transfers) is legal; from then on the
    # steady-state contract holds: zero recompiles, explicit transfers
    # only. This is guards.strict_mode() unrolled, because the loop
    # needs mark_warm/check at phase boundaries (warmup, validation)
    # that a single `with` region cannot express. Non-strict runs keep
    # the observe-only watch so drift still surfaces as a one-line
    # warning on the guard cadence.
    import contextlib

    from dexiraft_tpu.analysis import guards as jaxguards

    guard_stack = contextlib.ExitStack()
    watch: Optional[jaxguards.RecompileWatch] = None
    # bound to ckpt_dir: --keep_best scores persist in
    # <ckpt_dir>/retention.json, so a preempted-and-resumed run still
    # knows which old step is the best and keeps protecting it
    retention = RetentionPolicy(args.keep, args.keep_best,
                                directory=ckpt_dir)
    metrics = None
    preempted = False

    def note_flush(info) -> None:
        """Surface one committed (or failed) async flush in the logger:
        blocked_s is what the step loop actually paid, flush_s the work
        that overlapped training — the async-save win is their ratio."""
        if not info:
            return
        print(f"[ckpt] step {info['step']}: flush {info['flush_s']*1e3:.0f}"
              f" ms, train blocked {info['blocked_s']*1e3:.0f} ms"
              + (f" (FLUSH FAILED: {info['error']})" if info["error"]
                 else ""))
        logger.write_dict({"ckpt/save_blocked_s": info["blocked_s"],
                           "ckpt/flush_s": info["flush_s"]},
                          step=info["step"])

    def save_with_position(step: int, block: bool = False) -> None:
        """Checkpoint + stream-position sidecar + retention GC, as one
        operation — every save leaves a resumable, bounded directory.

        The checkpoint flush is ASYNC: the previous save's flush is
        barriered out first (wait_pending — its blocked/flush times go
        to the logger), retention GC runs against the committed
        directory, and only then is the new flush handed off; training
        overlaps it until the next barrier (save / validation window /
        rollback / exit). The guard verdict was taken by the caller
        BEFORE this runs, so a poisoned state is never handed off.
        block=True (emergency/final save) commits before returning."""
        nonlocal last_saved
        # checkpoint I/O is a sanctioned host sync — exempt from the
        # strict transfer guard, and from the recompile sentinel: the
        # fsdp per-shard snapshot compiles a one-time device copy per
        # leaf shape (train/checkpoint._host_snapshot), which the
        # end-of-run strict verdict must not read as steady-state drift
        ctx = (watch.sanctioned() if watch is not None
               else contextlib.nullcontext())
        with ctx, jax.transfer_guard("allow"):
            note_flush(ckpt.wait_pending(ckpt_dir))
            # GC BEFORE the new handoff: delete_step barriers on any
            # in-flight flush, so GC after would serialize save+GC and
            # surrender the overlap
            retention.apply(ckpt_dir, protect=(last_saved,))
            ckpt.save_checkpoint(ckpt_dir, state, step=step, block=False)
            save_position(ckpt_dir, step, stream_pos, seed=tc.seed,
                          loader_kind=loader_kind,
                          fingerprint=pack_fingerprint)
            if block:
                info = ckpt.wait_pending(ckpt_dir)
                note_flush(info)
                if info and info["error"]:
                    # an emergency/final save that did not commit must
                    # not be reported (or bookkept) as a checkpoint
                    raise RuntimeError(
                        f"checkpoint flush of step {step} failed: "
                        f"{info['error']}")
        last_saved = step

    # fault injection for the chaos tests/smoke: a real signal/fault
    # fired at a pinned step, flowing through the real recovery paths
    chaos_step = None
    if args.chaos:
        from dexiraft_tpu.resilience import chaos as chaos_lib

        chaos_step = chaos_lib.parse_spec(args.chaos)

    # device-side double buffering: batch N+1 is device_put with the
    # step's input shardings while step N runs — the synchronous
    # host->device hop leaves the critical path (data/prefetch.py).
    # The stream starts at the checkpointed position (exact resume).
    batches = prefetch_to_device(
        loader.batches(start_epoch=stream_pos.epoch,
                       start_offset=stream_pos.offset),
        mesh, depth=tc.prefetch_depth, pipeline_stats=loader.stats)
    preempt = PreemptionHandler()
    try:
        with preempt, mesh:
            # NOT armed over the first iteration: it contains the XLA
            # compile, whose minutes would either trip a steady-state
            # stall_timeout or deaden the straggler EWMA. The watchdog
            # arms once the steady-state contract does (watch warmup).
            for batch in batches:
                if elastic is not None:
                    # membership verdict check: lock-and-read local
                    # state (the RPCs live on the lease thread), raising
                    # ReconfigureNeeded/ElasticFallback out of this
                    # segment at a step boundary
                    elastic.poll()
                # range-based (not equality) so resumed runs landing inside
                # the window still profile, and stop only pairs with a start
                if (not prof_active and prof_start <= total_steps < prof_stop):
                    jax.profiler.start_trace(prof_dir)
                    prof_active = True
                state, metrics = step_fn(state, batch)
                total_steps += 1
                first_iteration = watch is None
                if first_iteration:
                    # the first step of this process just compiled —
                    # arm the steady-state contract from here (the
                    # watchdog included: its timeout is sized for
                    # steps, not compiles)
                    watch = jaxguards.RecompileWatch(f"train[{tc.name}]")
                    watch.mark_warm()
                    if args.strict:
                        guard_stack.enter_context(
                            jax.transfer_guard("disallow"))
                    wd.arm(total_steps + 1, "step+data")
                # note: advanced on CONSUMPTION, never rewound by a
                # rollback — the stream continues past a divergent
                # window instead of replaying it. The loader publishes
                # each yielded batch's true (epoch, offset), so batches
                # it dropped (zero survivors) can never desync the
                # checkpointed position from the actual stream
                epoch_b, offset_b = loader.positions.popleft()
                stream_pos = StreamPosition(epoch_b, offset_b).advance(
                    1, batches_per_epoch)
                logger.push(metrics)
                if chaos_step is not None:
                    chaos_step(total_steps)
                if prof_active and total_steps >= prof_stop:
                    jax.block_until_ready(metrics["loss"])
                    jax.profiler.stop_trace()
                    prof_active = False
                    print(f"[profile] trace -> {prof_dir}")

                # divergence guard: checked on its own cadence AND before
                # every checkpoint write, so a poisoned state is never saved
                if not args.no_guard and (
                        total_steps % args.guard_every == 0
                        or total_steps % tc.val_freq == 0):
                    loss_v = float(jax.device_get(metrics["loss"]))
                    # state_finite is the step's POST-update verdict — the
                    # loss alone certifies only the PRE-update params, not
                    # the state the checkpoint below would save
                    state_ok = bool(jax.device_get(
                        metrics.get("state_finite", True)))
                    # a poisoned verdict on ANY host rolls back ALL
                    # hosts — one host restoring alone while its peers
                    # keep stepping is a hung collective, not a
                    # recovery (identity single-process)
                    poisoned_here = guard.poisoned(loss_v, state_ok)
                    if coord.any_flag(poisoned_here):
                        # the agreed target: the newest step EVERY host
                        # has saved (-1 encodes "nothing saved yet", and
                        # min() makes any such host abort the mesh)
                        agreed = coord.min_int(
                            last_saved if last_saved is not None else -1)
                        target = None if agreed < 0 else agreed
                        guard.consume_rollback(
                            loss_v, state_ok, f"step {total_steps}"
                            + ("" if poisoned_here
                               else " (verdict from a peer host)"),
                            target, ckpt_dir=ckpt_dir)
                        # verified restore: should the rollback target
                        # itself turn out damaged, fall back further
                        # rather than crash mid-recovery — and re-agree
                        # across hosts until everyone restored the SAME
                        # step. Restore is sanctioned host I/O (strict-
                        # guard exempt); the guard must not turn
                        # recovery into a second failure.
                        wd.disarm(feed_ewma=False)
                        wd.arm(total_steps, "rollback-restore", steady=False)
                        with jax.transfer_guard("allow"):
                            state, last_saved = coord.agree_step(
                                lambda b: restore_verified(
                                    ckpt_dir, state, step=b,
                                    clean_debris=True),
                                target)
                        # the restored state has no fresh metrics; leaving
                        # the poisoned step's here would make the END-OF-RUN
                        # guard below veto the final save of a GOOD state
                        metrics = None
                        # printed AFTER the restore with the step it
                        # actually landed on — a verified fallback past
                        # the nominal target must not tell the operator
                        # to inspect a checkpoint that was never used
                        print(f"[guard] loss {loss_v:.4g} "
                              f"(state_finite={state_ok}, "
                              f"poisoned_here={poisoned_here}) at step "
                              f"{total_steps}; restored {ckpt_dir} step "
                              f"{last_saved} (rollback {guard.rollbacks}/"
                              f"{args.max_rollbacks})")
                        # relative rewind: the logger's counter is per-run
                        # (starts at 0 on resume), so subtract the rolled-
                        # back window rather than assigning the global step
                        logger.rewind(logger.total_steps
                                      - (total_steps - last_saved))
                        total_steps = last_saved
                        wd.disarm(feed_ewma=False)
                        wd.arm(total_steps + 1, "step+data")
                        continue  # never checkpoint on a rollback step

                # recompile sentinel, on the same cadence as the guard:
                # strict raises, non-strict warns once (satellite: drift
                # surfaces even when --strict is off)
                if total_steps % args.guard_every == 0:
                    if args.strict:
                        watch.check()
                    else:
                        watch.warn_if_drifted()

                # preemption is a COLLECTIVE verdict: one host's SIGTERM
                # must stop every host at the same step (a lone host
                # saving-and-exiting strands its peers in the next
                # collective). Single-process: the local flag, checked
                # every step, exactly as before; multi-host: one tiny
                # allgather every --coord_every steps.
                if coord.size == 1:
                    stop_now = preempt.triggered
                else:
                    stop_now = (total_steps % args.coord_every == 0
                                and coord.any_flag(preempt.triggered))
                if stop_now:
                    # graceful preemption: ONE emergency save at the
                    # step boundary (guard-checked — preemption is not a
                    # license to persist a poisoned state), then leave
                    # the loop; the position sidecar makes the later
                    # --resume continue the exact sample sequence
                    preempted = True
                    wd.disarm(feed_ewma=False)
                    wd.arm(total_steps, "emergency-save", steady=False)
                    if args.on_preempt == "save":
                        poisoned = False
                        if not args.no_guard and metrics is not None:
                            loss_v = float(jax.device_get(metrics["loss"]))
                            state_ok = bool(jax.device_get(
                                metrics.get("state_finite", True)))
                            poisoned = guard.poisoned(loss_v, state_ok)
                        # the save is all-hosts-or-none (orbax's save is
                        # itself collective): one host's poison vetoes
                        # the emergency save everywhere
                        if coord.any_flag(poisoned):
                            print(f"[preempt] state at step {total_steps} "
                                  f"is poisoned; NOT saving — latest good "
                                  f"checkpoint remains step {last_saved}")
                        else:
                            # block: the process exits right after — the
                            # flush must commit before it does
                            save_with_position(total_steps, block=True)
                            print(f"[preempt] emergency checkpoint: "
                                  f"{ckpt_dir} step {total_steps} (data "
                                  f"stream epoch {stream_pos.epoch}, batch "
                                  f"{stream_pos.offset}); resume with "
                                  f"--resume")
                    else:
                        print(f"[preempt] --on_preempt abort: stopping "
                              f"without saving (latest checkpoint: step "
                              f"{last_saved})")
                    break

                in_val_window = total_steps % tc.val_freq == 0
                if in_val_window:
                    # the step part of this iteration is done: feed its
                    # duration to the straggler EWMA (not on the first
                    # iteration — its armed window is partial) and
                    # re-arm over the sanctioned (slow)
                    # checkpoint+validation stretch
                    wd.disarm(feed_ewma=not first_iteration)
                    wd.arm(total_steps, "checkpoint+validation",
                           steady=False)
                    save_with_position(total_steps)
                    # grow-at-checkpoint: absorption is a COLLECTIVE
                    # decision (any_flag), so every incumbent leaves
                    # this segment at the same boundary; the segment
                    # loop commits the in-flight save, absorbs the
                    # joiners, and re-enters in the larger world
                    if elastic is not None and coord.any_flag(
                            bool(elastic.pending_joins())):
                        raise _GrowBoundary(total_steps)
                    # validation is a sanctioned window: its eval steps
                    # compile once per set (absorbed by mark_warm below)
                    # and its dataset readers are host-side by design
                    with jax.transfer_guard("allow"):
                        if tc.validation:
                            # barrier before the validation window —
                            # the resilience contract's barrier set
                            # (save/validation/rollback/GC/exit), kept
                            # deliberately even though it trades away
                            # flush-over-validation overlap: validation
                            # notes retention scores for the step being
                            # flushed, and a window where --keep_best
                            # ranks a checkpoint whose flush later
                            # FAILS would protect a step that does not
                            # exist. Runs without validation sets keep
                            # the full overlap.
                            note_flush(ckpt.wait_pending(ckpt_dir))
                        for vname in tc.validation:
                            results = validate(vname)
                            logger.write_dict(results, step=total_steps)
                            # retention's quality signal: the first
                            # EPE-like scalar of the FIRST validation set
                            # (lower = better) ranks this checkpoint for
                            # --keep_best
                            if vname == tc.validation[0] and results:
                                epe_keys = [k for k in results
                                            if "epe" in k or k == vname]
                                if epe_keys:
                                    retention.note_score(
                                        total_steps, results[epe_keys[0]])
                    watch.mark_warm()
                if total_steps >= tc.num_steps:
                    break
                # close this iteration's armed window (a validation
                # window stays out of the step-time EWMA, and the
                # first iteration's partial mid-body arm never seeds
                # it) and open the next — the re-arm also covers the
                # prefetch fetch between iterations. A first iteration
                # that landed on a val window still re-arms here, so
                # the non-steady validation region never leaks over
                # the next iteration.
                if not first_iteration or in_val_window:
                    wd.disarm(feed_ewma=not in_val_window)
                    wd.arm(total_steps + 1, "step+data")
    finally:
        # stop the host pipeline — on the happy path AND when the loop
        # dies (interrupt, OOM, failed restore): the Loader's feeder
        # thread / worker pool must not outlive the loop, and the
        # in-flight prefetched device batches have no work left to do
        # while validation and the final save run below
        batches.close()
        # disarm the transfer guard WITH the loop (also on the error
        # path — a leaked 'disallow' would poison later jax use in this
        # process); the final save below is host I/O, not steady state
        guard_stack.close()
        # the monitor must not outlive the loop: the exit path below is
        # host I/O whose duration has nothing to do with step progress
        wd.stop()
    if prof_active:  # window extended past the last step: finalize
        jax.profiler.stop_trace()
        print(f"[profile] trace (truncated at end of run) -> {prof_dir}")
    # the final save honors the guard too: a nan that arrives between
    # guard checks and the end of the run must not become the latest
    # checkpoint that --resume/eval would silently load. A preempted
    # run already made its one emergency save (or declined to) inside
    # the loop.
    final_ok = not preempted
    if final_ok and not args.no_guard and metrics is not None:
        loss_v = float(jax.device_get(metrics["loss"]))
        state_ok = bool(jax.device_get(metrics.get("state_finite", True)))
        if guard.poisoned(loss_v, state_ok):
            final_ok = False
            print(f"[guard] final state poisoned (loss {loss_v:.4g}, "
                  f"state_finite={state_ok}); "
                  f"skipping the final save — latest good checkpoint "
                  f"remains step {last_saved}")
    if final_ok:
        # block: this is the exit barrier — the process must not return
        # control with a flush still in flight
        save_with_position(total_steps, block=True)
    else:
        # even a vetoed final save barriers out any in-flight flush of
        # an earlier GOOD state before the process exits
        with jax.transfer_guard("allow"):
            note_flush(ckpt.wait_pending(ckpt_dir))
    cstats = ckpt.save_stats(ckpt_dir)
    if cstats.get("saves"):
        print(f"[ckpt] {cstats['saves']} async save(s): total flush "
              f"{cstats['total_flush_s']:.2f}s overlapped, total train "
              f"blocked {cstats['total_blocked_s']:.2f}s"
              + (f", {cstats['failed']} FAILED" if cstats.get("failed")
                 else ""))
    if wd.enabled and wd.straggler_warnings:
        print(f"[watchdog] {wd.straggler_warnings} straggler warning(s) "
              f"this run (EWMA step {wd.ewma_s:.2f}s)")
    logger.close()
    print(f"[prefetch] {batches.summary()}")
    if loader.stats.faults:
        print(f"[pipeline] {loader.stats.summary()}")
    # end-of-run sentinel verdict: strict fails the run on any
    # unabsorbed post-warmup compile; non-strict gets the (once-only)
    # drift warning if the cadence check never fired
    if watch is not None:
        if args.strict:
            watch.check()
        else:
            watch.warn_if_drifted()
    if preempted:
        print(f"Preempted ({preempt.signal_name}) at step {total_steps} "
              f"-> {ckpt_dir}")
    else:
        print(f"Done: {total_steps} steps -> {ckpt_dir}")


def _elastic_main(cfg: RAFTConfig, tc: TrainConfig, args) -> None:
    """The elastic segment loop: each train() call is one membership
    epoch; membership verdicts unwind it, the world is reconfigured
    (shrink on loss, grow at checkpoint boundaries), and the next
    segment re-enters with resume semantics in the new world. Only the
    cases elastic cannot absorb — rank-0 loss, a cascade below
    --min_hosts, a failed agreement — exit 98, the watchdog's
    restart-the-pod contract."""
    import os
    import os.path as osp

    from dexiraft_tpu.data.loader import world_compatible
    from dexiraft_tpu.parallel.distributed import _env_int
    from dexiraft_tpu.resilience import (
        CoordinatorTimeout,
        ElasticConfig,
        ElasticFallback,
        MembershipRuntime,
        ReconfigureNeeded,
    )
    from dexiraft_tpu.resilience.watchdog import STALL_EXIT_CODE
    from dexiraft_tpu.train import checkpoint as ckpt

    ckpt_dir = osp.join(args.output, tc.name)
    ecfg = ElasticConfig(
        # how peers dial THIS host (the coordination service binds here
        # when this host becomes an epoch's rank 0)
        host=os.environ.get("DEXIRAFT_ELASTIC_HOST", "127.0.0.1"),
        # the one channel that exists before a joiner has KV access:
        # the shared checkpoint filesystem
        board_dir=osp.join(ckpt_dir, "membership"),
        min_hosts=args.min_hosts,
        global_batch=tc.batch_size,
        # survivors may arrive at the agreement only after their own
        # consensus op times out against the dead peer
        reconfig_timeout_s=max(30.0, args.coord_timeout_s * 2),
    )
    mrt = MembershipRuntime(ecfg)
    try:
        if args.join:
            mrt.join(args.join)
            args.resume = True  # a joiner always enters via restore
        else:
            addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
            if addr is None:
                # solo incumbent: a one-host elastic world whose whole
                # point is absorbing joiners later
                addr = "127.0.0.1:7639"
                num, pid = 1, 0
            else:
                num = _env_int("JAX_NUM_PROCESSES")
                pid = _env_int("JAX_PROCESS_ID")
            mrt.bootstrap(addr, num, pid)
        prune = False
        while True:
            reason = world_compatible(tc.batch_size, mrt.size)
            if reason is not None:  # pre-checked by reconfigure; belt+braces
                raise ElasticFallback(reason)
            try:
                train(cfg, tc, args, elastic=mrt,
                      prune_above_restore=prune)
                return
            except (ReconfigureNeeded, CoordinatorTimeout) as verdict:
                print(f"[elastic] segment ended at epoch {mrt.epoch}: "
                      f"{verdict}", flush=True)
                mrt.reconfigure(dead=getattr(verdict, "dead", None))
                prune = True
            except _GrowBoundary as g:
                # commit the boundary's in-flight save before the
                # graceful teardown, so the joiners restore it
                ckpt.wait_pending(ckpt_dir)
                print(f"[elastic] absorbing "
                      f"{[j['name'] for j in mrt.pending_joins()]} at "
                      f"step {g.step}", flush=True)
                mrt.absorb_joins()
                prune = False
            args.resume = True  # every later segment enters via restore
    except ElasticFallback as e:
        print(f"[elastic] fallback to pod restart: {e}", flush=True)
        raise SystemExit(STALL_EXIT_CODE)
    finally:
        mrt.close()


def main(argv=None) -> None:
    from dexiraft_tpu.parallel.distributed import initialize

    args = build_parser().parse_args(argv)
    if args.coord_every < 1:
        sys.exit("train: --coord_every must be >= 1 (it is a step "
                 "modulus; there is no 'never poll' mode — preemption "
                 "broadcast is what keeps a multi-host mesh exiting "
                 "together)")
    if args.coord_timeout_s <= 0:
        sys.exit("train: --coord_timeout_s must be > 0 (a consensus op "
                 "with no timeout hangs the pod on the first dead peer)")
    cfg, tc = resolve_configs(args)
    if args.elastic or args.join:
        # elastic owns runtime initialization (per membership epoch);
        # the plain initialize() path must not claim the process first
        _elastic_main(cfg, tc, args)
        return
    initialize()  # no-op single-process; multi-host via env vars
    train(cfg, tc, args)


if __name__ == "__main__":
    main(sys.argv[1:])
