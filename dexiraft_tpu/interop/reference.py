"""Build the reference torch stack (when its checkout is mounted).

Interop tooling used by the parity tests and the measured-anchor script:
constructs the reference's v5 RAFT (core/raft.py) with a random-init
embedded DexiNed, working around two reference realities:

  * RAFT.__init__ hard-loads a DexiNed checkpoint from a path that ships
    outside the repo (core/raft.py:30-33) — torch.load is patched for
    the duration of construction and fed a freshly initialized DexiNed
    state dict instead;
  * the reference modules import each other by bare name (``from raft
    import RAFT`` etc.), so its directories go on sys.path temporarily.

Nothing here imports at package-import time; call sites pay the torch
import. Raises FileNotFoundError when the checkout is not mounted.
"""

from __future__ import annotations

import argparse
import importlib
import os.path as osp
import sys

REF_ROOT = "/root/reference"
REF_CORE = osp.join(REF_ROOT, "core")


def _is_reference_module(mod) -> bool:
    file = getattr(mod, "__file__", None)
    if file and file.startswith(REF_ROOT):
        return True
    # namespace packages (e.g. 'DexiNed') carry no __file__, only paths
    return any(str(p).startswith(REF_ROOT)
               for p in getattr(mod, "__path__", ()))


def _import_from(path: str, module: str):
    """Import ``module`` from ``path`` without leaking the reference's
    generically-named modules into sys.modules.

    The reference imports its siblings by bare name ('model', 'raft',
    'update', 'utils', ...). Left cached, a later unrelated ``import
    model`` anywhere in the process would silently receive the
    reference's — so after the import every sys.modules entry that
    resolves into the reference tree is evicted (and any pre-existing
    entry it shadowed is restored). The module objects we return stay
    alive through the references we hold; their internal imports were
    already resolved at import time.
    """
    before = dict(sys.modules)
    sys.path.insert(0, path)
    try:
        return importlib.import_module(module)
    finally:
        sys.path.remove(path)
        for name, mod in list(sys.modules.items()):
            if name in before and mod is before[name]:
                continue  # untouched pre-existing entry
            if _is_reference_module(mod):
                if name in before:
                    sys.modules[name] = before[name]
                else:
                    del sys.modules[name]


def build_reference_v5(dexi_seed: int = 7):
    """Reference v5 RAFT (eval mode) with seeded random DexiNed weights.

    Returns the torch module. Deterministic for a given ``dexi_seed``
    (the RAFT weights themselves come from torch.manual_seed state set
    here too, so two calls with the same seed build identical models).

    NOT thread-safe: torch.load is patched process-globally for the
    duration of construction (the reference hard-loads a checkpoint
    path that ships outside its repo) — call from one thread only.
    """
    if not osp.isdir(REF_CORE):
        raise FileNotFoundError(f"reference checkout not at {REF_CORE}")
    import torch

    TorchDexiNed = _import_from(
        osp.join(REF_CORE, "DexiNed"), "model").DexiNed
    torch.manual_seed(dexi_seed)
    dexi_sd = TorchDexiNed().state_dict()

    orig_load = torch.load
    torch.load = lambda *a, **k: dexi_sd
    try:
        TorchRAFTv5 = _import_from(REF_CORE, "raft").RAFT
        model = TorchRAFTv5(argparse.Namespace(
            small=False, dropout=0.0, mixed_precision=False,
            alternate_corr=False))
    finally:
        torch.load = orig_load
    model.eval()
    return model
