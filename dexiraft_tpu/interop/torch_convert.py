"""torch .pth -> flax variables converter (SURVEY.md §7 hard part 6).

Lets reference checkpoints (e.g. the frozen DexiNed `14_model.pth` that
core/raft.py:30-33 embeds) run in this framework without retraining, and
provides the numerical parity bridge used by the interop tests.

Layout rules:
  conv weight           OIHW -> HWIO             transpose (2, 3, 1, 0)
  conv-transpose weight (in, out, kH, kW) -> flax (kH, kW, out, in-group)
                        with spatial flip (torch's ConvTranspose2d is the
                        gradient of a strided conv; flax's ConvTranspose
                        is a true fractionally-strided conv, so the kernel
                        must be mirrored — validated by the parity test)
  bn weight/bias        -> params scale/bias
  bn running_mean/var   -> batch_stats mean/var
  num_batches_tracked   dropped

The name map is explicit (reference attribute names -> our flax
auto-numbered module paths, derived from identical construction order in
models/dexined.py) and every converted leaf is shape-checked, so a drift
in either architecture fails loudly rather than silently misloading.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Tuple

import numpy as np

# reference attribute -> our module path (models/dexined.py call order)
_DEXINED_BLOCKS = {
    "block_1": "DoubleConvBlock_0",
    "block_2": "DoubleConvBlock_1",
    "dblock_3": "DenseBlock_0",
    "dblock_4": "DenseBlock_1",
    "dblock_5": "DenseBlock_2",
    "dblock_6": "DenseBlock_3",
    "side_1": "SingleConvBlock_0",
    "side_2": "SingleConvBlock_1",
    "side_3": "SingleConvBlock_3",
    "side_4": "SingleConvBlock_5",
    "side_5": "side_5",
    "pre_dense_3": "SingleConvBlock_2",
    "pre_dense_4": "SingleConvBlock_4",
    "pre_dense_5": "SingleConvBlock_6",
    "pre_dense_6": "SingleConvBlock_7",
    "block_cat": "SingleConvBlock_8",
    "up_block_1": "UpConvBlock_0",
    "up_block_2": "UpConvBlock_1",
    "up_block_3": "UpConvBlock_2",
    "up_block_4": "UpConvBlock_3",
    "up_block_5": "UpConvBlock_4",
    "up_block_6": "UpConvBlock_5",
}


def _to_numpy(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t)


def _set(tree: Dict, path: Tuple[str, ...], value: np.ndarray) -> None:
    node = tree
    for p in path[:-1]:
        node = node.setdefault(p, {})
    node[path[-1]] = value


def _convert_leaf(torch_key: str, sub: str, leaf: str, value: np.ndarray):
    """-> (collection, module, param_name, converted array) or None."""
    if leaf == "num_batches_tracked":
        return None
    if sub.startswith("conv") or sub == "conv":
        if leaf == "weight":
            return "params", f"Conv_{_idx(sub, 'conv')}", "kernel", \
                value.transpose(2, 3, 1, 0)
        return "params", f"Conv_{_idx(sub, 'conv')}", "bias", value
    if sub.startswith(("bn", "norm")) or sub == "bn":
        base = "bn" if sub.startswith("bn") else "norm"
        mod = f"BatchNorm_{_idx(sub, base)}"
        if leaf == "weight":
            return "params", mod, "scale", value
        if leaf == "bias":
            return "params", mod, "bias", value
        if leaf == "running_mean":
            return "batch_stats", mod, "mean", value
        if leaf == "running_var":
            return "batch_stats", mod, "var", value
    raise KeyError(f"unhandled torch key {torch_key!r}")


def _idx(name: str, base: str) -> int:
    """conv -> 0, conv1 -> 0, conv2 -> 1, bn2 -> 1, norm1 -> 0 ..."""
    suffix = name[len(base):]
    return int(suffix) - 1 if suffix else 0


def _convert_upblock_leaf(feat_idx: int, leaf: str, value: np.ndarray):
    """UpConvBlock torch Sequential indices: 0,3,6,... are 1x1 convs;
    2,5,8,... are ConvTranspose2d (model.py:81-109, conv/relu/deconv
    triplets)."""
    triplet, pos = divmod(feat_idx, 3)
    if pos == 0:  # 1x1 conv
        if leaf == "weight":
            return f"Conv_{triplet}", "kernel", value.transpose(2, 3, 1, 0)
        return f"Conv_{triplet}", "bias", value
    if pos == 2:  # transposed conv: (in, out, kH, kW) -> (kH, kW, in, out),
        # spatially flipped (gradient-of-conv vs fractionally-strided conv)
        if leaf == "weight":
            k = value.transpose(2, 3, 0, 1)[::-1, ::-1]
            return f"ConvTranspose_{triplet}", "kernel", np.ascontiguousarray(k)
        return f"ConvTranspose_{triplet}", "bias", value
    raise KeyError(f"unexpected UpConvBlock feature index {feat_idx}")


def convert_dexined_state_dict(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """Reference DexiNed state_dict -> {'params': ..., 'batch_stats': ...}."""
    out: Dict[str, Any] = {"params": {}, "batch_stats": {}}
    for key, raw in state_dict.items():
        value = _to_numpy(raw).astype(np.float32)
        parts = key.split(".")
        block = parts[0]
        if block not in _DEXINED_BLOCKS:
            raise KeyError(f"unknown DexiNed block {block!r} in {key!r}")
        ours = _DEXINED_BLOCKS[block]

        if block.startswith("up_block"):
            assert parts[1] == "features", key
            mod, name, conv = _convert_upblock_leaf(
                int(parts[2]), parts[3], value)
            _set(out["params"], (ours, mod, name), conv)
            continue

        if block.startswith("dblock"):
            # dblock_k.denselayer{j}.{conv|norm}{i}.{leaf}
            layer = f"DenseLayer_{int(parts[1].removeprefix('denselayer')) - 1}"
            res = _convert_leaf(key, parts[2], parts[3], value)
            if res is None:
                continue
            coll, mod, name, conv = res
            _set(out[coll], (ours, layer, mod, name), conv)
            continue

        res = _convert_leaf(key, parts[1], parts[2], value)
        if res is None:
            continue
        coll, mod, name, conv = res
        _set(out[coll], (ours, mod, name), conv)
    return out


def verify_against(template: Mapping[str, Any],
                   converted: Mapping[str, Any]) -> None:
    """Assert converted tree paths/shapes exactly match a model-init
    template (strict load — unlike restore_params_into)."""
    import jax

    t_flat = {jax.tree_util.keystr(k): v.shape for k, v in
              jax.tree_util.tree_flatten_with_path(template)[0]}
    c_flat = {jax.tree_util.keystr(k): v.shape for k, v in
              jax.tree_util.tree_flatten_with_path(dict(converted))[0]}
    missing = sorted(set(t_flat) - set(c_flat))
    extra = sorted(set(c_flat) - set(t_flat))
    bad = [k for k in t_flat.keys() & c_flat.keys()
           if tuple(t_flat[k]) != tuple(c_flat[k])]
    if missing or extra or bad:
        raise ValueError(
            f"conversion mismatch: missing={missing[:5]} extra={extra[:5]} "
            f"shape={[(k, t_flat[k], c_flat[k]) for k in bad[:5]]}")


# ---------------------------------------------------------------------------
# RAFT (core/raft.py family)
# ---------------------------------------------------------------------------

# update_block.* -> ScanRAFTStep_0.BasicUpdateBlock_0.* (full model)
_UPDATE_BLOCK_FULL = {
    "encoder.convc1": ("BasicMotionEncoder_0", "Conv_0"),
    "encoder.convc2": ("BasicMotionEncoder_0", "Conv_1"),
    "encoder.convf1": ("BasicMotionEncoder_0", "Conv_2"),
    "encoder.convf2": ("BasicMotionEncoder_0", "Conv_3"),
    "encoder.conv": ("BasicMotionEncoder_0", "Conv_4"),
    "gru.convz1": ("SepConvGRU_0", "Conv_0"),
    "gru.convr1": ("SepConvGRU_0", "Conv_1"),
    "gru.convq1": ("SepConvGRU_0", "Conv_2"),
    "gru.convz2": ("SepConvGRU_0", "Conv_3"),
    "gru.convr2": ("SepConvGRU_0", "Conv_4"),
    "gru.convq2": ("SepConvGRU_0", "Conv_5"),
    "flow_head.conv1": ("FlowHead_0", "Conv_0"),
    "flow_head.conv2": ("FlowHead_0", "Conv_1"),
    "mask.0": ("Conv_0",),
    "mask.2": ("Conv_1",),
}

# small model (SmallUpdateBlock: SmallMotionEncoder + ConvGRU, no mask)
_UPDATE_BLOCK_SMALL = {
    "encoder.convc1": ("SmallMotionEncoder_0", "Conv_0"),
    "encoder.convf1": ("SmallMotionEncoder_0", "Conv_1"),
    "encoder.convf2": ("SmallMotionEncoder_0", "Conv_2"),
    "encoder.conv": ("SmallMotionEncoder_0", "Conv_3"),
    "gru.convz": ("ConvGRU_0", "Conv_0"),
    "gru.convr": ("ConvGRU_0", "Conv_1"),
    "gru.convq": ("ConvGRU_0", "Conv_2"),
    "flow_head.conv1": ("FlowHead_0", "Conv_0"),
    "flow_head.conv2": ("FlowHead_0", "Conv_1"),
}


def _convert_encoder_key(parts, value, small: bool = False):
    """BasicEncoder/SmallEncoder names -> our extractor module paths.

    Stem: conv1 -> Conv_0, norm1 -> BatchNorm_0 (batch-norm encoders only;
    instance norm is parameter-free on both sides), conv2 -> Conv_1.
    layer{L}.{j} -> ResidualBlock_{2(L-1)+j} (full) or
    BottleneckBlock_{...} (small): convN -> Conv_{N-1}, normN ->
    BatchNorm_{N-1}, downsample.0 -> shortcut conv (Conv_2 residual /
    Conv_3 bottleneck), downsample.1 -> shortcut BN. The bare normK that
    aliases downsample.1 (reference registers the same module twice,
    extractor.py) is skipped by the caller when a downsample exists in
    the same block.
    """
    block_cls = "BottleneckBlock" if small else "ResidualBlock"
    shortcut_conv = "Conv_3" if small else "Conv_2"
    shortcut_bn = "BatchNorm_3" if small else "BatchNorm_2"
    sub, leaf = parts[-2], parts[-1]
    if parts[0] == "conv1":
        mod = ("Conv_0",)
    elif parts[0] == "conv2":
        mod = ("Conv_1",)
    elif parts[0] == "norm1":
        mod = ("BatchNorm_0",)
    elif parts[0].startswith("layer"):
        layer = int(parts[0].removeprefix("layer"))
        block = f"{block_cls}_{2 * (layer - 1) + int(parts[1])}"
        if sub == "downsample" or parts[2] == "downsample":
            which = int(parts[3])
            mod = ((block, shortcut_conv) if which == 0
                   else (block, shortcut_bn))
            sub = "conv" if which == 0 else "bn"
        elif parts[2].startswith("conv"):
            mod = (block, f"Conv_{int(parts[2].removeprefix('conv')) - 1}")
        elif parts[2].startswith("norm"):
            mod = (block, f"BatchNorm_{int(parts[2].removeprefix('norm')) - 1}")
        else:
            raise KeyError(f"unhandled encoder key {'.'.join(parts)}")
    else:
        raise KeyError(f"unhandled encoder key {'.'.join(parts)}")

    is_conv = mod[-1].startswith("Conv")
    if is_conv:
        if leaf == "weight":
            return "params", mod + ("kernel",), value.transpose(2, 3, 1, 0)
        return "params", mod + ("bias",), value
    if leaf == "weight":
        return "params", mod + ("scale",), value
    if leaf == "bias":
        return "params", mod + ("bias",), value
    if leaf == "running_mean":
        return "batch_stats", mod + ("mean",), value
    if leaf == "running_var":
        return "batch_stats", mod + ("var",), value
    raise KeyError(f"unhandled encoder leaf {'.'.join(parts)}")


def _block_has_downsample(state_dict, prefix: str) -> bool:
    return any(k.startswith(prefix + ".downsample.") for k in state_dict)


def convert_raft_state_dict(state_dict: Mapping[str, Any],
                            small: bool = False) -> Dict[str, Any]:
    """Reference RAFT state_dict (raft_1..raft_5 family, optional
    'module.' prefix) -> our flax variables.

    Handles fnet/cnet/efnet/ecnet encoders, the shared update block (full
    or small), and an embedded DexiNed (v4/v5) under its 'dexined.'
    prefix.
    """
    state_dict = {k.removeprefix("module."): v for k, v in state_dict.items()}
    out: Dict[str, Any] = {"params": {}, "batch_stats": {}}

    dexined_sub = {k.removeprefix("dexined."): v for k, v in state_dict.items()
                   if k.startswith("dexined.")}
    if dexined_sub:
        dx = convert_dexined_state_dict(dexined_sub)
        out["params"]["DexiNed_0"] = dx["params"]
        out["batch_stats"]["DexiNed_0"] = dx["batch_stats"]

    ub_map = _UPDATE_BLOCK_SMALL if small else _UPDATE_BLOCK_FULL
    ub_root = ("ScanRAFTStep_0",
               "SmallUpdateBlock_0" if small else "BasicUpdateBlock_0")

    for key, raw in state_dict.items():
        if key.startswith("dexined.") or key.endswith("num_batches_tracked"):
            continue
        value = _to_numpy(raw).astype(np.float32)
        parts = key.split(".")
        root = parts[0]

        if root in ("fnet", "cnet", "efnet", "ecnet"):
            # skip the bare normK that aliases downsample.1 (the reference
            # registers the same BN module under both names)
            if (parts[1].startswith("layer")
                    and parts[3].startswith("norm")
                    and _block_has_downsample(state_dict,
                                              ".".join(parts[:3]))
                    and parts[3] == _last_norm(state_dict, ".".join(parts[:3]))):
                continue
            coll, path, conv = _convert_encoder_key(parts[1:], value,
                                                    small=small)
            _set(out[coll], (root,) + path, conv)
            continue

        if root == "update_block":
            sub = ".".join(parts[1:-1])
            leaf = parts[-1]
            if sub not in ub_map:
                raise KeyError(f"unhandled update_block key {key!r}")
            mod = ub_root + ub_map[sub]
            if leaf == "weight":
                _set(out["params"], mod + ("kernel",),
                     value.transpose(2, 3, 1, 0))
            else:
                _set(out["params"], mod + ("bias",), value)
            continue

        raise KeyError(f"unknown RAFT root module {root!r} in {key!r}")
    if not out["batch_stats"]:
        out["batch_stats"] = {}
    return out


def _last_norm(state_dict, block_prefix: str) -> str:
    """Highest-numbered normK inside a residual block (the one the
    reference aliases into downsample.1)."""
    norms = set()
    for k in state_dict:
        if k.startswith(block_prefix + ".norm"):
            norms.add(k[len(block_prefix) + 1:].split(".")[0])
    return max(norms) if norms else ""


def load_raft_pth(path: str, small: bool = False,
                  verify_template=None) -> Dict[str, Any]:
    """Load a reference RAFT .pth (DataParallel-prefixed per
    evaluate.py:221-222) and convert."""
    import torch

    sd = torch.load(path, map_location="cpu")
    if isinstance(sd, dict) and "state_dict" in sd:
        sd = sd["state_dict"]
    converted = convert_raft_state_dict(sd, small=small)
    if verify_template is not None:
        verify_against(verify_template, converted)
    return converted


# ---------------------------------------------------------------------------
# Export: flax variables -> torch state_dict (the inverse bridge: train on
# TPU, hand the checkpoint back to a reference-stack consumer)
# ---------------------------------------------------------------------------


def _probe_mapping(template: Mapping[str, Any], convert_fn) -> Dict[str, Any]:
    """Discover torch-key -> (collection, flax path) through the FORWARD
    converter: run it on constant-filled stand-ins (every layout transform
    it applies — transposes, flips — preserves a constant fill) and read
    each key's destination off the constant. Reusing the converter as the
    single source of truth means export can never drift from import."""
    import jax

    probes, names = {}, {}
    for i, (key, raw) in enumerate(template.items()):
        c = float(i + 1)
        probes[key] = np.full(np.shape(_to_numpy(raw)), c, np.float32)
        names[c] = key
    converted = convert_fn(probes)
    mapping: Dict[str, Any] = {}
    for coll in ("params", "batch_stats"):
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                dict(converted.get(coll, {})))[0]:
            mapping[names[float(leaf.flat[0])]] = (
                coll, tuple(p.key for p in path))
    return mapping


def _export_leaf(path: Tuple[str, ...], value: np.ndarray) -> np.ndarray:
    """Invert the forward layout rules for one leaf."""
    if path[-1] != "kernel":
        return np.asarray(value, np.float32)
    if "ConvTranspose" in path[-2]:
        # forward: (in, out, kH, kW) -> transpose(2, 3, 0, 1) + spatial flip
        return np.ascontiguousarray(
            np.asarray(value, np.float32)[::-1, ::-1].transpose(2, 3, 0, 1))
    return np.ascontiguousarray(
        np.asarray(value, np.float32).transpose(3, 2, 0, 1))


def _fetch(tree: Mapping[str, Any], path: Tuple[str, ...]) -> np.ndarray:
    node: Any = tree
    for p in path:
        node = node[p]
    return np.asarray(node)


def _export_state_dict(variables: Mapping[str, Any],
                       template: Mapping[str, Any],
                       convert_fn) -> Dict[str, np.ndarray]:
    template = dict(template)
    stripped = {k.removeprefix("module."): k for k in template}
    mapping = _probe_mapping(
        {k: template[orig] for k, orig in stripped.items()}, convert_fn)

    out: Dict[str, np.ndarray] = {}
    for key, raw in template.items():
        k = key.removeprefix("module.")
        if k.endswith("num_batches_tracked"):
            out[key] = _to_numpy(raw)  # dropped on import; keep as-is
            continue
        if k not in mapping:
            # the bare normK the reference aliases onto downsample.1
            # (skipped on import); both torch keys carry the same tensor,
            # so export the shortcut-BN twin's value here
            parts = k.split(".")
            twin = ".".join(parts[:3] + ["downsample", "1", parts[-1]])
            if twin not in mapping:
                raise KeyError(f"no flax source for torch key {key!r}")
            coll, path = mapping[twin]
        else:
            coll, path = mapping[k]
        out[key] = _export_leaf(path, _fetch(variables[coll], path))
    return out


def export_raft_state_dict(variables: Mapping[str, Any],
                           template: Mapping[str, Any],
                           small: bool = False) -> Dict[str, np.ndarray]:
    """Flax RAFT variables -> a torch state_dict (numpy values) with the
    template's exact key set — `model.load_state_dict` it after wrapping
    the arrays in torch tensors. Exactly inverts convert_raft_state_dict
    (round-trip pinned bitwise in tests/test_torch_interop.py)."""
    return _export_state_dict(
        variables, template,
        lambda sd: convert_raft_state_dict(sd, small=small))


def export_dexined_state_dict(variables: Mapping[str, Any],
                              template: Mapping[str, Any]
                              ) -> Dict[str, np.ndarray]:
    """Flax DexiNed variables -> a torch state_dict (numpy values)."""
    return _export_state_dict(variables, template,
                              convert_dexined_state_dict)


def load_dexined_pth(path: str, verify_template=None) -> Dict[str, Any]:
    """Load a reference DexiNed .pth and convert; strips an optional
    'module.' DataParallel prefix (evaluate.py:221-222 convention)."""
    import torch

    sd = torch.load(path, map_location="cpu")
    if isinstance(sd, dict) and "state_dict" in sd:
        sd = sd["state_dict"]
    sd = {k.removeprefix("module."): v for k, v in sd.items()}
    converted = convert_dexined_state_dict(sd)
    if verify_template is not None:
        verify_against(verify_template, converted)
    return converted
