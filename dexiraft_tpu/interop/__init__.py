"""Checkpoint interop with the reference's torch .pth state dicts."""

from dexiraft_tpu.interop.torch_convert import (
    convert_dexined_state_dict,
    load_dexined_pth,
)

__all__ = ["convert_dexined_state_dict", "load_dexined_pth"]
