"""Flow-field visualization: Middlebury color wheel (core/utils/flow_viz.py).

The standard Baker et al. encoding: hue = flow direction from a 55-bin
RY/YG/GC/CB/BM/MR wheel, saturation = magnitude (normalized to the frame's
max by default), out-of-range vectors dimmed by 75%.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def make_colorwheel() -> np.ndarray:
    """(55, 3) uint8-range RGB wheel: RY=15 YG=6 GC=4 CB=11 BM=13 MR=6."""
    RY, YG, GC, CB, BM, MR = 15, 6, 4, 11, 13, 6
    wheel = np.zeros((RY + YG + GC + CB + BM + MR, 3))
    ramps = [
        (RY, 0, 1, False),  # red -> yellow: G ramps up
        (YG, 1, 0, True),   # yellow -> green: R ramps down
        (GC, 1, 2, False),  # green -> cyan: B ramps up
        (CB, 2, 1, True),   # cyan -> blue: G ramps down
        (BM, 2, 0, False),  # blue -> magenta: R ramps up
        (MR, 0, 2, True),   # magenta -> red: B ramps down
    ]
    col = 0
    for n, hold, ramp, down in ramps:
        wheel[col:col + n, hold] = 255
        vals = np.floor(255 * np.arange(n) / n)
        wheel[col:col + n, ramp] = 255 - vals if down else vals
        col += n
    return wheel


_WHEEL = make_colorwheel()


def flow_uv_to_colors(u: np.ndarray, v: np.ndarray,
                      convert_to_bgr: bool = False) -> np.ndarray:
    """Map unit-scaled (u, v) to RGB via wheel interpolation."""
    ncols = _WHEEL.shape[0]
    rad = np.sqrt(u ** 2 + v ** 2)
    angle = np.arctan2(-v, -u) / np.pi  # [-1, 1]
    fk = (angle + 1) / 2 * (ncols - 1)
    k0 = np.floor(fk).astype(np.int32)
    k1 = (k0 + 1) % ncols
    f = (fk - k0)[..., None]

    col = (1 - f) * _WHEEL[k0] / 255.0 + f * _WHEEL[k1] / 255.0
    in_range = rad[..., None] <= 1
    col = np.where(in_range, 1 - rad[..., None] * (1 - col), col * 0.75)
    img = np.floor(255 * col).astype(np.uint8)
    return img[..., ::-1] if convert_to_bgr else img


def flow_to_image(flow: np.ndarray, clip_flow: Optional[float] = None,
                  convert_to_bgr: bool = False, rad_max: Optional[float] = None
                  ) -> np.ndarray:
    """(H, W, 2) flow -> (H, W, 3) uint8 visualization.

    rad_max fixes the normalization (for consistent scaling across a
    sequence); default is the frame's own max magnitude.
    """
    flow = np.asarray(flow, np.float32)
    if clip_flow is not None:
        flow = np.clip(flow, 0, clip_flow)
    u, v = flow[..., 0], flow[..., 1]
    rad = np.sqrt(u ** 2 + v ** 2)
    denom = (rad_max if rad_max is not None else rad.max()) + 1e-5
    return flow_uv_to_colors(u / denom, v / denom, convert_to_bgr)
