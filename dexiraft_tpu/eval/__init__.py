"""Evaluation: dataset-metric validators, benchmark submissions, flow viz."""

from dexiraft_tpu.eval.flow_viz import flow_to_image
from dexiraft_tpu.eval.interpolate import forward_interpolate
from dexiraft_tpu.eval.validate import (
    validate_chairs,
    validate_hd1k,
    validate_kitti,
    validate_sintel,
)
from dexiraft_tpu.eval.submission import (
    create_kitti_submission,
    create_sintel_submission,
)

__all__ = [
    "flow_to_image",
    "forward_interpolate",
    "validate_chairs",
    "validate_sintel",
    "validate_kitti",
    "validate_hd1k",
    "create_sintel_submission",
    "create_kitti_submission",
]
