"""Benchmark submission writers (evaluate.py:22-77).

Sintel: per-sequence ordered inference with optional WARM START — the
previous frame's low-res flow is propagated by forward_interpolate and
fed as flow_init (evaluate.py:40-44). Unlike the reference (scipy
griddata on host, a device round-trip per frame), propagation runs
on-device (dexiraft_tpu.eval.interpolate).

KITTI: per-frame 16-bit PNG encoding.
"""

from __future__ import annotations

import os
import os.path as osp
from typing import Callable, Optional, Tuple

import numpy as np

from dexiraft_tpu.data.flow_io import write_flo, write_flow_kitti
from dexiraft_tpu.data.padder import InputPadder
from dexiraft_tpu.eval.interpolate import forward_interpolate

EvalFn = Callable[..., Tuple[np.ndarray, np.ndarray]]


def create_sintel_submission(
    eval_fn: EvalFn,
    output_path: str = "sintel_submission",
    warm_start: bool = False,
    datasets=None,
) -> None:
    """Write .flo predictions for the Sintel test split (evaluate.py:22-54).

    eval_fn(image1, image2, flow_init=...) -> (flow_low, flow_up), jitted
    with iters=32.
    """
    if datasets is None:
        from dexiraft_tpu.data.datasets import MpiSintel
        datasets = {d: MpiSintel(None, split="test", dstype=d)
                    for d in ("clean", "final")}

    for dstype, ds in datasets.items():
        flow_prev, sequence_prev = None, None
        for i in range(len(ds)):
            s = ds.sample(i)
            sequence, frame = s["extra_info"]
            if sequence != sequence_prev:
                flow_prev = None

            padder = InputPadder(s["image1"].shape)
            im1, im2 = padder.pad(s["image1"][None], s["image2"][None])
            flow_low, flow_up = eval_fn(im1, im2, flow_init=flow_prev)
            flow = np.asarray(padder.unpad(np.asarray(flow_up)))[0]

            if warm_start:
                flow_prev = np.asarray(forward_interpolate(flow_low[0]))[None]

            out_dir = osp.join(output_path, dstype, sequence)
            os.makedirs(out_dir, exist_ok=True)
            write_flo(osp.join(out_dir, f"frame{frame + 1:04d}.flo"), flow)
            sequence_prev = sequence


def create_kitti_submission(
    eval_fn: EvalFn,
    output_path: str = "kitti_submission",
    dataset=None,
) -> None:
    """Write 16-bit PNG predictions for the KITTI test split
    (evaluate.py:58-77); eval_fn jitted with iters=24."""
    if dataset is None:
        from dexiraft_tpu.data.datasets import KITTI
        dataset = KITTI(None, split="testing")
    os.makedirs(output_path, exist_ok=True)
    for i in range(len(dataset)):
        s = dataset.sample(i)
        (frame_id,) = s["extra_info"]
        padder = InputPadder(s["image1"].shape, mode="kitti")
        im1, im2 = padder.pad(s["image1"][None], s["image2"][None])
        _, flow_up = eval_fn(im1, im2)
        flow = np.asarray(padder.unpad(np.asarray(flow_up)))[0]
        write_flow_kitti(osp.join(output_path, frame_id), flow)
