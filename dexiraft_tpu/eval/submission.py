"""Benchmark submission writers (evaluate.py:22-77).

Sintel: per-sequence ordered inference with optional WARM START — the
previous frame's low-res flow is propagated by forward_interpolate and
fed as flow_init (evaluate.py:40-44). Unlike the reference (scipy
griddata on host, a device round-trip per frame), propagation runs
on-device (dexiraft_tpu.eval.interpolate).

KITTI: per-frame 16-bit PNG encoding.

Batching (`batch_size>1`): KITTI frames are independent and stream
through the inference engine (dexiraft_tpu.serve) like a validation
set. Sintel's warm start is sequential WITHIN a sequence but
independent ACROSS sequences, so the batched path runs `batch_size`
sequences abreast: position j of each sequence rides one batch, and
each row carries ITS OWN flow_init (the engine materializes zeros for
rows whose sequence just started or already ended — numerically the
cold start). Frame j+1 still waits for frame j's flow_low, but the
forward now amortizes its prelude over a whole batch of sequences.
"""

from __future__ import annotations

import os
import os.path as osp
from typing import Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from dexiraft_tpu.data.flow_io import write_flo, write_flow_kitti
from dexiraft_tpu.data.padder import InputPadder
from dexiraft_tpu.eval.interpolate import forward_interpolate

EvalFn = Callable[..., Tuple[np.ndarray, np.ndarray]]


def _write_sintel(output_path: str, dstype: str, sequence: str,
                  frame: int, flow: np.ndarray) -> None:
    out_dir = osp.join(output_path, dstype, sequence)
    os.makedirs(out_dir, exist_ok=True)
    write_flo(osp.join(out_dir, f"frame{frame + 1:04d}.flo"), flow)


def _sequence_indices(ds) -> "Dict[str, List[int]]":
    """Dataset index lists per Sintel sequence, in frame order. Reads
    the dataset's extra_info table (never decodes images)."""
    seqs: Dict[str, List[int]] = {}
    for i, (sequence, _frame) in enumerate(ds.extra_info):
        seqs.setdefault(sequence, []).append(i)
    return seqs


def _sintel_batched(eval_fn: EvalFn, ds, dstype: str, output_path: str,
                    warm_start: bool, batch_size: int, engine=None) -> None:
    """`batch_size` sequences abreast with per-item flow_init carry."""
    from dexiraft_tpu.serve import InferenceEngine, ServeConfig

    if engine is None:
        engine = InferenceEngine(
            eval_fn, ServeConfig(batch_size=batch_size, mode="sintel",
                                 warm_start=warm_start))
    if not warm_start:
        # no carry -> frames are independent; the fully pipelined
        # stream() path (async in-flight dispatch) beats the
        # position-synchronous loop below
        def samples():
            for i in range(len(ds)):
                s = ds.sample(i)
                yield {"image1": s["image1"], "image2": s["image2"],
                       "extra_info": s["extra_info"]}

        for r in engine.stream(samples(), mode="sintel"):
            sequence, frame = r.item["extra_info"]
            _write_sintel(output_path, dstype, sequence, frame, r.flow_up)
        return
    batch_size = engine.config.batch_size  # group sequences to its shape
    seqs = list(_sequence_indices(ds).items())
    for g in range(0, len(seqs), batch_size):
        group = seqs[g:g + batch_size]
        carry: Dict[str, Optional[np.ndarray]] = {s: None for s, _ in group}
        for pos in range(max(len(idxs) for _, idxs in group)):
            items, names = [], []
            for sequence, idxs in group:
                if pos >= len(idxs):
                    continue  # this sequence already ended; row drops out
                s = ds.sample(idxs[pos])
                items.append({"image1": s["image1"], "image2": s["image2"],
                              "flow_init": carry[sequence],
                              "extra_info": s["extra_info"]})
                names.append(sequence)
            for sequence, r in zip(names, engine.run_batch(items)):
                _, frame = r.item["extra_info"]
                _write_sintel(output_path, dstype, sequence, frame, r.flow_up)
                carry[sequence] = np.asarray(forward_interpolate(r.flow_low))


def create_sintel_submission(
    eval_fn: EvalFn,
    output_path: str = "sintel_submission",
    warm_start: bool = False,
    datasets=None,
    batch_size: int = 1,
    engine=None,
) -> None:
    """Write .flo predictions for the Sintel test split (evaluate.py:22-54).

    eval_fn(image1, image2, flow_init=...) -> (flow_low, flow_up), jitted
    with iters=32. batch_size>1 (or a caller-built engine, e.g. a
    data-parallel one) runs sequences abreast through the serving engine
    (module docstring) and needs a dataset exposing `extra_info`
    (FlowDataset does).
    """
    if datasets is None:
        from dexiraft_tpu.data.datasets import MpiSintel
        datasets = {d: MpiSintel(None, split="test", dstype=d)
                    for d in ("clean", "final")}

    if batch_size > 1 or engine is not None:
        for dstype, ds in datasets.items():
            _sintel_batched(eval_fn, ds, dstype, output_path,
                            warm_start, batch_size, engine=engine)
        return

    for dstype, ds in datasets.items():
        flow_prev, sequence_prev = None, None
        for i in range(len(ds)):
            s = ds.sample(i)
            sequence, frame = s["extra_info"]
            if sequence != sequence_prev:
                flow_prev = None

            padder = InputPadder(s["image1"].shape)
            im1, im2 = padder.pad(s["image1"][None], s["image2"][None])
            flow_low, flow_up = eval_fn(im1, im2, flow_init=flow_prev)
            # explicit fetch (jaxlint JL007): the per-frame sync is the
            # point of this loop — device_get says so out loud, and the
            # strict transfer guard (analysis.guards) lets it through
            flow = np.asarray(padder.unpad(jax.device_get(flow_up)))[0]

            if warm_start:
                # fetch FIRST, interpolate on host: forward_interpolate
                # is numpy, and handing it a device array would be an
                # implicit (strict-guard-tripping) transfer
                flow_prev = forward_interpolate(
                    jax.device_get(flow_low)[0])[None]

            _write_sintel(output_path, dstype, sequence, frame, flow)
            sequence_prev = sequence


def create_kitti_submission(
    eval_fn: EvalFn,
    output_path: str = "kitti_submission",
    dataset=None,
    batch_size: int = 1,
    engine=None,
) -> None:
    """Write 16-bit PNG predictions for the KITTI test split
    (evaluate.py:58-77); eval_fn jitted with iters=24. batch_size>1
    streams the independent frames through the serving engine."""
    if dataset is None:
        from dexiraft_tpu.data.datasets import KITTI
        dataset = KITTI(None, split="testing")
    os.makedirs(output_path, exist_ok=True)

    if batch_size > 1 or engine is not None:
        if engine is None:
            from dexiraft_tpu.serve import InferenceEngine, ServeConfig

            engine = InferenceEngine(
                eval_fn, ServeConfig(batch_size=batch_size, mode="kitti"))
        samples = (dataset.sample(i) for i in range(len(dataset)))
        for r in engine.stream(samples, mode="kitti"):
            (frame_id,) = r.item["extra_info"]
            write_flow_kitti(osp.join(output_path, frame_id), r.flow_up)
        return

    for i in range(len(dataset)):
        s = dataset.sample(i)
        (frame_id,) = s["extra_info"]
        padder = InputPadder(s["image1"].shape, mode="kitti")
        im1, im2 = padder.pad(s["image1"][None], s["image2"][None])
        _, flow_up = eval_fn(im1, im2)
        flow = np.asarray(padder.unpad(jax.device_get(flow_up)))[0]
        write_flow_kitti(osp.join(output_path, frame_id), flow)
