"""Dataset-metric validators (evaluate.py:81-210).

Each validator consumes `eval_fn(image1, image2) -> (flow_low, flow_up)`
— a jitted test-mode forward built with the reference iteration counts
(chairs/kitti 24, sintel 32) via dexiraft_tpu.train.step.make_eval_step —
and a dataset, and returns the reference's metric dict. Metrics
accumulate in numpy on host.

Batching: `batch_size=1` (the default) is the reference behavior — one
padded frame pair per forward, synchronous fetch. `batch_size>1`
streams the dataset through the throughput-mode inference engine
(dexiraft_tpu.serve): same replicate-edge pad shapes (bucket multiple ==
stride), same eval-mode forward, so the metrics match the per-image
path to fp32 tolerance (pinned by tests/test_zserve_engine.py); frames
are just grouped, dispatched ahead, and fetched late. Every per-frame
metric is order-invariant under the engine's bucket-grouped completion
order (means over concatenated per-frame values).

validate_hd1k fixes the reference's undefined-variable crash
(evaluate.py:197 references valid_gt that was never read) by actually
using the dataset's sparse valid mask.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterator, Tuple

import jax
import numpy as np

from dexiraft_tpu.data.padder import InputPadder

EvalFn = Callable[..., Tuple[np.ndarray, np.ndarray]]


def _epe(pred: np.ndarray, gt: np.ndarray) -> np.ndarray:
    return np.sqrt(np.sum((pred - gt) ** 2, axis=-1))


def _run(eval_fn: EvalFn, img1: np.ndarray, img2: np.ndarray,
         mode: str) -> np.ndarray:
    """Pad -> forward -> unpad; returns (H, W, 2) upsampled flow."""
    padder = InputPadder(img1.shape, mode=mode)
    p1, p2 = padder.pad(img1[None], img2[None])
    _, flow_up = eval_fn(p1, p2)
    # explicit device->host fetch (jaxlint JL007): this per-frame sync
    # is the reference behavior; spelling it device_get keeps it visible
    # and transfer-guard-clean (analysis.guards.strict_mode)
    return np.asarray(padder.unpad(jax.device_get(flow_up)))[0]


def _frame_flows(eval_fn: EvalFn, dataset, mode: str,
                 batch_size: int = 1, engine=None) -> Iterator[Tuple[dict, np.ndarray]]:
    """Yield (sample, unpadded flow) for every dataset frame.

    batch_size==1 without an engine is the reference per-image loop;
    otherwise frames stream through the serving engine (completion
    order; metrics below are order-invariant).
    """
    if engine is None and batch_size == 1:
        for i in range(len(dataset)):
            s = dataset.sample(i)
            yield s, _run(eval_fn, s["image1"], s["image2"], mode)
        return
    if engine is None:
        from dexiraft_tpu.serve import InferenceEngine, ServeConfig

        engine = InferenceEngine(eval_fn,
                                 ServeConfig(batch_size=batch_size, mode=mode))
    samples = (dataset.sample(i) for i in range(len(dataset)))
    for r in engine.stream(samples, mode=mode):
        yield r.item, r.flow_up


def validate_chairs(eval_fn: EvalFn, dataset=None, *, batch_size: int = 1,
                    engine=None) -> Dict[str, float]:
    """FlyingChairs val EPE (evaluate.py:81-98; iters=24 in the caller)."""
    if dataset is None:
        from dexiraft_tpu.data.datasets import FlyingChairs
        dataset = FlyingChairs(None, split="validation")
    epe_all = []
    for s, flow in _frame_flows(eval_fn, dataset, "sintel", batch_size, engine):
        epe_all.append(_epe(flow, s["flow"]).ravel())
    epe = float(np.concatenate(epe_all).mean())
    print(f"Validation Chairs EPE: {epe:.3f}")
    return {"chairs": epe}


def validate_sintel(eval_fn: EvalFn, datasets=None, *, batch_size: int = 1,
                    engine=None) -> Dict[str, float]:
    """Sintel train-split clean+final EPE / px accuracies (evaluate.py:102-133)."""
    if datasets is None:
        from dexiraft_tpu.data.datasets import MpiSintel
        datasets = {d: MpiSintel(None, split="training", dstype=d)
                    for d in ("clean", "final")}
    results: Dict[str, float] = {}
    for dstype, ds in datasets.items():
        epe_all = []
        for s, flow in _frame_flows(eval_fn, ds, "sintel", batch_size, engine):
            epe_all.append(_epe(flow, s["flow"]).ravel())
        epe = np.concatenate(epe_all)
        results[dstype] = float(epe.mean())
        results[f"{dstype}_px1"] = float((epe < 1).mean())
        results[f"{dstype}_px3"] = float((epe < 3).mean())
        results[f"{dstype}_px5"] = float((epe < 5).mean())
        print(f"Validation ({dstype}) EPE: {results[dstype]:.3f}, "
              f"1px: {results[f'{dstype}_px1']:.3f}, "
              f"3px: {results[f'{dstype}_px3']:.3f}, "
              f"5px: {results[f'{dstype}_px5']:.3f}")
    return results


def _sparse_metrics(eval_fn: EvalFn, dataset, mode: str,
                    batch_size: int = 1, engine=None) -> Tuple[float, float, int]:
    """Sparse EPE over valid pixels + F1 (= % of valid pixels with epe>3
    AND epe/mag>5%, the KITTI outlier definition, evaluate.py:158-166).

    A frame with ZERO valid pixels would make `epe[val].mean()` NaN and
    silently poison the dataset-level mean (np.mean propagates it);
    such frames are skipped and counted — the third return — so the
    dataset EPE stays a mean over frames that actually have ground
    truth.
    """
    epe_list, out_list, skipped = [], [], 0
    for s, flow in _frame_flows(eval_fn, dataset, mode, batch_size, engine):
        val = s["valid"].ravel() >= 0.5
        if not val.any():
            skipped += 1
            continue
        epe = _epe(flow, s["flow"]).ravel()
        mag = np.sqrt(np.sum(s["flow"] ** 2, axis=-1)).ravel()
        out = (epe > 3.0) & ((epe / np.maximum(mag, 1e-12)) > 0.05)
        epe_list.append(epe[val].mean())
        out_list.append(out[val])
    if not epe_list:
        raise ValueError("every frame had an empty valid mask — no sparse "
                         "metrics to report")
    return (float(np.mean(epe_list)),
            100.0 * float(np.concatenate(out_list).mean()),
            skipped)


def _sparse_summary(name: str, epe: float, f1: float, skipped: int) -> None:
    note = f" ({skipped} empty-mask frames skipped)" if skipped else ""
    print(f"Validation {name}: {epe:.3f}, {f1:.3f}{note}")


def validate_kitti(eval_fn: EvalFn, dataset=None, *, batch_size: int = 1,
                   engine=None) -> Dict[str, float]:
    """KITTI-15 train-split EPE + F1 (evaluate.py:137-172; iters=24)."""
    if dataset is None:
        from dexiraft_tpu.data.datasets import KITTI
        dataset = KITTI(None, split="training")
    epe, f1, skipped = _sparse_metrics(eval_fn, dataset, "kitti",
                                       batch_size, engine)
    _sparse_summary("KITTI", epe, f1, skipped)
    return {"kitti-epe": epe, "kitti-f1": f1}


def validate_hd1k(eval_fn: EvalFn, dataset=None, *, batch_size: int = 1,
                  engine=None) -> Dict[str, float]:
    """HD1K sparse EPE + F1 — the reference's version crashes on an
    undefined variable (evaluate.py:197); fixed here."""
    if dataset is None:
        from dexiraft_tpu.data.datasets import HD1K
        dataset = HD1K(None)
    epe, f1, skipped = _sparse_metrics(eval_fn, dataset, "kitti",
                                       batch_size, engine)
    _sparse_summary("HD1K", epe, f1, skipped)
    return {"hd1k-epe": epe, "hd1k-f1": f1}


def validate_edgesum(eval_fn: EvalFn, dataset=None, *, batch_size: int = 1,
                     engine=None) -> Dict[str, float]:
    """v1-lineage summed-fusion validation (alt/evaluate_1.py:84-94):
    the model runs on the image pair AND the edge-image pair; the two
    upsampled flows are summed before EPE. dataset must yield edge pairs
    (EdgePairDataset samples: image1/2, edges1/2, flow) — there is no
    default dataset, since the edge tree location is user-supplied.

    Batched: each frame becomes TWO engine items (image pair, edge pair)
    that batch and pipeline like any others; the flows re-join by frame
    index on fetch."""
    if dataset is None:
        raise ValueError(
            "validate_edgesum needs an edge-pair dataset (build one with "
            "EdgePairDataset.from_parallel_tree); it has no default")
    if engine is None and batch_size == 1:
        epe_all = []
        for i in range(len(dataset)):
            s = dataset.sample(i)
            im_flow = _run(eval_fn, s["image1"], s["image2"], "sintel")
            em_flow = _run(eval_fn, s["edges1"], s["edges2"], "sintel")
            epe_all.append(_epe(im_flow + em_flow, s["flow"]).ravel())
    else:
        if engine is None:
            from dexiraft_tpu.serve import InferenceEngine, ServeConfig

            engine = InferenceEngine(
                eval_fn, ServeConfig(batch_size=batch_size, mode="sintel"))

        def both_passes():
            for i in range(len(dataset)):
                s = dataset.sample(i)
                yield {"image1": s["image1"], "image2": s["image2"],
                       "flow": s["flow"], "pair": i}
                yield {"image1": s["edges1"], "image2": s["edges2"],
                       "pair": i}

        halves: Dict[int, np.ndarray] = {}
        epe_all = []
        for r in engine.stream(both_passes(), mode="sintel"):
            pair = r.item["pair"]
            if pair not in halves:
                halves[pair] = r
                continue
            other = halves.pop(pair)
            gt = r.item.get("flow", other.item.get("flow"))
            epe_all.append(_epe(r.flow_up + other.flow_up, gt).ravel())
        if halves:  # must hold even under python -O
            raise RuntimeError(
                f"engine yielded only one pass for frames {sorted(halves)}")
    epe = float(np.concatenate(epe_all).mean())
    print(f"Validation (edge-sum fusion) EPE: {epe:.3f}")
    return {"edgesum": epe}


VALIDATORS = {
    "chairs": validate_chairs,
    "sintel": validate_sintel,
    "kitti": validate_kitti,
    "hd1k": validate_hd1k,
    "edgesum": validate_edgesum,
}


def run_validation(name: str, eval_fn: EvalFn, dataset=None, *,
                   batch_size: int = 1, engine=None) -> Dict[str, float]:
    return VALIDATORS[name](eval_fn, dataset,
                            batch_size=batch_size, engine=engine)
