"""Dataset-metric validators (evaluate.py:81-210).

Each validator consumes `eval_fn(image1, image2) -> (flow_low, flow_up)`
— a jitted test-mode forward built with the reference iteration counts
(chairs/kitti 24, sintel 32) via dexiraft_tpu.train.step.make_eval_step —
and a dataset, and returns the reference's metric dict. Batch size is 1
per frame pair, matching the reference's eval loops; metrics accumulate
in numpy on host.

validate_hd1k fixes the reference's undefined-variable crash
(evaluate.py:197 references valid_gt that was never read) by actually
using the dataset's sparse valid mask.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import numpy as np

from dexiraft_tpu.data.padder import InputPadder

EvalFn = Callable[..., Tuple[np.ndarray, np.ndarray]]


def _epe(pred: np.ndarray, gt: np.ndarray) -> np.ndarray:
    return np.sqrt(np.sum((pred - gt) ** 2, axis=-1))


def _run(eval_fn: EvalFn, img1: np.ndarray, img2: np.ndarray,
         mode: str) -> np.ndarray:
    """Pad -> forward -> unpad; returns (H, W, 2) upsampled flow."""
    padder = InputPadder(img1.shape, mode=mode)
    p1, p2 = padder.pad(img1[None], img2[None])
    _, flow_up = eval_fn(p1, p2)
    return np.asarray(padder.unpad(np.asarray(flow_up)))[0]


def validate_chairs(eval_fn: EvalFn, dataset=None) -> Dict[str, float]:
    """FlyingChairs val EPE (evaluate.py:81-98; iters=24 in the caller)."""
    if dataset is None:
        from dexiraft_tpu.data.datasets import FlyingChairs
        dataset = FlyingChairs(None, split="validation")
    epe_all = []
    for i in range(len(dataset)):
        s = dataset.sample(i)
        flow = _run(eval_fn, s["image1"], s["image2"], "sintel")
        epe_all.append(_epe(flow, s["flow"]).ravel())
    epe = float(np.concatenate(epe_all).mean())
    print(f"Validation Chairs EPE: {epe:.3f}")
    return {"chairs": epe}


def validate_sintel(eval_fn: EvalFn, datasets=None) -> Dict[str, float]:
    """Sintel train-split clean+final EPE / px accuracies (evaluate.py:102-133)."""
    if datasets is None:
        from dexiraft_tpu.data.datasets import MpiSintel
        datasets = {d: MpiSintel(None, split="training", dstype=d)
                    for d in ("clean", "final")}
    results: Dict[str, float] = {}
    for dstype, ds in datasets.items():
        epe_all = []
        for i in range(len(ds)):
            s = ds.sample(i)
            flow = _run(eval_fn, s["image1"], s["image2"], "sintel")
            epe_all.append(_epe(flow, s["flow"]).ravel())
        epe = np.concatenate(epe_all)
        results[dstype] = float(epe.mean())
        results[f"{dstype}_px1"] = float((epe < 1).mean())
        results[f"{dstype}_px3"] = float((epe < 3).mean())
        results[f"{dstype}_px5"] = float((epe < 5).mean())
        print(f"Validation ({dstype}) EPE: {results[dstype]:.3f}, "
              f"1px: {results[f'{dstype}_px1']:.3f}, "
              f"3px: {results[f'{dstype}_px3']:.3f}, "
              f"5px: {results[f'{dstype}_px5']:.3f}")
    return results


def _sparse_metrics(eval_fn: EvalFn, dataset, mode: str) -> Tuple[float, float]:
    """Sparse EPE over valid pixels + F1 (= % of valid pixels with epe>3
    AND epe/mag>5%, the KITTI outlier definition, evaluate.py:158-166)."""
    epe_list, out_list = [], []
    for i in range(len(dataset)):
        s = dataset.sample(i)
        flow = _run(eval_fn, s["image1"], s["image2"], mode)
        epe = _epe(flow, s["flow"]).ravel()
        mag = np.sqrt(np.sum(s["flow"] ** 2, axis=-1)).ravel()
        val = s["valid"].ravel() >= 0.5
        out = (epe > 3.0) & ((epe / np.maximum(mag, 1e-12)) > 0.05)
        epe_list.append(epe[val].mean())
        out_list.append(out[val])
    return (float(np.mean(epe_list)),
            100.0 * float(np.concatenate(out_list).mean()))


def validate_kitti(eval_fn: EvalFn, dataset=None) -> Dict[str, float]:
    """KITTI-15 train-split EPE + F1 (evaluate.py:137-172; iters=24)."""
    if dataset is None:
        from dexiraft_tpu.data.datasets import KITTI
        dataset = KITTI(None, split="training")
    epe, f1 = _sparse_metrics(eval_fn, dataset, "kitti")
    print(f"Validation KITTI: {epe:.3f}, {f1:.3f}")
    return {"kitti-epe": epe, "kitti-f1": f1}


def validate_hd1k(eval_fn: EvalFn, dataset=None) -> Dict[str, float]:
    """HD1K sparse EPE + F1 — the reference's version crashes on an
    undefined variable (evaluate.py:197); fixed here."""
    if dataset is None:
        from dexiraft_tpu.data.datasets import HD1K
        dataset = HD1K(None)
    epe, f1 = _sparse_metrics(eval_fn, dataset, "kitti")
    print(f"Validation HD1K: {epe:.3f}, {f1:.3f}")
    return {"hd1k-epe": epe, "hd1k-f1": f1}


def validate_edgesum(eval_fn: EvalFn, dataset=None) -> Dict[str, float]:
    """v1-lineage summed-fusion validation (alt/evaluate_1.py:84-94):
    the model runs on the image pair AND the edge-image pair; the two
    upsampled flows are summed before EPE. dataset must yield edge pairs
    (EdgePairDataset samples: image1/2, edges1/2, flow) — there is no
    default dataset, since the edge tree location is user-supplied."""
    if dataset is None:
        raise ValueError(
            "validate_edgesum needs an edge-pair dataset (build one with "
            "EdgePairDataset.from_parallel_tree); it has no default")
    epe_all = []
    for i in range(len(dataset)):
        s = dataset.sample(i)
        im_flow = _run(eval_fn, s["image1"], s["image2"], "sintel")
        em_flow = _run(eval_fn, s["edges1"], s["edges2"], "sintel")
        epe_all.append(_epe(im_flow + em_flow, s["flow"]).ravel())
    epe = float(np.concatenate(epe_all).mean())
    print(f"Validation (edge-sum fusion) EPE: {epe:.3f}")
    return {"edgesum": epe}


VALIDATORS = {
    "chairs": validate_chairs,
    "sintel": validate_sintel,
    "kitti": validate_kitti,
    "hd1k": validate_hd1k,
    "edgesum": validate_edgesum,
}


def run_validation(name: str, eval_fn: EvalFn, dataset=None) -> Dict[str, float]:
    return VALIDATORS[name](eval_fn, dataset)
