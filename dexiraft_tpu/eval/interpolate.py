"""Warm-start flow propagation, fully on-device.

The reference's forward_interpolate (core/utils/utils.py:26-54) splats the
previous frame's low-res flow forward and re-grids it with scipy
griddata(nearest) — a device->host->device round-trip per frame in the
submission loop (evaluate.py:43-44, SURVEY.md §3.3).

Here the splat is a scatter on device, and the nearest-neighbor re-grid
is a jump-flood Voronoi fill: each splatted cell seeds its CONTINUOUS
landing coordinates, and log2(max(H, W)) gather/compare rounds propagate
the nearest seed to every pixel — the same assignment griddata(nearest)
computes, without leaving the chip. Remaining divergence vs scipy is
limited to (a) two points landing in one rounded cell (the scatter keeps
one; scipy keeps whichever is nearer to each query) and (b) rare
jump-flood misses on adversarial seed layouts; both are quantified in
tests/test_eval.py::TestWarmStartParity and bounded in docs/parity.md.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _jfa_steps(h: int, w: int) -> list:
    """Jump-flood step sizes: N/2, ..., 1 plus a final 1 (the JFA+1
    variant, which removes most of plain JFA's rare misses)."""
    n = 1
    while n < max(h, w):
        n *= 2
    steps = []
    k = n // 2
    while k >= 1:
        steps.append(k)
        k //= 2
    return steps + [1]


# scatter-grid supersampling: points closer than ~1/S px can still
# collide in one cell (last write wins where scipy keeps the per-query
# nearest), so S trades memory (S^2 cells) for collision rarity. At S=4
# the measured divergence vs scipy on smooth sintel-like flows is
# mean 0.016 px with 99.7% of pixels <0.5 px (docs/parity.md); the
# input is the 1/8-resolution flow_low, so S^2 cells stay tiny
_SUPERSAMPLE = 4


@jax.jit
def forward_interpolate(flow: jax.Array) -> jax.Array:
    """Propagate (H, W, 2) flow to the next frame's grid.

    Each pixel's flow vector is carried to its continuous target
    location; every output pixel takes the value of the NEAREST carried
    point (scipy griddata(nearest) semantics, core/utils/utils.py:40-51).
    With no in-frame points at all, returns zeros (the reference's
    fill_value).
    """
    h, w = flow.shape[:2]
    s = _SUPERSAMPLE
    hs, ws = h * s, w * s
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    x1 = xs + flow[..., 0]
    y1 = ys + flow[..., 1]
    # the reference's STRICT interior test on the continuous coords
    valid = (x1 > 0) & (x1 < w) & (y1 > 0) & (y1 < h)
    # scatter onto the s-times-finer grid, coords kept in FINE units
    x1f = x1 * s
    y1f = y1 * s
    xi = jnp.clip(jnp.round(x1f), 0, ws - 1).astype(jnp.int32)
    yi = jnp.clip(jnp.round(y1f), 0, hs - 1).astype(jnp.int32)
    # invalid points get an out-of-range index -> dropped by the scatter
    lin = jnp.where(valid, yi * ws + xi, hs * ws).ravel()

    FAR = jnp.float32(1e9)  # sentinel seed coordinate: "no seed here"
    seed = jnp.full((hs * ws, 4), FAR, jnp.float32)
    # (seed_x, seed_y, value_x, value_y) per fine cell
    seed = seed.at[lin].set(
        jnp.concatenate([x1f.reshape(-1, 1), y1f.reshape(-1, 1),
                         flow.reshape(-1, 2)], axis=1),
        mode="drop").reshape(hs, ws, 4)

    ysf, xsf = jnp.meshgrid(jnp.arange(hs, dtype=jnp.float32),
                            jnp.arange(ws, dtype=jnp.float32), indexing="ij")

    def dist2(state):
        return ((state[..., 0] - xsf) ** 2 + (state[..., 1] - ysf) ** 2)

    # carry each cell's CURRENT squared distance as a 5th channel so the
    # compare below evaluates one dist2 per neighbor, not two
    best = jnp.concatenate([seed, dist2(seed)[..., None]], axis=-1)
    for k in _jfa_steps(hs, ws):
        for dy in (-k, 0, k):
            for dx in (-k, 0, k):
                if dy == 0 and dx == 0:
                    continue
                cand = jnp.roll(best, (dy, dx), axis=(0, 1))
                # cells whose roll wrapped around carry a foreign seed;
                # a wrapped seed can only be NEARER than the true one
                # through the wrap, so invalidate it
                src_y = ysf - dy
                src_x = xsf - dx
                wrapped = ((src_y < 0) | (src_y >= hs)
                           | (src_x < 0) | (src_x >= ws))
                cand = jnp.where(wrapped[..., None], FAR, cand)
                cand = cand.at[..., 4].set(dist2(cand))
                best = jnp.where((cand[..., 4] < best[..., 4])[..., None],
                                 cand, best)

    # output pixels sit at fine-grid nodes (s*i, s*j): stride-slice them
    best = best[::s, ::s]
    # no seed anywhere (every vector left the frame): reference fill=0
    found = best[..., 0] < FAR * 0.5
    return jnp.where(found[..., None], best[..., 2:4], 0.0)
