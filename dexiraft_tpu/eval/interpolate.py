"""Warm-start flow propagation, fully on-device.

The reference's forward_interpolate (core/utils/utils.py:26-54) splats the
previous frame's low-res flow forward and re-grids it with scipy
griddata(nearest) — a device->host->device round-trip per frame in the
submission loop (evaluate.py:43-44, SURVEY.md §3.3).

Here the splat is a scatter on device and holes are filled by iterated
masked 3x3 averaging (a chamfer-style approximation of nearest-neighbor
fill; documented divergence — hole values are local means rather than
exact nearest, which only seeds the next frame's refinement).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _box3(x: jax.Array) -> jax.Array:
    """3x3 box sum over (H, W, C)."""
    return jax.lax.reduce_window(
        x, 0.0, jax.lax.add, (3, 3, 1), (1, 1, 1), "SAME"
    )


@partial(jax.jit, static_argnames="max_fill_iters")
def forward_interpolate(flow: jax.Array, max_fill_iters: int = 64) -> jax.Array:
    """Propagate (H, W, 2) flow to the next frame's grid.

    Each pixel's flow vector is carried to its rounded target location;
    unreached pixels are filled by repeated masked dilation.
    """
    h, w = flow.shape[:2]
    ys, xs = jnp.meshgrid(jnp.arange(h), jnp.arange(w), indexing="ij")
    x1 = xs + flow[..., 0]
    y1 = ys + flow[..., 1]
    xi = jnp.round(x1).astype(jnp.int32)
    yi = jnp.round(y1).astype(jnp.int32)
    inside = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
    # out-of-frame points get an out-of-range index -> dropped by the scatter
    lin = jnp.where(inside, yi * w + xi, h * w)

    splat = jnp.zeros((h * w, 2), jnp.float32).at[lin.ravel()].set(
        flow.reshape(-1, 2), mode="drop")
    mask = jnp.zeros((h * w, 1), jnp.float32).at[lin.ravel()].set(
        1.0, mode="drop")
    splat = splat.reshape(h, w, 2)
    mask = mask.reshape(h, w, 1)

    def fill_cond(state):
        i, _, m = state
        return (i < max_fill_iters) & jnp.any(m < 0.5)

    def fill_body(state):
        i, f, m = state
        cnt = _box3(m)
        avg = _box3(f * m) / jnp.maximum(cnt, 1.0)
        f = jnp.where(m > 0.5, f, avg)
        m = jnp.maximum(m, jnp.minimum(cnt, 1.0))
        return i + 1, f, m

    _, filled, _ = jax.lax.while_loop(
        fill_cond, fill_body, (jnp.int32(0), splat, mask))
    return filled
