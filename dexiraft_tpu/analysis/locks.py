"""Instrumented lock-order runtime: the dynamic half of threadlint.

The serve/resilience tier is a 12-module thread fabric, and every
concurrency bug this repo has shipped (the RouterStats unlocked `+=`,
the VideoEngine stats-lock stall, the RecompileWatch mark_warm race,
the flush-barrier ordering bug) was found by a human reviewer after the
fact. threadlint (JL020+) catches the *textual* half of that class;
this module catches the half only visible at run time:

- **lock-order inversions / deadlock cycles** — every lock in the fleet
  is an :class:`OrderedLock`: a named, rank-carrying wrapper whose rank
  comes from the one central :data:`LOCK_ORDER` registry below.
  Acquiring lock B while holding lock A records the edge A->B in a
  per-process acquisition graph; an edge that closes a cycle (two code
  paths taking the same pair in opposite orders — the ABBA deadlock) or
  inverts the declared ranks raises :class:`LockOrderViolation` at the
  SECOND acquisition under strict mode (``set_strict(True)``, armed by
  ``--strict`` serving and by the test suite) and warns once per edge
  otherwise. The detector fires *before* blocking, so a seeded deadlock
  is a stack trace naming both locks, never a hung process.
- **held-too-long spans + contention** — each lock keeps max/total held
  time and a contended-acquisition count (all clock reads go through
  the registry's injectable clock, so tests pin the math on a fake
  clock). ``stats_record()`` is the ``locks`` block the serve tier's
  /stats endpoints and chaos_smoke's record tail surface.

Design constraints, in order: pure stdlib (serve/router must import
this with no jax anywhere near the path); near-zero cost on the
uncontended fast path (per-lock gauges are mutated only while the lock
itself is held — no global lock on plain acquires; the registry's
internal mutex is touched only for *nested* acquisitions, registration,
and stats reads); and honest under races (a violation is counted and
reported even when non-strict mode lets execution proceed).

The declared total order is the contract reviewers used to reconstruct
from CHANGES.md archaeology (docs/serving.md "Threading model" now
spells it out): outermost first, so a thread may only acquire DOWN the
list while holding earlier entries. threadlint's JL024 enforces the
static mirror of the same registry.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Sequence, Tuple

#: The fleet's declared total lock order, outermost first. A thread
#: holding LOCK_ORDER[i] may acquire LOCK_ORDER[j] only for j > i.
#: Every OrderedLock name below exists in the tree; threadlint keeps a
#: pure-stdlib mirror of this tuple (tests/test_zzzthreadlint.py pins
#: them equal, the shardlint LAYOUT_AXES idiom).
LOCK_ORDER: Tuple[str, ...] = (
    "serve.video.chunk",         # VideoEngine._lock: one chunk's frame loop
    "serve.server.stop",         # FlowService._stop_lock: drain idempotence
    "serve.scheduler.cv",        # Scheduler._cv: queues + dispatch decision
    "serve.router.supervisor",   # router_cli._Supervisor._lock: child procs
    "serve.router.autoscale",    # Router._autoscale_lock: scrape-window
                                 # read-and-swap (nests pool + stats records)
    "serve.router.pool",         # ReplicaPool._lock: breaker + ring + affinity
    "serve.router.inflight",     # Router._inflight_lock: admission bound
    "serve.router.stats",        # RouterStats._lock: proxy counters
    "serve.video.inflight",      # VideoEngine._inflight_lock: chunk admission
    "serve.video.stats",         # VideoEngine._stats_lock: chunk counters
    "serve.sessions.store",      # SessionStore._lock: flow-seed carry map
    "serve.sessions.device",     # DeviceSessionStore._lock: device carry map
    "analysis.guards.watch",     # RecompileWatch._slock: sanctioned windows
    "analysis.guards.listener",  # guards._lock: one-time listener install
    "resilience.watchdog.armed", # HangWatchdog._lock: armed-region tuple
    "train.checkpoint.pending",  # checkpoint._LOCK: pending-flush registry
    "data.loader.pool",          # _PoolManager._lock: decode-pool generation
    "resilience.trace.ring",     # CollectiveTrace._lock: flight-recorder ring
)


class LockOrderViolation(RuntimeError):
    """A lock acquisition inverted the declared rank order, closed an
    acquisition cycle (potential ABBA deadlock), or re-entered a
    non-reentrant lock on its own thread. Raised at the offending
    acquisition — before blocking — under strict mode."""


class _Held:
    """One entry of a thread's held-lock stack."""

    __slots__ = ("lock", "depth", "t0")

    def __init__(self, lock: "OrderedLock", depth: int, t0: float):
        self.lock = lock
        self.depth = depth
        self.t0 = t0


class LockRegistry:
    """Process-wide acquisition graph + violation/contention accounting.

    One module-level instance (:data:`REGISTRY`) serves the fleet;
    tests construct private registries (with fake clocks and their own
    strict flag) so seeded violations never pollute the global record
    chaos_smoke asserts is clean.
    """

    VIOLATION_WINDOW = 32   # retained violation messages (stats blob)

    def __init__(self, order: Sequence[str] = LOCK_ORDER, *,
                 strict: Optional[bool] = None,
                 held_warn_ms: float = 1000.0,
                 clock: Callable[[], float] = time.monotonic):
        self._rank: Dict[str, int] = {n: i for i, n in enumerate(order)}
        # plain threading.Lock ON PURPOSE: the registry's own mutex must
        # not feed the graph it guards, and it is never held across a
        # blocking user-lock acquire
        self._meta = threading.Lock()
        self._edges: Dict[str, set] = {}          # held-name -> {acquired}
        # (held, acquired) pairs already validated violation-free: the
        # steady-state fast path checks this IMMUTABLE snapshot without
        # _meta (replaced wholesale under _meta on growth), so hot
        # nested acquisitions (chunk->stats per frame, inflight->stats
        # per request) stop serializing on one global mutex after their
        # first validation. Sound because the acquisition that CREATES
        # a violation (the edge closing a cycle, the inverted rank) is
        # by definition not yet in this set — skipping re-checks of
        # clean edges can never skip the violating one.
        self._clean_pairs: frozenset = frozenset()
        self._locks: Dict[str, "weakref.WeakSet[OrderedLock]"] = {}
        self._warned: set = set()                 # (kind, held, acquired)
        self._violations: List[str] = []
        self._tls = threading.local()
        self.order_violations = 0
        self.cycles = 0
        self.strict = (os.environ.get("DEXIRAFT_LOCK_STRICT") == "1"
                       if strict is None else bool(strict))
        self.held_warn_ms = float(held_warn_ms)
        self.clock = clock

    # ---- bookkeeping -----------------------------------------------------

    def rank(self, name: str) -> Optional[int]:
        return self._rank.get(name)

    def _held_stack(self) -> List[_Held]:
        stack = getattr(self._tls, "held", None)
        if stack is None:
            stack = self._tls.held = []
        return stack

    def _register(self, lock: "OrderedLock") -> None:
        with self._meta:
            self._locks.setdefault(lock.name, weakref.WeakSet()).add(lock)

    def _reaches(self, src: str, dst: str) -> Optional[List[str]]:
        """BFS over the acquisition graph; the src->dst path if one
        exists (meta lock held by the caller)."""
        parents: Dict[str, Optional[str]] = {src: None}
        frontier = [src]
        while frontier:
            nxt: List[str] = []
            for node in frontier:
                for succ in self._edges.get(node, ()):
                    if succ in parents:
                        continue
                    parents[succ] = node
                    if succ == dst:
                        path = [dst]
                        while parents[path[-1]] is not None:
                            path.append(parents[path[-1]])
                        return path[::-1]
                    nxt.append(succ)
            frontier = nxt
        return None

    # ---- the ordering check (nested acquisitions only) -------------------

    def note_nested(self, lock: "OrderedLock",
                    held: Sequence[_Held]) -> None:
        """Record the held->lock edges and detect violations. Called
        BEFORE blocking on `lock`, so a strict-mode raise names the
        would-be deadlock instead of becoming one."""
        problems: List[Tuple[str, str, str, str]] = []
        clean: List[Tuple[str, str]] = []
        with self._meta:
            for entry in held:
                h = entry.lock
                if h.name == lock.name:
                    # a DIFFERENT instance with the same name (same-
                    # instance re-entry never reaches here): a total
                    # order by name cannot order these, so two threads
                    # nesting two instances in opposite orders is an
                    # undetectable ABBA — flag the nesting itself
                    self.order_violations += 1
                    problems.append((
                        "same-name-nesting", h.name, lock.name,
                        f"two '{lock.name}' instances nested on one "
                        f"thread — the name order cannot rank them, so "
                        f"an opposite-order nesting elsewhere deadlocks "
                        f"undetected; give the instances distinct "
                        f"LOCK_ORDER names (or restructure to not "
                        f"nest)"))
                    continue
                path = self._reaches(lock.name, h.name)
                if path is not None:
                    self.cycles += 1
                    chain = " -> ".join(path + [lock.name])
                    problems.append((
                        "deadlock-cycle", h.name, lock.name,
                        f"acquiring '{lock.name}' while holding "
                        f"'{h.name}' closes the acquisition cycle "
                        f"[{chain}] — another code path takes these "
                        f"locks in the opposite order (ABBA deadlock)"))
                elif (lock.rank is not None and h.rank is not None
                        and lock.rank < h.rank):
                    self.order_violations += 1
                    problems.append((
                        "rank-inversion", h.name, lock.name,
                        f"'{lock.name}' (rank {lock.rank}) acquired "
                        f"while holding '{h.name}' (rank {h.rank}) — "
                        f"LOCK_ORDER declares the opposite nesting"))
                else:
                    clean.append((h.name, lock.name))
                self._edges.setdefault(h.name, set()).add(lock.name)
            if clean and not problems:
                # promote the whole validated combination to the fast
                # path (only when NO held pair misbehaved: a violating
                # acquisition must keep being counted every time)
                self._clean_pairs = self._clean_pairs.union(clean)
            for _, _, _, msg in problems:
                if len(self._violations) < self.VIOLATION_WINDOW:
                    self._violations.append(msg)
            fresh = [p for p in problems
                     if (p[0], p[1], p[2]) not in self._warned]
            self._warned.update((p[0], p[1], p[2]) for p in fresh)
        if not problems:
            return
        if self.strict:
            raise LockOrderViolation(
                "; ".join(f"{p[0]}: {p[3]}" for p in problems))
        for kind, _, _, msg in fresh:
            print(f"[locks] {kind}: {msg} (warn-once; strict mode "
                  f"raises here)", file=sys.stderr, flush=True)

    # ---- stats -----------------------------------------------------------

    def stats_record(self) -> dict:
        """The ``locks`` stats block (serve /stats, chaos_smoke record):
        violation verdicts plus per-lock contention/held gauges."""
        with self._meta:
            by_lock = {}
            held_too_long = 0
            for name in sorted(self._locks):
                acq = cont = long = 0
                max_ms = 0.0
                for lk in self._locks[name]:
                    acq += lk.acquisitions
                    cont += lk.contended
                    long += lk.held_too_long
                    max_ms = max(max_ms, lk.max_held_ms)
                held_too_long += long
                if acq:
                    by_lock[name] = {
                        "acquisitions": acq,
                        "contended": cont,
                        "max_held_ms": round(max_ms, 3),
                        "held_too_long": long,
                    }
            return {
                "strict": self.strict,
                "order_violations": self.order_violations,
                "cycles": self.cycles,
                "held_too_long": held_too_long,
                "violations": list(self._violations),
                "by_lock": by_lock,
            }


class OrderedLock:
    """A named Lock/RLock that feeds the registry's lock-order graph.

    Drop-in for ``threading.Lock()`` / ``threading.RLock()`` (with
    ``reentrant=True``), including as the lock under a
    ``threading.Condition`` — the ``_release_save`` /
    ``_acquire_restore`` / ``_is_owned`` protocol keeps the held-stack
    bookkeeping correct across ``Condition.wait`` (waiting is not
    holding, so a wait closes the current held span and opens a fresh
    one on wake).

    ``name`` should be declared in :data:`LOCK_ORDER`; an undeclared
    name gets no rank (cycle detection still applies — test fixtures
    and scratch locks stay usable) and threadlint's JL024 flags any
    *nesting* of it in the fleet's source.
    """

    def __init__(self, name: str, *, reentrant: bool = False,
                 registry: Optional[LockRegistry] = None):
        self.name = name
        self._reentrant = reentrant
        self._registry = registry if registry is not None else REGISTRY
        self.rank = self._registry.rank(name)
        self._inner = threading.RLock() if reentrant else threading.Lock()
        # gauges below are mutated ONLY while this lock is held (or on a
        # failed non-blocking probe of an uncontended path — never), so
        # they need no extra lock of their own
        self.acquisitions = 0
        self.contended = 0
        self.max_held_ms = 0.0
        self.total_held_ms = 0.0
        self.held_too_long = 0
        self._registry._register(self)

    def __repr__(self) -> str:
        return (f"OrderedLock({self.name!r}, rank={self.rank}, "
                f"reentrant={self._reentrant})")

    # ---- core API --------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        reg = self._registry
        held = reg._held_stack()
        for entry in held:
            if entry.lock is self:
                if self._reentrant:
                    got = self._inner.acquire(blocking, timeout)
                    if got:
                        entry.depth += 1
                    return got
                if not blocking:
                    # Condition's default _is_owned probes with
                    # acquire(False): held-by-us must answer False,
                    # not raise
                    return False
                raise LockOrderViolation(
                    f"re-acquiring non-reentrant lock '{self.name}' on "
                    f"the thread that already holds it — guaranteed "
                    f"self-deadlock")
        if held:
            # fast path: a nested combination whose every (held, this)
            # pair was already validated violation-free skips the
            # registry mutex + graph walk entirely (an immutable-set
            # read; see _clean_pairs). Anything new goes the slow way.
            clean = reg._clean_pairs
            if not all((e.lock.name, self.name) in clean for e in held):
                # same-name pairs are never promoted to clean, so a
                # second same-named instance always takes the slow path
                # (where it is flagged as unorderable)
                reg.note_nested(self, held)   # may raise under strict
        waited = False
        got = self._inner.acquire(False)
        if not got:
            if not blocking:
                return False
            waited = True
            got = (self._inner.acquire(True) if timeout is None
                   or timeout < 0 else self._inner.acquire(True, timeout))
            if not got:
                return False
        t0 = reg.clock()
        self.acquisitions += 1
        if waited:
            self.contended += 1
        held.append(_Held(self, 1, t0))
        return True

    def release(self) -> None:
        reg = self._registry
        held = reg._held_stack()
        for i in range(len(held) - 1, -1, -1):
            entry = held[i]
            if entry.lock is self:
                entry.depth -= 1
                if entry.depth == 0:
                    del held[i]
                    self._note_span(entry.t0)
                self._inner.release()
                return
        # a cross-thread release would free the inner lock but leave
        # the acquirer's _Held entry stranded on ITS stack forever —
        # every later acquisition on that thread would be checked
        # against a phantom held lock (false violations) and the span
        # gauge would never close. No fleet lock is handed off between
        # threads, so make the misuse loud instead of corrupting the
        # runtime's bookkeeping.
        raise RuntimeError(
            f"OrderedLock '{self.name}' released by a thread that does "
            f"not hold it — cross-thread lock hand-off is not supported "
            f"(use an Event/queue to transfer ownership)")

    def _note_span(self, t0: float) -> None:
        # still holding the lock here: gauge mutation is race-free
        dt_ms = (self._registry.clock() - t0) * 1e3
        self.total_held_ms += dt_ms
        if dt_ms > self.max_held_ms:
            self.max_held_ms = dt_ms
        if dt_ms > self._registry.held_warn_ms:
            self.held_too_long += 1

    def __enter__(self) -> "OrderedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        if self._reentrant:
            # RLock has no .locked(), and a bare non-blocking probe
            # would succeed REENTRANTLY for the owning thread (falsely
            # answering "not locked" while it holds it) — check
            # ownership first, probe only as the other-thread case
            if (hasattr(self._inner, "_is_owned")
                    and self._inner._is_owned()):
                return True
            if self._inner.acquire(False):
                self._inner.release()
                return False
            return True
        return self._inner.locked()

    # ---- threading.Condition protocol ------------------------------------
    # Condition.wait must FULLY release the lock (all recursion levels)
    # and the held-stack entry with it: a waiting thread holds nothing.

    def _is_owned(self) -> bool:
        if self._reentrant and hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        return any(e.lock is self for e in self._registry._held_stack())

    def _release_save(self):
        held = self._registry._held_stack()
        depth = 1
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock is self:
                entry = held[i]
                depth = entry.depth
                del held[i]
                self._note_span(entry.t0)
                break
        if self._reentrant and hasattr(self._inner, "_release_save"):
            return (self._inner._release_save(), depth)
        self._inner.release()
        return (None, depth)

    def _acquire_restore(self, saved) -> None:
        state, depth = saved
        if self._reentrant and hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._registry._held_stack().append(
            _Held(self, depth, self._registry.clock()))


#: The process-wide registry every fleet lock reports to.
REGISTRY = LockRegistry()


def set_strict(on: bool = True) -> None:
    """Arm (or disarm) strict mode on the global registry: order
    violations and deadlock cycles raise at the offending acquisition.
    Wired behind ``--strict`` serving and armed for the whole test
    suite (tests/conftest.py) — the lock-order analog of the fsdp
    replication canary."""
    REGISTRY.strict = bool(on)


def stats_record() -> dict:
    """The global registry's ``locks`` stats block."""
    return REGISTRY.stats_record()
