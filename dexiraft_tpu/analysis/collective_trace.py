"""collective_trace — the collective flight recorder (distlint's
runtime half).

distlint (JL030+) proves the *text* cannot diverge; this module proves
the *run* did not. Every collective op a host issues — Coordinator
consensus rounds, elastic membership epoch installs, orbax checkpoint
barriers — is stamped into a bounded ring buffer as
``(namespace, round, op, args_digest)``. Peers cross-check each other's
stamps two ways:

  * **in-band, every round**: ``Coordinator._allgather`` piggybacks
    each host's ``op|digest`` stamp on the consensus value it already
    posts to the KV store — zero extra RPCs — and every reader compares
    the peer's stamp for the round against its own. The FIRST round
    whose ops disagree raises :class:`CollectiveDivergence` naming
    (host, round, expected-vs-seen) the moment the mismatched key
    arrives: a one-line diagnosis in seconds, instead of a
    ``CoordinatorTimeout`` after the full timeout window.
  * **out-of-band, on demand**: each host publishes its encoded trace
    tail under ``{namespace}/trace/{host}`` on the coord cadence; the
    timeout path and the post-mortem tooling fetch peers' tails and run
    :func:`verify_lockstep` — a pure function over scripted-or-real
    traces that names the first divergent op.

The recorder is process-global and always on (a few hundred tuples in
a deque — the cost is noise): the hang watchdog dumps its tail next to
the faulthandler stacks, multihost children pin it in their result
JSON, and chaos-smoke pins a ``collective_trace`` verdict block with
``divergences == 0``. The ring is guarded by the
``resilience.trace.ring`` OrderedLock (leaf rank in LOCK_ORDER —
stamping never nests outward).

Digests cover only *protocol-identifying* args (namespace, op, barrier
key) — never the local values being agreed on, which legitimately
differ per host (the whole point of ``any_flag`` is that one host's
flag differs).
"""

from __future__ import annotations

import time
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from dexiraft_tpu.analysis.locks import OrderedLock

#: entries kept per host — enough for hours of coord cadence; the ring
#: bounds memory on multi-day runs
DEFAULT_CAPACITY = 512

#: entries published to peers / dumped on stall (the interesting part
#: of a divergence is its first op, which lockstep keeps near the tail)
PUBLISH_TAIL = 64


class CollectiveDivergence(RuntimeError):
    """Two hosts issued DIFFERENT collective ops for the same round.

    Raised by the in-band lockstep check the moment the mismatched
    stamp arrives — naming the first divergent (host, round,
    expected-vs-seen) — instead of letting the skewed host pair
    mismatched rounds until a ``CoordinatorTimeout`` fires with no
    attribution.
    """

    def __init__(self, namespace: str, round_id: int, host: int,
                 expected: str, seen: str):
        super().__init__(
            f"collective divergence at namespace '{namespace}' round "
            f"{round_id}: host {host} issued '{seen}' where this host "
            f"issued '{expected}' — the hosts' collective sequences "
            f"split at this round (an identity-dependent branch, a "
            f"mid-protocol bail, or a swallowed error upstream); the "
            f"first divergent op above is the bug's address, fix the "
            f"branch that skipped or added it")
        self.namespace = namespace
        self.round_id = round_id
        self.host = host
        self.expected = expected
        self.seen = seen


def args_digest(*parts) -> str:
    """Stable 8-hex digest of protocol-identifying args — identical on
    every host for a lockstep call, cheap enough for every round."""
    blob = "\x1f".join(str(p) for p in parts).encode()
    return f"{zlib.crc32(blob) & 0xFFFFFFFF:08x}"


class CollectiveTrace:
    """Bounded per-host ring of ``(namespace, round, op, digest, t)``.

    ``clock`` is injectable (tests drive ring/timestamp semantics on a
    fake clock); timestamps are LOCAL diagnostics only and never
    participate in cross-host comparison.
    """

    def __init__(self, host: int = 0, capacity: int = DEFAULT_CAPACITY,
                 clock: Callable[[], float] = time.monotonic):
        self.host = int(host)
        self.capacity = int(capacity)
        self._clock = clock
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = OrderedLock("resilience.trace.ring")
        #: per-namespace auto round counters for stamp points that have
        #: no native round id (membership epochs, orbax barriers)
        self._counters: Dict[str, int] = {}
        self.recorded = 0
        #: rounds whose peer stamps the in-band check compared clean
        self.verified_rounds = 0
        #: divergences DETECTED by this host (chaos-smoke pins 0)
        self.divergences = 0

    # -- stamping ----------------------------------------------------------

    def record(self, namespace: str, op: str,
               round_id: Optional[int] = None,
               digest: Optional[str] = None) -> Tuple[str, int, str, str]:
        """Stamp one collective op; returns the entry (sans timestamp).

        round_id=None draws from the per-namespace counter (stamp
        points without a native round: membership installs, barriers).
        digest=None derives it from (namespace, op, round).
        """
        with self._lock:
            if round_id is None:
                round_id = self._counters.get(namespace, 0)
                self._counters[namespace] = round_id + 1
            if digest is None:
                digest = args_digest(namespace, op, round_id)
            entry = (namespace, int(round_id), op, digest)
            self._ring.append(entry + (self._clock(),))
            self.recorded += 1
        return entry

    def note_verified(self, n: int = 1) -> None:
        with self._lock:
            self.verified_rounds += n

    def note_divergence(self) -> None:
        with self._lock:
            self.divergences += 1

    # -- reading -----------------------------------------------------------

    def tail(self, n: int = PUBLISH_TAIL) -> List[Tuple]:
        with self._lock:
            items = list(self._ring)
        return items[-n:]

    def snapshot(self) -> dict:
        """The ``collective_trace`` verdict block (result-JSON /
        chaos-record schema; tests pin these keys)."""
        with self._lock:
            return {
                "host": self.host,
                "entries": self.recorded,
                "verified_rounds": self.verified_rounds,
                "divergences": self.divergences,
                "last": [list(e[:4]) for e in list(self._ring)[-8:]],
            }

    def render_tail(self, n: int = 16) -> str:
        """Human-readable tail for the watchdog stall dump: a hung
        consensus names the round it died in."""
        rows = [f"  {ns}/{rid}: {op} [{dig}] t={t:.3f}"
                for ns, rid, op, dig, t in self.tail(n)]
        head = (f"[collective-trace host {self.host}] last "
                f"{len(rows)} op(s) (of {self.recorded} recorded, "
                f"{self.verified_rounds} peer-verified, "
                f"{self.divergences} divergence(s)):")
        return "\n".join([head] + (rows or ["  <no collectives yet>"]))

    def dump(self, path: str) -> str:
        """Write the full ring to ``path`` (the CoordinatorTimeout
        message references this file); returns the path."""
        with open(path, "w") as f:
            f.write(self.render_tail(self.capacity) + "\n")
        return path

    # -- publication -------------------------------------------------------

    def encode_tail(self, n: int = PUBLISH_TAIL) -> str:
        """Wire form for KV publication: ``ns|round|op|digest`` rows
        joined by ``;`` (namespaces/ops never contain either)."""
        return ";".join(f"{ns}|{rid}|{op}|{dig}"
                        for ns, rid, op, dig, _ in self.tail(n))


def decode_trace(blob: str) -> List[Tuple[str, int, str, str]]:
    """Inverse of :meth:`CollectiveTrace.encode_tail`."""
    out: List[Tuple[str, int, str, str]] = []
    for row in blob.split(";"):
        if not row:
            continue
        ns, rid, op, dig = row.split("|")
        out.append((ns, int(rid), op, dig))
    return out


# --------------------------------------------------------------------------
# the lockstep verifier (pure: scripted-trace tests drive it directly)
# --------------------------------------------------------------------------


def verify_lockstep(traces: Dict[int, Sequence[Sequence]]) -> dict:
    """Cross-check per-host op sequences; name the FIRST divergent op.

    ``traces`` maps host id -> sequence of ``(namespace, round, op,
    digest)`` rows (extra trailing fields like timestamps are
    ignored). The lowest host id is the reference. Hosts are compared
    over their common prefix; a host whose trace ends while the
    reference continues is NOT a divergence (ring capacity and
    publish cadence legitimately skew lengths) — only a row that
    *disagrees* is.

    Returns ``{"ok", "hosts", "compared", "first_divergence"}`` where
    first_divergence is None or ``{"host", "index", "round",
    "namespace", "expected", "seen"}`` (expected = the reference
    host's op at that position).
    """
    if not traces:
        return {"ok": True, "hosts": 0, "compared": 0,
                "first_divergence": None}
    ref_host = min(traces)
    ref = [tuple(r[:4]) for r in traces[ref_host]]
    first: Optional[dict] = None
    compared = 0
    for host in sorted(traces):
        if host == ref_host:
            continue
        rows = [tuple(r[:4]) for r in traces[host]]
        for i in range(min(len(ref), len(rows))):
            compared += 1
            if rows[i] == ref[i]:
                continue
            ns, rid, op, dig = ref[i]
            sns, srid, sop, sdig = rows[i]
            div = {"host": host, "index": i, "round": srid,
                   "namespace": sns,
                   "expected": f"{ns}/{rid}:{op}[{dig}]",
                   "seen": f"{sns}/{srid}:{sop}[{sdig}]"}
            if first is None or i < first["index"]:
                first = div
            break
    return {"ok": first is None, "hosts": len(traces),
            "compared": compared, "first_divergence": first}


# --------------------------------------------------------------------------
# the process-global recorder
# --------------------------------------------------------------------------

_RECORDER: Optional[CollectiveTrace] = None


def install(host: int = 0, capacity: int = DEFAULT_CAPACITY,
            clock: Callable[[], float] = time.monotonic
            ) -> CollectiveTrace:
    """(Re)configure the process recorder — multihost children call
    this with their process id before the first collective; tests with
    a fake clock."""
    global _RECORDER
    _RECORDER = CollectiveTrace(host=host, capacity=capacity, clock=clock)
    return _RECORDER


def recorder() -> CollectiveTrace:
    """The process recorder, lazily created (host 0) so every wired
    stamp point works without setup."""
    global _RECORDER
    if _RECORDER is None:
        _RECORDER = CollectiveTrace()
    return _RECORDER


def record(namespace: str, op: str, round_id: Optional[int] = None,
           digest: Optional[str] = None) -> Tuple[str, int, str, str]:
    """Module-level stamp — the one-liner the wiring sites call."""
    return recorder().record(namespace, op, round_id=round_id,
                             digest=digest)
