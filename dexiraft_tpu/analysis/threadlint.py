"""threadlint — jaxlint's lock-discipline rules (JL020+).

The serve/resilience tier is a thread fabric (handler threads, one
dispatcher, health/probe/supervisor/flush/watchdog threads), and
CHANGES.md shows concurrency is the repo's most review-bug-prone class:
the RouterStats unlocked ``+=`` undercount (PR 11), the VideoEngine
stats-lock stall and RecompileWatch thread races (PR 14), and the
flush-barrier ordering bug (PR 10) were all caught by humans. Like
jaxlint's JAX footguns, these defects are *textual* — so this module
makes them a gate instead of a reviewer. The runtime half (deadlock
cycles, held spans, contention) lives in the sibling ``locks.py``.

Rule catalog (docs/static_analysis.md has the long-form version):

  JL020 unlocked-shared-write   plain write to a shared ``self.X`` that
                            the class protects under a lock elsewhere,
                            outside any ``with self._lock`` block — a
                            lost-update / torn-read race with every
                            locked reader.
  JL021 unlocked-rmw        read-modify-write (``self.x += n``,
                            ``self.d[k] = ...``, ``self.q.append``/
                            ``pop``/``update``/...) on a lock-protected
                            attr without the lock held — the silent
                            undercount class (the PR 11 RouterStats
                            bug, verbatim).
  JL022 manual-lock-acquire ``.acquire()`` on a lock attr with no
                            try-finally ``.release()`` in the function
                            — an exception between them wedges every
                            other thread forever; use ``with`` (or the
                            try/finally idiom) instead.
  JL023 blocking-under-lock a blocking call (sleep, subprocess,
                            urlopen, ``Thread.join``, ``Event.wait``,
                            future ``.result``, ``getresponse``,
                            ``jax.device_get``/``block_until_ready``)
                            while a lock is held — every thread
                            queueing on that lock stalls behind the
                            I/O. ``cv.wait`` on the held condition is
                            exempt (it releases while waiting).
  JL024 undeclared-lock-order   nested lock acquisition whose
                            (outer, inner) pair is not declared — both
                            locks must carry names from the central
                            LOCK_ORDER registry (analysis/locks.py)
                            with ranks in acquisition order, or the
                            runtime's cycle detector is the only thing
                            standing between the pair and an ABBA
                            deadlock.

Scope discipline (what keeps the rules quiet on honest code): JL020/21
run only inside classes that own a lock, and only on attrs the class
mutates *under* that lock somewhere — an attr never locked is not a
contract, and ``__init__`` (construction happens-before publication)
never counts. A helper method whose every intra-class call site sits
inside a ``with``-lock block is treated as lock-held (the
``_sweep``/``_note_affinity`` idiom), computed as a fixpoint. One level
of ``name = self.attr`` aliasing is resolved (the ``st = self.stats``
idiom). Cross-object state (``svc.engine.stats``) is out of static
reach — that is exactly what the OrderedLock runtime covers.

This module is pure stdlib and is loaded BY ``jaxlint.py`` by file
path (the shardlint pattern), so the gate, the baseline allowlist, and
``# jaxlint: disable=JL02X`` suppression all work unchanged.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

RULES = {
    "JL020": "unlocked-shared-write",
    "JL021": "unlocked-rmw",
    "JL022": "manual-lock-acquire",
    "JL023": "blocking-under-lock",
    "JL024": "undeclared-lock-order",
}

#: Mirror of the fleet's declared lock order (analysis/locks.py
#: LOCK_ORDER). threadlint must stay importable with zero package
#: imports (lint_gate loads it by file path pre-pytest), so the names
#: are pinned here and tests/test_zzzthreadlint.py asserts they equal
#: the live registry's — the shardlint LAYOUT_AXES idiom.
LOCK_ORDER: Tuple[str, ...] = (
    "serve.video.chunk",
    "serve.server.stop",
    "serve.scheduler.cv",
    "serve.router.supervisor",
    "serve.router.autoscale",
    "serve.router.pool",
    "serve.router.inflight",
    "serve.router.stats",
    "serve.video.inflight",
    "serve.video.stats",
    "serve.sessions.store",
    "serve.sessions.device",
    "analysis.guards.watch",
    "analysis.guards.listener",
    "resilience.watchdog.armed",
    "train.checkpoint.pending",
    "data.loader.pool",
    "resilience.trace.ring",
)
_RANK = {name: i for i, name in enumerate(LOCK_ORDER)}

# dotted names (post alias-resolution) that construct a lock
_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "Lock", "RLock",
    "OrderedLock", "locks.OrderedLock",
    "dexiraft_tpu.analysis.locks.OrderedLock",
}
_CV_CTORS = {"threading.Condition", "Condition"}
_THREAD_CTORS = {"threading.Thread", "Thread"}
# container/dict/deque methods that mutate their receiver in place
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "add", "discard",
    "remove", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "move_to_end",
}
# calls that block the calling thread (JL023)
_BLOCKING_DOTTED = {
    "time.sleep", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "urllib.request.urlopen", "socket.create_connection",
    "jax.device_get", "jax.block_until_ready",
}
_BLOCKING_ATTRS = {
    "sleep", "wait", "result", "getresponse", "urlopen",
    "block_until_ready", "recv", "accept", "connect", "join",
}


# --------------------------------------------------------------------------
# lock-carrier discovery
# --------------------------------------------------------------------------


def _lock_decl(linter, value: ast.AST) -> Optional[Tuple[bool, Optional[str]]]:
    """(is_lock, declared_name) when `value` constructs a lock:
    threading.Lock/RLock (name None), OrderedLock("name", ...), or a
    Condition over either. None when it is not a lock construction."""
    if not isinstance(value, ast.Call):
        return None
    callee = linter.mod.dotted(value.func)
    if callee in _LOCK_CTORS:
        name = None
        if (callee.split(".")[-1] == "OrderedLock" and value.args
                and isinstance(value.args[0], ast.Constant)
                and isinstance(value.args[0].value, str)):
            name = value.args[0].value
        return True, name
    if callee in _CV_CTORS:
        if value.args:
            inner = _lock_decl(linter, value.args[0])
            if inner is not None:
                return inner
        return True, None   # Condition() over its default RLock
    return None


def _class_locks(linter, cls: ast.ClassDef) -> Dict[str, Optional[str]]:
    """self-attr name -> declared OrderedLock name (None when the attr
    holds an anonymous threading lock)."""
    out: Dict[str, Optional[str]] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        decl = _lock_decl(linter, node.value)
        if decl is None:
            continue
        for t in node.targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                out[t.attr] = decl[1]
    return out


def _module_locks(linter) -> Dict[str, Optional[str]]:
    """Module-global lock name -> declared OrderedLock name."""
    out: Dict[str, Optional[str]] = {}
    for node in linter.mod.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        decl = _lock_decl(linter, node.value)
        if decl is None:
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out[t.id] = decl[1]
    return out


def _thread_attrs(linter, cls: ast.ClassDef) -> Set[str]:
    """self attrs assigned a threading.Thread (JL023's join targets)."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and linter.mod.dotted(node.value.func) in _THREAD_CTORS):
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    out.add(t.attr)
    return out


# --------------------------------------------------------------------------
# per-function scan
# --------------------------------------------------------------------------

# carrier key: ("self", attr) for self.<attr>, ("mod", name) for a
# module-global lock


def _carrier(node: ast.AST, self_locks: Dict[str, Optional[str]],
             module_locks: Dict[str, Optional[str]],
             aliases: Dict[str, str]):
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)):
        if node.value.id == "self" and node.attr in self_locks:
            return ("self", node.attr)
    if isinstance(node, ast.Name):
        if node.id in module_locks:
            return ("mod", node.id)
        attr = aliases.get(node.id)
        if attr is not None and attr in self_locks:
            return ("self", attr)
    return None


def _self_root(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """First attribute after `self` for a self.X[..].Y target/receiver,
    resolving one level of ``name = self.attr`` aliasing."""
    chain: List[str] = []
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
        if isinstance(node, ast.Attribute):
            chain.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        if node.id == "self":
            return chain[-1] if chain else None
        return aliases.get(node.id)
    return None


class _Mutation:
    __slots__ = ("root", "rmw", "locked", "node", "op", "method")

    def __init__(self, root, rmw, locked, node, op, method):
        self.root = root
        self.rmw = rmw
        self.locked = locked
        self.node = node
        self.op = op
        self.method = method


class _FnScan:
    """One pass over a function body tracking the held-lock stack."""

    def __init__(self, linter, fn, self_locks, module_locks, thread_attrs,
                 method_names: Set[str]):
        self.linter = linter
        self.fn = fn
        self.self_locks = self_locks
        self.module_locks = module_locks
        self.thread_attrs = thread_attrs
        self.method_names = method_names
        self.aliases: Dict[str, str] = {}
        self.thread_vars: Set[str] = set()
        self.mutations: List[_Mutation] = []
        self.calls: List[Tuple[str, bool]] = []      # (callee, locked)
        self.blocking: List[Tuple[ast.Call, bool, str]] = []
        self.acquires: Dict[tuple, List[ast.Call]] = {}
        self.released_in_finally: Set[tuple] = set()
        self.pairs: List[Tuple[tuple, tuple, ast.AST]] = []
        self._walk(fn.body, held=())

    # ---- statement walk -------------------------------------------------

    def _walk(self, stmts: Sequence[ast.stmt], held: tuple) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue   # fresh scope
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = held
                for item in stmt.items:
                    key = _carrier(item.context_expr, self.self_locks,
                                   self.module_locks, self.aliases)
                    if key is not None:
                        for outer in inner:
                            self.pairs.append((outer, key,
                                               item.context_expr))
                        inner = inner + (key,)
                    else:
                        self._scan_expr(item.context_expr, held)
                self._walk(stmt.body, inner)
                continue
            if isinstance(stmt, ast.Try):
                for key in self._finally_releases(stmt):
                    self.released_in_finally.add(key)
            self._scan_stmt_exprs(stmt, held)
            if isinstance(stmt, (ast.Assign, ast.AugAssign)):
                self._note_assignment(stmt, held)
            for blk in self._stmt_blocks(stmt):
                self._walk(blk, held)

    @staticmethod
    def _stmt_blocks(stmt: ast.stmt) -> List[List[ast.stmt]]:
        blocks = []
        for attr in ("body", "orelse", "finalbody"):
            blk = getattr(stmt, attr, None)
            if isinstance(blk, list) and blk and isinstance(blk[0], ast.stmt):
                blocks.append(blk)
        for h in getattr(stmt, "handlers", []) or []:
            blocks.append(h.body)
        return blocks

    def _finally_releases(self, stmt: ast.Try) -> List[tuple]:
        out = []
        for s in stmt.finalbody:
            for node in ast.walk(s):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "release"):
                    key = _carrier(node.func.value, self.self_locks,
                                   self.module_locks, self.aliases)
                    if key is not None:
                        out.append(key)
        return out

    # ---- expression scan ------------------------------------------------

    def _scan_stmt_exprs(self, stmt: ast.stmt, held: tuple) -> None:
        """Scan the statement's own expressions (not nested stmt lists)
        for calls: blocking-under-lock, intra-class calls, manual
        acquires, and in-place mutator calls."""
        for field, value in ast.iter_fields(stmt):
            values = value if isinstance(value, list) else [value]
            for v in values:
                if isinstance(v, ast.expr):
                    self._scan_expr(v, held)

    def _scan_expr(self, expr: ast.AST, held: tuple) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            # intra-class call (the lock-held-helper fixpoint input)
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self"
                    and f.attr in self.method_names):
                self.calls.append((f.attr, bool(held)))
            # manual acquire on a lock carrier
            if isinstance(f, ast.Attribute) and f.attr == "acquire":
                key = _carrier(f.value, self.self_locks,
                               self.module_locks, self.aliases)
                if key is not None:
                    self.acquires.setdefault(key, []).append(node)
            # in-place mutator on a self-rooted container
            if (isinstance(f, ast.Attribute) and f.attr in _MUTATORS):
                root = _self_root(f.value, self.aliases)
                if root is not None and root not in self.self_locks:
                    self.mutations.append(_Mutation(
                        root, True, bool(held), node,
                        f".{f.attr}()", self.fn.name))
            # blocking call while a lock is held
            if held:
                self._note_blocking(node, held)
            elif self._is_blocking(node, held):
                # recorded unbound: flagged later iff the whole method
                # proves lock-held via the call-graph fixpoint
                self.blocking.append((node, False, self._blocking_label(node)))

    def _is_blocking(self, node: ast.Call, held: tuple) -> bool:
        f = node.func
        dotted = self.linter.mod.dotted(f)
        if dotted in _BLOCKING_DOTTED:
            return True
        if isinstance(f, ast.Attribute) and f.attr in _BLOCKING_ATTRS:
            if f.attr == "join" and not self._threadish(f.value):
                return False   # str.join / os.path.join
            if f.attr == "wait":
                key = _carrier(f.value, self.self_locks,
                               self.module_locks, self.aliases)
                if key is not None and (key in held or not held):
                    # cv.wait on the held condition RELEASES while
                    # waiting — the one sanctioned blocking wait
                    return False
            return True
        return False

    def _blocking_label(self, node: ast.Call) -> str:
        dotted = self.linter.mod.dotted(node.func)
        if dotted in _BLOCKING_DOTTED:
            return dotted
        return f".{node.func.attr}()"

    def _note_blocking(self, node: ast.Call, held: tuple) -> None:
        if self._is_blocking(node, held):
            self.blocking.append((node, True, self._blocking_label(node)))

    def _threadish(self, recv: ast.AST) -> bool:
        root = _self_root(recv, self.aliases)
        if root is not None and root in self.thread_attrs:
            return True
        return isinstance(recv, ast.Name) and recv.id in self.thread_vars

    # ---- assignments ----------------------------------------------------

    def _note_assignment(self, stmt, held: tuple) -> None:
        locked = bool(held)
        if isinstance(stmt, ast.AugAssign):
            root = _self_root(stmt.target, self.aliases)
            if root is not None and root not in self.self_locks:
                op = type(stmt.op).__name__
                self.mutations.append(_Mutation(
                    root, True, locked, stmt, f"aug-assign ({op})",
                    self.fn.name))
            return
        # plain Assign: aliases, thread vars, then target mutations
        if isinstance(stmt.value, ast.Call):
            callee = self.linter.mod.dotted(stmt.value.func)
            if callee in _THREAD_CTORS:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.thread_vars.add(t.id)
        for t in stmt.targets:
            if (isinstance(t, ast.Name)
                    and isinstance(stmt.value, ast.Attribute)
                    and isinstance(stmt.value.value, ast.Name)
                    and stmt.value.value.id == "self"):
                self.aliases[t.id] = stmt.value.attr
        for t in stmt.targets:
            targets = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for tgt in targets:
                if isinstance(tgt, ast.Name):
                    continue
                root = _self_root(tgt, self.aliases)
                if root is None or root in self.self_locks:
                    continue
                rmw = isinstance(tgt, ast.Subscript)
                self.mutations.append(_Mutation(
                    root, rmw, locked, stmt,
                    "subscript-store" if rmw else "attribute write",
                    self.fn.name))


# --------------------------------------------------------------------------
# class-level analysis
# --------------------------------------------------------------------------


def _lockheld_fixpoint(scans: Dict[str, _FnScan]
                       ) -> Tuple[Set[str], Set[str]]:
    """(always_locked, sometimes_locked) method sets, by intra-class
    call-site analysis (the ``_sweep`` idiom, as a fixpoint).

    always_locked: EVERY call site is lock-held (directly or via
    another always-locked method) — the method's mutations are
    sanctioned. sometimes_locked: >= 1 call site is lock-held — the
    method's mutations still ESTABLISH the protection contract (the
    class does lock this state), so a method that is also reachable
    unlocked gets flagged rather than silently untracked. A method
    with no intra-class call sites is neither (it is API)."""
    sites: Dict[str, List[Tuple[str, bool]]] = {}
    for caller, scan in scans.items():
        for callee, locked in scan.calls:
            sites.setdefault(callee, []).append((caller, locked))
    always: Set[str] = set()
    sometimes: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for name in scans:
            if name == "__init__":
                continue
            callers = sites.get(name)
            if not callers:
                continue
            if name not in always and all(
                    locked or c in always for c, locked in callers):
                always.add(name)
                changed = True
            if name not in sometimes and any(
                    locked or c in sometimes for c, locked in callers):
                sometimes.add(name)
                changed = True
    return always, sometimes | always


def _check_class(linter, cls: ast.ClassDef,
                 module_locks: Dict[str, Optional[str]]) -> None:
    self_locks = _class_locks(linter, cls)
    methods = {n.name: n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    if not self_locks:
        # no lock, no contract: single-threaded classes (and ones whose
        # callers own the locking) stay out of JL020/21's reach
        return
    thread_attrs = _thread_attrs(linter, cls)
    scans = {name: _FnScan(linter, fn, self_locks, module_locks,
                           thread_attrs, set(methods))
             for name, fn in methods.items()}
    lockheld, sometimes_locked = _lockheld_fixpoint(scans)

    # tracked attrs: mutated under a lock somewhere in the class —
    # that locked site IS the class's declared protection contract
    # (sometimes_locked is deliberately the wider set: a helper with a
    # single locked call site still declares the contract, and its
    # OTHER, unlocked reachability is then the finding)
    tracked: Dict[str, str] = {}
    for name, scan in scans.items():
        if name == "__init__":
            continue
        for m in scan.mutations:
            if ((m.locked or name in sometimes_locked)
                    and m.root not in tracked):
                tracked[m.root] = name
    for name, scan in scans.items():
        if name == "__init__":
            continue
        for m in scan.mutations:
            if m.locked or name in lockheld or m.root not in tracked:
                continue
            lock_names = ", ".join(f"self.{a}" for a in sorted(self_locks))
            if m.rmw:
                linter.flag(
                    "JL021", m.node,
                    f"read-modify-write of shared 'self.{m.root}' "
                    f"({m.op}) in {cls.name}.{name} without the lock — "
                    f"the class protects this attr under a lock in "
                    f"{cls.name}.{tracked[m.root]}; concurrent updates "
                    f"lose increments (the RouterStats undercount bug "
                    f"class). Hold {lock_names} here")
            else:
                linter.flag(
                    "JL020", m.node,
                    f"write to shared 'self.{m.root}' in "
                    f"{cls.name}.{name} without the lock — the class "
                    f"protects this attr under a lock in "
                    f"{cls.name}.{tracked[m.root]}, so this write races "
                    f"every locked reader. Hold {lock_names} here")
        _flag_fn_common(linter, cls.name, scan,
                        whole_fn_locked=scan.fn.name in lockheld,
                        self_locks=self_locks, module_locks=module_locks)


def _flag_fn_common(linter, owner: str, scan: _FnScan, *,
                    whole_fn_locked: bool,
                    self_locks: Dict[str, Optional[str]],
                    module_locks: Dict[str, Optional[str]]) -> None:
    """JL022/JL023/JL024 for one scanned function."""
    # JL022: manual acquire with no try-finally release in the function
    for key, nodes in scan.acquires.items():
        if key in scan.released_in_finally:
            continue
        label = key[1] if key[0] == "mod" else f"self.{key[1]}"
        for node in nodes:
            linter.flag(
                "JL022", node,
                f"manual {label}.acquire() in {owner}.{scan.fn.name} "
                f"with no try-finally release in the function — an "
                f"exception between acquire and release wedges every "
                f"other thread on this lock; use `with {label}:` (or "
                f"release in a finally)")
    # JL023: blocking calls under a held lock (or in a provably
    # lock-held helper)
    for node, held, label in scan.blocking:
        if not held and not whole_fn_locked:
            continue
        linter.flag(
            "JL023", node,
            f"blocking call {label} in {owner}.{scan.fn.name} while a "
            f"lock is held — every thread queueing on that lock stalls "
            f"behind this wait; move the blocking work outside the "
            f"locked region (snapshot under the lock, block after)")
    # JL024: nested acquisition pairs vs the declared order
    for outer, inner, node in scan.pairs:
        o_name = (module_locks if outer[0] == "mod"
                  else self_locks).get(outer[1])
        i_name = (module_locks if inner[0] == "mod"
                  else self_locks).get(inner[1])
        o_lbl = outer[1] if outer[0] == "mod" else f"self.{outer[1]}"
        i_lbl = inner[1] if inner[0] == "mod" else f"self.{inner[1]}"
        if o_name is None or i_name is None:
            anon = o_lbl if o_name is None else i_lbl
            linter.flag(
                "JL024", node,
                f"nested lock acquisition {o_lbl} -> {i_lbl} in "
                f"{owner}.{scan.fn.name}, but {anon} is an anonymous "
                f"lock — nested locks must be OrderedLocks named in "
                f"the central LOCK_ORDER registry (analysis/locks.py) "
                f"so the pair's order is declared and runtime-checked")
            continue
        if o_name not in _RANK or i_name not in _RANK:
            missing = o_name if o_name not in _RANK else i_name
            linter.flag(
                "JL024", node,
                f"nested lock acquisition '{o_name}' -> '{i_name}' in "
                f"{owner}.{scan.fn.name}, but '{missing}' is not in "
                f"the LOCK_ORDER registry (analysis/locks.py) — "
                f"declare it so the pair participates in the total "
                f"order")
            continue
        if _RANK[o_name] >= _RANK[i_name]:
            linter.flag(
                "JL024", node,
                f"nested lock acquisition '{o_name}' (rank "
                f"{_RANK[o_name]}) -> '{i_name}' (rank "
                f"{_RANK[i_name]}) in {owner}.{scan.fn.name} inverts "
                f"the declared LOCK_ORDER — another path nesting these "
                f"in registry order would ABBA-deadlock against this "
                f"one")


def _check_module_functions(linter,
                            module_locks: Dict[str, Optional[str]]) -> None:
    """JL022/23/24 for module-level functions using module-global locks
    (the train/checkpoint.py shape). JL020/21 stay class-scoped: module
    globals have no single owning lock contract to infer."""
    if not module_locks:
        return
    for node in linter.mod.tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        scan = _FnScan(linter, node, {}, module_locks, set(), set())
        _flag_fn_common(linter, "<module>", scan, whole_fn_locked=False,
                        self_locks={}, module_locks=module_locks)


def run_rules(linter) -> None:
    """Entry point jaxlint's _Linter calls; duck-typed on (mod, flag)."""
    module_locks = _module_locks(linter)
    for node in ast.walk(linter.mod.tree):
        if isinstance(node, ast.ClassDef):
            _check_class(linter, node, module_locks)
    _check_module_functions(linter, module_locks)
