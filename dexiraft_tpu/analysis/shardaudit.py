"""Shard audit — the dynamic half of the sharding contract.

shardlint (JL010+) proves every spec is *drawn from* the canonical
layout; this pass proves what the compiler actually *does* with them.
It lowers and compiles the donated train step and the eval/serve steps
on a forced 8-virtual-device host mesh (no TPU needed — GSPMD
partitioning is platform-independent), reads every input/output leaf's
resolved sharding off the compiled executables, and

  * diffs the result against the checked-in golden
    (``analysis/layout_golden.json``) — ANY drift is a nonzero exit, so
    a silently changed spec fails CI the same way a lint finding does;
  * resolves the layout's *declared* array groups (batch, carry, and
    the on-demand correlation fmap set — the canary, now that the
    flash-blocked kernel killed the materialized all-pairs volume in
    the production eval/serve config) at the production reference
    geometry and flags any group over a size threshold that resolves
    fully replicated and is not pinned as replicated-by-design in
    ``parallel.layout.REPLICATED_OK``.

Three goldens: ``layout_golden.json`` pins the data x seq (and serve)
legs exactly as before; ``layout_golden_fsdp.json`` pins the FENCE
train step on the virtual {data x fsdp x seq} mesh — params/opt_state
resolved to their per-leaf fsdp storage shardings, divisibility-
fallback leaves replicated, and the over-threshold replicated canary
armed on them with no REPLICATED_OK exemption; and
``layout_golden_halo.json`` pins the HALO compute-sharded train step
(compute_sharding="halo") on the same mesh — identical state storage
groups, batch leaves P('data', 'seq') as shard_map slab inputs, and
the declared halo_activations canary armed at the production geometry.

Run it via ``scripts/shard_audit.py`` (which forces the host platform
before jax initializes); the tier-1 verify command runs it right after
``lint_gate.py`` and audits ALL goldens by default. Regeneration
workflow: docs/static_analysis.md.

Granularity note: shardings are reported per GROUP (a state field, a
batch key — e.g. ``[0].params`` or ``[1]['image1']``), each carrying
the SET of distinct specs its leaves resolved to. That keeps the golden
compact and stable across param-tree growth while still failing on any
spec change (a single differently-pinned leaf adds a spec to its
group's set).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

GOLDEN_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "layout_golden.json")
#: The fsdp leg's golden: the train step compiled on a virtual
#: {data x fsdp x seq} mesh, params/opt_state resolved to their fsdp
#: storage shardings (per-leaf, divisibility fallback included) plus the
#: declared groups re-resolved on that mesh. A separate file so the
#: data x seq golden's semantics stay untouched.
FSDP_GOLDEN_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "layout_golden_fsdp.json")
#: The halo compute-sharding leg's golden: the train step compiled with
#: compute_sharding="halo" on the same {data x fsdp x seq} mesh. Its
#: semantics differ from the fsdp leg's in exactly the ways the mode
#: promises — batch leaves resolve P('data', 'seq') INTO a shard_map
#: (explicit slabs, not GSPMD annotations), the state keeps its fsdp
#: storage layout with NO gather fence inside, and metrics replicate.
HALO_GOLDEN_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "layout_golden_halo.json")

#: Audit geometry: small model + tiny frames keep the three compiles
#: ~a minute on CPU; the SPECS resolved are geometry-independent.
AUDIT_IMAGE = (48, 64)
AUDIT_BATCH = 8
AUDIT_ITERS = 2
#: Production reference geometry for the declared-group size tripwire
#: (Sintel serve shape; the per-sample all-pairs volume here is the
#: ~200 MB canary).
PROD_IMAGE = (440, 1024)
PROD_BATCH = 8
DEFAULT_THRESHOLD_MB = 64.0
#: The audit's train/eval mesh (the MULTICHIP dryrun mesh) and the
#: serve mesh, as {axis: size} over the 8 forced host devices.
TRAIN_MESH = {"data": 4, "seq": 2}
SERVE_MESH = {"data": 8}
#: The fsdp leg's mesh: all three axes live on the same 8 devices, so
#: the golden pins how the fsdp storage shardings compose with data and
#: seq compute sharding in one compile.
FSDP_MESH = {"data": 2, "fsdp": 2, "seq": 2}


def _group_key(path: Tuple[Any, ...]) -> str:
    """First two key-path entries — field-of-argument granularity."""
    from jax.tree_util import keystr

    return keystr(tuple(path[:2]))


def _section(shardings, avals) -> Dict[str, Dict[str, Any]]:
    """(shardings pytree, matching avals pytree) -> per-group summary:
    sorted unique spec strings, leaf count, total/max leaf bytes."""
    import numpy as np
    from jax.tree_util import tree_flatten_with_path

    from dexiraft_tpu.parallel.layout import spec_str

    s_leaves = tree_flatten_with_path(shardings)[0]
    a_leaves = tree_flatten_with_path(avals)[0]
    groups: Dict[str, Dict[str, Any]] = {}
    by_path = {tuple(p): s for p, s in s_leaves}
    for path, aval in a_leaves:
        sh = by_path.get(tuple(path))
        if sh is None:
            continue
        key = _group_key(tuple(path))
        g = groups.setdefault(key, {"specs": set(), "leaves": 0,
                                    "bytes": 0, "max_leaf_bytes": 0})
        g["specs"].add(spec_str(sh.spec))
        g["leaves"] += 1
        nbytes = int(np.prod(aval.shape, dtype=np.int64)
                     * np.dtype(aval.dtype).itemsize)
        g["bytes"] += nbytes
        g["max_leaf_bytes"] = max(g["max_leaf_bytes"], nbytes)
    return {k: {"specs": sorted(v["specs"]), "leaves": v["leaves"],
                "bytes": v["bytes"], "max_leaf_bytes": v["max_leaf_bytes"]}
            for k, v in sorted(groups.items())}


def _mesh_dict(mesh) -> Dict[str, int]:
    return {str(k): int(v) for k, v in mesh.shape.items()}


def _compiled_sections(jitted, args: Tuple[Any, ...]) -> Dict[str, Any]:
    """Lower+compile on abstract avals; return in/out group summaries."""
    lowered = jitted.lower(*args)
    compiled = lowered.compile()
    in_sh = compiled.input_shardings[0]  # (args, kwargs) — args side
    out_sh = compiled.output_shardings
    # output avals ride the Lowered we already have — an eval_shape here
    # would re-trace the whole (grad-of-scan) step a second time
    out_avals = lowered.out_info
    return {"in": _section(in_sh, args), "out": _section(out_sh, out_avals)}


def _audit_state(cfg, tc):
    """Abstract TrainState (shapes/dtypes only — nothing allocated)."""
    import jax

    from dexiraft_tpu.train.state import create_state

    return jax.eval_shape(
        lambda: create_state(jax.random.PRNGKey(0), cfg, tc))


def _batch_avals(batch_size: int, h: int, w: int):
    import numpy as np
    import jax

    return {
        "image1": jax.ShapeDtypeStruct((batch_size, h, w, 3), np.float32),
        "image2": jax.ShapeDtypeStruct((batch_size, h, w, 3), np.float32),
        "flow": jax.ShapeDtypeStruct((batch_size, h, w, 2), np.float32),
        "valid": jax.ShapeDtypeStruct((batch_size, h, w), np.float32),
    }


def audit_train(mesh=None) -> Dict[str, Any]:
    from dexiraft_tpu.config import TrainConfig, raft_v1
    from dexiraft_tpu.parallel.layout import make_mesh_2d
    from dexiraft_tpu.train.step import make_train_step

    if mesh is None:
        mesh = make_mesh_2d(TRAIN_MESH["data"], TRAIN_MESH["seq"])
    h, w = AUDIT_IMAGE
    cfg = raft_v1(small=True)
    tc = TrainConfig(name="shardaudit", stage="chairs", num_steps=10,
                     batch_size=AUDIT_BATCH, image_size=(h, w),
                     iters=AUDIT_ITERS)
    step = make_train_step(cfg, tc, mesh=mesh)
    state = _audit_state(cfg, tc)
    sections = _compiled_sections(step, (state, _batch_avals(AUDIT_BATCH,
                                                             h, w)))
    return {"mesh": _mesh_dict(mesh), **sections}


def audit_train_fsdp(mesh=None) -> Dict[str, Any]:
    """The fsdp leg: the SAME donated train step compiled on the
    {data x fsdp x seq} mesh. The resolved in/out state shardings are
    the storage layout (params/opt_state per-leaf over 'fsdp', small
    leaves replicated by the layout's divisibility fallback); the batch
    keeps P('data', 'seq') — fsdp is storage, not compute (the step's
    gather fences), so the compute sections must look exactly like the
    data x seq leg's apart from the state groups."""
    from dexiraft_tpu.parallel.layout import make_mesh_fsdp

    if mesh is None:
        mesh = make_mesh_fsdp(FSDP_MESH["data"], FSDP_MESH["fsdp"],
                              FSDP_MESH["seq"])
    return audit_train(mesh)


def audit_train_halo(mesh=None) -> Dict[str, Any]:
    """The halo compute-sharding leg: the train step built with
    compute_sharding="halo" on the {data x fsdp x seq} mesh
    (train/step._make_halo_train_step -> parallel/halo). The golden
    pins the mode's whole contract at the jit boundary: state in/out in
    fsdp STORAGE layout (identical groups to the fsdp leg — the two
    modes interchange on the same stored state), batch leaves
    P('data', 'seq') as shard_map slab inputs, loss/metrics replicated.
    The audit geometry satisfies the halo divisibility rules by
    construction (48 rows / (8*2) = 3 feature rows per seq device)."""
    from dexiraft_tpu.config import TrainConfig, raft_v1
    from dexiraft_tpu.parallel.layout import make_mesh_fsdp
    from dexiraft_tpu.train.step import make_train_step

    if mesh is None:
        mesh = make_mesh_fsdp(FSDP_MESH["data"], FSDP_MESH["fsdp"],
                              FSDP_MESH["seq"])
    h, w = AUDIT_IMAGE
    cfg = raft_v1(small=True)
    tc = TrainConfig(name="shardaudit", stage="chairs", num_steps=10,
                     batch_size=AUDIT_BATCH, image_size=(h, w),
                     iters=AUDIT_ITERS)
    step = make_train_step(cfg, tc, mesh=mesh, compute_sharding="halo")
    state = _audit_state(cfg, tc)
    sections = _compiled_sections(step, (state, _batch_avals(AUDIT_BATCH,
                                                             h, w)))
    return {"mesh": _mesh_dict(mesh), **sections}


def _audit_eval_step(mesh) -> Dict[str, Any]:
    """Shared body for the eval and serve audits — same forward step,
    different mesh (2-D train mesh vs 1-D serve mesh).

    Compiles the PRODUCTION eval/serve configuration: the flash-blocked
    fused step (corr_impl="flash" + fused_update — what
    resolve_corr_impl("auto") picks on TPU), so the audited executables
    are the volume-free ones that actually serve. The Pallas kernel is
    forced into interpreter mode for the compile — this audit runs on
    the CPU backend, where Mosaic cannot lower; the resolved in/out
    shardings are unaffected (GSPMD partitions the jit boundary, and
    the param tree is identical across corr impls by the
    FusedCorrEncoder contract)."""
    import numpy as np
    import jax

    from dexiraft_tpu.config import TrainConfig, raft_v1
    from dexiraft_tpu.train.step import make_eval_step

    h, w = AUDIT_IMAGE
    cfg = raft_v1(small=True, corr_impl="flash", fused_update=True)
    state = _audit_state(cfg, TrainConfig())
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    im = jax.ShapeDtypeStruct((AUDIT_BATCH, h, w, 3), np.float32)
    fi = jax.ShapeDtypeStruct((AUDIT_BATCH, h // 8, w // 8, 2), np.float32)
    prev = os.environ.get("DEXIRAFT_PALLAS_INTERPRET")
    os.environ["DEXIRAFT_PALLAS_INTERPRET"] = "1"
    try:
        step = make_eval_step(cfg, iters=AUDIT_ITERS, mesh=mesh)
        sections = _compiled_sections(step,
                                      (variables, im, im, None, None, fi))
    finally:
        if prev is None:
            os.environ.pop("DEXIRAFT_PALLAS_INTERPRET", None)
        else:
            os.environ["DEXIRAFT_PALLAS_INTERPRET"] = prev
    return {"mesh": _mesh_dict(mesh), **sections}


def audit_eval(mesh=None) -> Dict[str, Any]:
    from dexiraft_tpu.parallel.layout import make_mesh_2d

    if mesh is None:
        mesh = make_mesh_2d(TRAIN_MESH["data"], TRAIN_MESH["seq"])
    return _audit_eval_step(mesh)


def audit_serve(mesh=None) -> Dict[str, Any]:
    from dexiraft_tpu.parallel.layout import make_serve_mesh

    if mesh is None:
        mesh = make_serve_mesh(SERVE_MESH["data"])
    return _audit_eval_step(mesh)


def _serve_mesh_and_cfg():
    """Shared setup for the split-step serve audits: the 1-D serve mesh
    and the production eval/serve config (flash + fused — what
    resolve_corr_impl("auto") picks on TPU), matching _audit_eval_step
    so the split signatures are audited in the same configuration as
    the monolithic one they compose into."""
    from dexiraft_tpu.config import raft_v1
    from dexiraft_tpu.parallel.layout import make_serve_mesh

    return (make_serve_mesh(SERVE_MESH["data"]),
            raft_v1(small=True, corr_impl="flash", fused_update=True))


def audit_serve_encode(mesh=None) -> Dict[str, Any]:
    """The streaming tier's per-frame encoder stage (PR 14: RAFT
    mode="encode" via train.step.make_encode_step), compiled on the
    serve mesh. The golden pins variables replicated, the frame batch
    P('data', ...), and every feature-dict output leaf (fmap/ctx) batch-
    sharded — the device-resident session carry stores these arrays
    as-is, so a spec change here silently changes what N streams pin in
    HBM. Golden regenerated for this audit's introduction (new section,
    no pre-existing specs changed)."""
    import numpy as np
    import jax

    from dexiraft_tpu.config import TrainConfig
    from dexiraft_tpu.train.step import make_encode_step

    default_mesh, cfg = _serve_mesh_and_cfg()
    if mesh is None:
        mesh = default_mesh
    h, w = AUDIT_IMAGE
    state = _audit_state(cfg, TrainConfig())
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    im = jax.ShapeDtypeStruct((AUDIT_BATCH, h, w, 3), np.float32)
    step = make_encode_step(cfg, mesh=mesh)
    sections = _compiled_sections(step, (variables, im, None))
    return {"mesh": _mesh_dict(mesh), **sections}


def audit_serve_refine(mesh=None) -> Dict[str, Any]:
    """The streaming tier's refinement stage (RAFT mode="step" via
    train.step.make_refine_step) on the serve mesh: feature dicts in,
    (flow_low, flow_up) out, everything batch-sharded, variables
    replicated. Feature avals come from eval_shape over the encode step
    — the audit can never drift from the real carry shapes. Same Pallas
    interpreter dance as _audit_eval_step (CPU backend; resolved
    shardings are unaffected)."""
    import numpy as np
    import jax

    from dexiraft_tpu.config import TrainConfig
    from dexiraft_tpu.train.step import make_encode_step, make_refine_step

    default_mesh, cfg = _serve_mesh_and_cfg()
    if mesh is None:
        mesh = default_mesh
    h, w = AUDIT_IMAGE
    state = _audit_state(cfg, TrainConfig())
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    im = jax.ShapeDtypeStruct((AUDIT_BATCH, h, w, 3), np.float32)
    fi = jax.ShapeDtypeStruct((AUDIT_BATCH, h // 8, w // 8, 2), np.float32)
    feats = jax.eval_shape(make_encode_step(cfg), variables, im)
    prev = os.environ.get("DEXIRAFT_PALLAS_INTERPRET")
    os.environ["DEXIRAFT_PALLAS_INTERPRET"] = "1"
    try:
        step = make_refine_step(cfg, iters=AUDIT_ITERS, mesh=mesh)
        sections = _compiled_sections(step, (variables, feats, feats, fi))
    finally:
        if prev is None:
            os.environ.pop("DEXIRAFT_PALLAS_INTERPRET", None)
        else:
            os.environ["DEXIRAFT_PALLAS_INTERPRET"] = prev
    return {"mesh": _mesh_dict(mesh), **sections}


def declared_groups(threshold_mb: float = DEFAULT_THRESHOLD_MB,
                    mesh=None, halo: bool = False) -> Dict[str, Any]:
    """Resolve the layout's declared array groups at the PRODUCTION
    reference geometry: per-group canonical spec, total bytes, bytes
    per device, and the replicated-over-threshold flag. This is where
    the size canaries live — intermediates (corr_fmaps) and persistent
    state (params/opt_state, which since the fsdp axis went live carry
    NO replicated-by-design exemption: on an fsdp mesh they resolve
    sharded, and a layout change that pins them replicated over the
    threshold fails the audit)."""
    from dexiraft_tpu.parallel.layout import (
        LAYOUT,
        REPLICATED_OK,
        make_mesh_2d,
        spec_str,
    )

    if mesh is None:
        mesh = make_mesh_2d(TRAIN_MESH["data"], TRAIN_MESH["seq"])
    h, w = PROD_IMAGE
    b = PROD_BATCH
    hw8 = (h // 8) * (w // 8)
    # (name, spec, total bytes at the reference geometry). Totals are
    # FULL-BATCH so every axis in the spec genuinely divides its dim —
    # a per-sample (B=1) total divided by the data axis would understate
    # the per-device footprint 4x (GSPMD cannot split a size-1 dim).
    #
    # The corr_volume group is GONE (ISSUE 12): the production eval/
    # serve config is the flash-blocked kernel, which never materializes
    # the all-pairs volume — only the fmaps live in HBM. The canary
    # moved to corr_fmaps, the streamed tensor set of the on-demand
    # path (fmap1 + the 4-level pooled fmap2 pyramid, 256-channel fp32):
    # ~134 MB full-batch at 440x1024, still over the 64 MB tripwire if
    # ever pinned replicated. (--corr_impl allpairs still exists; its
    # volume keeps the canonical LAYOUT.corr_volume spec.)
    fmap_bytes = b * hw8 * 256 * 4  # one (B, H/8, W/8, 256) fp32 fmap
    pyramid_bytes = sum(b * (hw8 >> (2 * i)) * 256 * 4 for i in range(4))
    entries = [
        ("batch", LAYOUT.batch_for(mesh), b * h * w * 3 * 4 * 2),
        ("carry", LAYOUT.carry(), b * hw8 * 2 * 4),
        ("corr_fmaps", LAYOUT.corr_fmaps(mesh),
         fmap_bytes + pyramid_bytes),
        ("params", LAYOUT.params(mesh), 5_300_000 * 4),
        ("opt_state", LAYOUT.opt_state(mesh), 2 * 5_300_000 * 4),
    ]
    if halo:
        # halo-mode ACTIVATIONS canary (the halo leg only): the sharded
        # forward's persistent feature-map working set — fmap1 + fmap2 +
        # context, (B, H/8, W/8, 256) fp32 each at the reference
        # geometry (~165 MB full-batch at 440x1024). Declared with the
        # shard_map slab spec P('data', 'seq'); if a layout change ever
        # resolves it fully replicated it trips the 64 MB wire with no
        # REPLICATED_OK exemption — replicated activations at pod batch
        # sizes are exactly the regression halo mode exists to prevent
        entries.append(("halo_activations",
                        LAYOUT.batch_spatial_compute(), 3 * fmap_bytes))
    mesh_shape = dict(mesh.shape)
    out = {}
    for name, spec, total in entries:
        shards = 1
        for entry in tuple(spec):
            for ax in (entry if isinstance(entry, tuple) else (entry,)):
                if ax is not None:
                    shards *= mesh_shape.get(ax, 1)
        per_device = total // shards
        replicated = shards == 1
        flagged = (replicated and per_device > threshold_mb * 2**20
                   and name not in REPLICATED_OK)
        out[name] = {
            "spec": spec_str(spec),
            "total_mb": round(total / 2**20, 2),
            "per_device_mb": round(per_device / 2**20, 2),
            "replicated": replicated,
            "flagged": flagged,
        }
    return out


STEP_AUDITS = {"train": audit_train, "eval": audit_eval,
               "serve": audit_serve,
               # the split-model streaming signatures (PR 14): the same
               # param tree as `serve` compiled as separate encode /
               # refine executables — the device-carry session store
               # holds the encode outputs between frames, so their
               # resolved shardings are part of the serving contract
               "serve_encode": audit_serve_encode,
               "serve_refine": audit_serve_refine}
#: Steps audited against the SEPARATE fsdp golden (FSDP_GOLDEN_PATH).
FSDP_STEP_AUDITS = {"train_fsdp": audit_train_fsdp}
#: Steps audited against the halo golden (HALO_GOLDEN_PATH).
HALO_STEP_AUDITS = {"train_halo": audit_train_halo}


def _report_header() -> Dict[str, Any]:
    from dexiraft_tpu.parallel.layout import LAYOUT

    return {
        "version": 1,
        "axes": {"data": LAYOUT.data_axis, "fsdp": LAYOUT.fsdp_axis,
                 "seq": LAYOUT.seq_axis},
        "audit_image": list(AUDIT_IMAGE),
        "audit_batch": AUDIT_BATCH,
    }


def run_audit(steps: Sequence[str] = ("train", "eval", "serve",
                                      "serve_encode", "serve_refine"),
              threshold_mb: float = DEFAULT_THRESHOLD_MB) -> Dict[str, Any]:
    report: Dict[str, Any] = {
        **_report_header(),
        "steps": {},
        "declared": declared_groups(threshold_mb),
    }
    for name in steps:
        report["steps"][name] = STEP_AUDITS[name]()
    return report


def run_audit_fsdp(steps: Sequence[str] = ("train_fsdp",),
                   threshold_mb: float = DEFAULT_THRESHOLD_MB
                   ) -> Dict[str, Any]:
    """The fsdp report, diffed against FSDP_GOLDEN_PATH: the train step
    on the {data x fsdp x seq} mesh plus the declared groups re-resolved
    there — params/opt_state show P('fsdp') with replicated=False, and
    the over-threshold canary stays armed with no exemption."""
    from dexiraft_tpu.parallel.layout import make_mesh_fsdp

    mesh = make_mesh_fsdp(FSDP_MESH["data"], FSDP_MESH["fsdp"],
                          FSDP_MESH["seq"])
    report: Dict[str, Any] = {
        **_report_header(),
        "steps": {},
        "declared": declared_groups(threshold_mb, mesh=mesh),
    }
    for name in steps:
        report["steps"][name] = FSDP_STEP_AUDITS[name]()
    return report


def run_audit_halo(steps: Sequence[str] = ("train_halo",),
                   threshold_mb: float = DEFAULT_THRESHOLD_MB
                   ) -> Dict[str, Any]:
    """The halo report, diffed against HALO_GOLDEN_PATH: the
    compute_sharding="halo" train step on the {data x fsdp x seq} mesh
    plus the declared groups re-resolved there WITH the
    halo_activations canary (declared_groups(halo=True)) — the sharded
    forward's feature-map set declared P('data', 'seq') and the 64 MB
    replicated tripwire armed on it."""
    from dexiraft_tpu.parallel.layout import make_mesh_fsdp

    mesh = make_mesh_fsdp(FSDP_MESH["data"], FSDP_MESH["fsdp"],
                          FSDP_MESH["seq"])
    report: Dict[str, Any] = {
        **_report_header(),
        "steps": {},
        "declared": declared_groups(threshold_mb, mesh=mesh, halo=True),
    }
    for name in steps:
        report["steps"][name] = HALO_STEP_AUDITS[name]()
    return report


# --------------------------------------------------------------------------
# golden diff — pure functions (tested without any compile)
# --------------------------------------------------------------------------


def diff_golden(report: Dict[str, Any], golden: Dict[str, Any]) -> List[str]:
    """Drift lines between a (possibly partial) report and the golden.
    Steps absent from the REPORT are not compared (partial --steps
    runs); steps absent from the GOLDEN are drift."""
    drift: List[str] = []
    for key in ("version", "axes", "audit_image", "audit_batch"):
        if report.get(key) != golden.get(key):
            drift.append(f"{key}: golden {golden.get(key)!r} != "
                         f"current {report.get(key)!r}")
    for step, sec in report.get("steps", {}).items():
        gsec = golden.get("steps", {}).get(step)
        if gsec is None:
            drift.append(f"steps.{step}: not in golden (regenerate with "
                         f"--write-golden)")
            continue
        drift.extend(_diff_section(f"steps.{step}", sec, gsec))
    # declared groups: specs + replication flags must match exactly
    for name, cur in report.get("declared", {}).items():
        gold = golden.get("declared", {}).get(name)
        if gold is None:
            drift.append(f"declared.{name}: not in golden")
            continue
        for field in ("spec", "replicated", "flagged"):
            if cur.get(field) != gold.get(field):
                drift.append(
                    f"declared.{name}.{field}: golden {gold.get(field)!r} "
                    f"!= current {cur.get(field)!r}")
    for name in golden.get("declared", {}):
        if name not in report.get("declared", {}):
            drift.append(f"declared.{name}: vanished from the layout")
    return drift


def _diff_section(prefix: str, sec: Dict[str, Any],
                  gsec: Dict[str, Any]) -> List[str]:
    drift = []
    if sec.get("mesh") != gsec.get("mesh"):
        drift.append(f"{prefix}.mesh: golden {gsec.get('mesh')!r} != "
                     f"current {sec.get('mesh')!r}")
    for io in ("in", "out"):
        cur, gold = sec.get(io, {}), gsec.get(io, {})
        for group in sorted(set(cur) | set(gold)):
            c, g = cur.get(group), gold.get(group)
            if c is None:
                drift.append(f"{prefix}.{io}.{group}: vanished "
                             f"(golden specs {g['specs']})")
            elif g is None:
                drift.append(f"{prefix}.{io}.{group}: new group with "
                             f"specs {c['specs']} — regenerate the "
                             f"golden if intended")
            elif c["specs"] != g["specs"]:
                drift.append(f"{prefix}.{io}.{group}: golden specs "
                             f"{g['specs']} != current {c['specs']}")
    return drift


def flagged_groups(report: Dict[str, Any]) -> List[str]:
    """Declared groups tripping the replicated-over-threshold wire."""
    return [f"declared.{name}: {g['total_mb']} MB resolves fully "
            f"replicated (spec {g['spec']}) — shard it or pin it in "
            f"parallel.layout.REPLICATED_OK"
            for name, g in report.get("declared", {}).items()
            if g.get("flagged")]


def load_golden(path: str = GOLDEN_PATH) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def golden_hash(path: str = GOLDEN_PATH) -> str:
    """sha1 of the golden file's canonical JSON — the provenance stamp
    dryrun_multichip prints into the MULTICHIP record."""
    blob = json.dumps(load_golden(path), sort_keys=True,
                      separators=(",", ":")).encode()
    return hashlib.sha1(blob).hexdigest()


def write_golden(report: Dict[str, Any], path: str = GOLDEN_PATH) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
