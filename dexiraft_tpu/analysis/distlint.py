"""distlint — jaxlint's collective-divergence rules (JL030+).

The repo's deadliest multi-host bug class is *collective divergence*:
one host takes a branch, returns early, or swallows an exception around
a collective round, and every peer blocks in the next exchange until a
timeout fires — the mixed-mesh resume stranding peers mid-agree_step,
the 300 s zombie-flush barrier pin, and the watchdog arms leaked past
an exception were all caught by human review. Like jaxlint's JAX
footguns and threadlint's lock races, these defects are *textual*: the
"every round collective on every host" invariant can be read off the
AST. This module turns it into a gate. The runtime half (the collective
flight recorder + lockstep verifier) lives in the sibling
``collective_trace.py``.

Rule catalog (docs/static_analysis.md has the long-form version):

  JL030 divergent-collective-branch   a collective call (Coordinator
                            ``any_flag``/``min_int``/``agree_step``,
                            ``lax.psum``/``all_gather``/``ppermute``,
                            orbax's ``sync_global_processes``,
                            ``elastic_initialize``/``teardown``) under
                            a branch on host identity (process index,
                            rank, coordinator-ness, hostname) whose
                            arms do not issue MATCHING collective
                            sequences — some hosts join the exchange,
                            the rest never will.
  JL031 mid-protocol-bail   ``return``/``raise``/``continue`` between
                            collective rounds of a multi-round protocol
                            function on a LOCAL condition — one host
                            bails, peers hang in the round it skipped.
                            A bail governed by a collective verdict
                            (an ``if`` on ``any_flag(...)`` or a value
                            assigned from one) is the sanctioned shape:
                            every host bails together.
  JL032 unbounded-distributed-wait    ``.wait()``/``.join()``/
                            ``.result()``/``wait_until_finished()``
                            with no timeout on a distributed path — a
                            dead peer turns the wait into a silent
                            forever-hang no watchdog can attribute
                            (the PR 19 zombie-flush lesson,
                            generalized).
  JL033 swallowed-collective-error    a collective inside a ``try``
                            whose ``except`` swallows and continues —
                            this host's round counter silently falls
                            one behind its peers and every later
                            exchange pairs mismatched rounds.
  JL034 unreleased-armed-region   watchdog ``.arm(...)`` (or a
                            ``sanctioned()`` window) with no
                            ``finally``-path ``disarm``/``stop`` in the
                            function — an exception mid-region leaks
                            the armed contract, and the next slow-but-
                            healthy phase is executed as a stall.

Scope discipline (what keeps the rules quiet on honest code): the
collective vocabulary is a pinned name set (the LAYOUT_AXES /
LOCK_ORDER mirror idiom) — only calls that *are* this repo's
collectives participate, so single-host code never trips. JL030 runs
per-``if`` and compares the full collective sequence of both arms
(identical sequences are the sanctioned "different args, same
protocol" shape). JL031 runs only in protocol functions (two or more
collective call sites, or a collective inside a loop), never counts
``break`` (it stays inside the function, before the next round), and
exempts bails inside ``except`` handlers — failing loudly after a
broken round is the correct move, not a divergence. JL032 is
path-scoped to the distributed tier (resilience/, the distributed
backend, the multi-host checkpoint path) so single-process queue
plumbing elsewhere keeps its idioms. JL034 mirrors threadlint JL022's
function-scope check: any ``try``/``finally`` releasing the armed
receiver anywhere in the function sanctions every arm in it (the
``arm(); try: ... finally: stop()`` idiom puts the arm *outside* the
``try``).

This module is pure stdlib and is loaded BY ``jaxlint.py`` by file
path (the shardlint pattern), so the gate, the baseline allowlist, and
``# jaxlint: disable=JL03X`` suppression all work unchanged.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Sequence, Set, Tuple

RULES = {
    "JL030": "divergent-collective-branch",
    "JL031": "mid-protocol-bail",
    "JL032": "unbounded-distributed-wait",
    "JL033": "swallowed-collective-error",
    "JL034": "unreleased-armed-region",
}

#: The repo's collective vocabulary, pinned (the shardlint LAYOUT_AXES
#: idiom): a call participates in JL030/031/033 iff its terminal name
#: is here. Coordinator primitives (resilience/coord.py), the lax
#: collectives shard_map bodies issue (parallel/halo.py), orbax's
#: process barrier, and the elastic backend splice points — each is a
#: blocking rendezvous every live host must join.
_COLLECTIVE_NAMES: Set[str] = {
    # Coordinator consensus primitives (+ the raw exchange they ride)
    "any_flag", "min_int", "agree_step", "_allgather",
    # XLA collectives inside shard_map/pmap bodies
    "psum", "pmean", "pmax", "pmin", "all_gather", "ppermute",
    "all_to_all", "psum_scatter", "pshuffle",
    # orbax checkpoint barrier entry (and the test shim's name)
    "sync_global_processes", "kv_sync",
    # elastic backend splice: every surviving host re-initializes
    "elastic_initialize", "elastic_teardown",
}

#: Host-identity markers for JL030: an ``if`` test mentioning one of
#: these branches on WHO the host is, not on replicated state.
#: (``size``/``epoch`` are deliberately absent — identical on every
#: host, branching on them is lockstep.)
_IDENTITY_ATTRS: Set[str] = {
    "process_index", "process_id", "index", "rank", "host_id",
    "is_coordinator", "is_leader", "is_primary", "hostname",
}
_IDENTITY_NAMES: Set[str] = {"rank", "hostname", "is_coordinator",
                             "is_leader"}
_IDENTITY_CALLS: Set[str] = {"process_index", "process_id",
                             "gethostname"}

#: JL032's blocking-wait vocabulary: attrs whose ZERO-ARG form blocks
#: forever. Positional-arg forms (``join(sep)``, ``wait(5)``,
#: ``result(t)``) and a non-None ``timeout=`` keyword are bounded.
_WAIT_ATTRS: Set[str] = {"wait", "join", "result",
                         "wait_until_finished"}
_TIMEOUT_KWARGS: Set[str] = {"timeout", "timeout_s", "timeout_ms",
                             "timeout_secs"}

#: JL032 runs only on the distributed tier (normalized-path markers):
#: a dead PEER is what makes an unbounded wait unrecoverable, and only
#: these paths wait on peers.
_DIST_PATH_MARKERS: Tuple[str, ...] = (
    "dexiraft_tpu/resilience/",
    "dexiraft_tpu/parallel/distributed.py",
    "dexiraft_tpu/train/checkpoint.py",
    "dexiraft_tpu/analysis/collective_trace.py",
)

#: JL034's armed-region vocabulary: acquire attr -> release attrs that
#: discharge it when called on the same receiver root inside a
#: ``finally`` (``stop`` counts — it disarms and retires the monitor).
_ARM_ATTR = "arm"
_RELEASE_ATTRS: Set[str] = {"disarm", "stop"}
_WINDOW_ATTR = "sanctioned"


# --------------------------------------------------------------------------
# shared helpers
# --------------------------------------------------------------------------


def _terminal_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _is_collective(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and _terminal_name(node.func) in _COLLECTIVE_NAMES)


def _own_walk(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk that does NOT descend into nested function/class/lambda
    scopes — their protocol structure is judged on its own."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _collectives_in(node: ast.AST) -> List[ast.Call]:
    """Collective call sites under `node`, own scope only, in source
    order (line, col)."""
    calls = [n for n in _own_walk(node) if _is_collective(n)]
    calls.sort(key=lambda c: (c.lineno, c.col_offset))
    return calls


def _test_is_identity(test: ast.AST) -> Optional[str]:
    """The identity marker an ``if`` test branches on, or None."""
    for sub in ast.walk(test):
        if isinstance(sub, ast.Call):
            name = _terminal_name(sub.func)
            if name in _IDENTITY_CALLS:
                return f"{name}()"
        elif isinstance(sub, ast.Attribute):
            if sub.attr in _IDENTITY_ATTRS:
                return f".{sub.attr}"
        elif isinstance(sub, ast.Name):
            if sub.id in _IDENTITY_NAMES:
                return sub.id
    return None


def _root_name(node: ast.AST) -> Optional[str]:
    """Leftmost name of a dotted receiver (``wd`` for ``wd``,
    ``self`` for ``self.watch``)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _receiver_key(func: ast.Attribute) -> Optional[str]:
    """Receiver identity for arm/release matching: ``self.watch`` and
    ``wd`` keep their full dotted spelling so distinct carriers on the
    same object do not alias."""
    parts: List[str] = []
    node: ast.AST = func.value
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# --------------------------------------------------------------------------
# JL030 — collective under a host-identity branch
# --------------------------------------------------------------------------


def _branch_sequence(stmts: Sequence[ast.stmt]) -> List[str]:
    calls: List[ast.Call] = []
    for s in stmts:
        if _is_collective(s):
            calls.append(s)  # pragma: no cover - stmts are not Calls
        calls.extend(c for c in _own_walk(s) if _is_collective(c))
    calls.sort(key=lambda c: (c.lineno, c.col_offset))
    return [_terminal_name(c.func) or "?" for c in calls]


def _rule_jl030(linter) -> None:
    for node in ast.walk(linter.mod.tree):
        if not isinstance(node, ast.If):
            continue
        marker = _test_is_identity(node.test)
        if marker is None:
            continue
        body_seq = _branch_sequence(node.body)
        else_seq = _branch_sequence(node.orelse)
        if not body_seq and not else_seq:
            continue
        if body_seq == else_seq:
            continue  # matching-branches exemption: same protocol
        first = (_collectives_in_stmts(node.body)
                 or _collectives_in_stmts(node.orelse))[0]
        name = _terminal_name(first.func)
        linter.flag(
            "JL030", first,
            f"collective '{name}' under a host-identity branch "
            f"(test mentions '{marker}') whose arms issue different "
            f"collective sequences ({body_seq or '[]'} vs "
            f"{else_seq or '[]'}) — hosts on the other arm never join "
            f"this exchange and every peer hangs in it; hoist the "
            f"collective out of the branch or mirror the sequence in "
            f"both arms")


def _collectives_in_stmts(stmts: Sequence[ast.stmt]) -> List[ast.Call]:
    calls: List[ast.Call] = []
    for s in stmts:
        calls.extend(c for c in _own_walk(s) if _is_collective(c))
    calls.sort(key=lambda c: (c.lineno, c.col_offset))
    return calls


# --------------------------------------------------------------------------
# JL031 — early bail between collective rounds
# --------------------------------------------------------------------------


class _BailScan:
    """Walk one protocol function's statements tracking governing ifs,
    enclosing loops, and except-handler context."""

    def __init__(self, fn, verdict_names: Set[str]):
        self.fn = fn
        self.verdict_names = verdict_names
        #: (node, kind, in_collective_loop, governed, in_handler)
        self.bails: List[Tuple[ast.stmt, str, bool, bool, bool]] = []
        self._walk(fn.body, ifs=(), loop_coll=False, handler=False)

    def _test_collective(self, test: ast.AST) -> bool:
        for sub in ast.walk(test):
            if _is_collective(sub):
                return True
            if isinstance(sub, ast.Name) and sub.id in self.verdict_names:
                return True
        return False

    def _walk(self, stmts: Sequence[ast.stmt], ifs: tuple,
              loop_coll: bool, handler: bool) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.Return):
                self.bails.append((stmt, "return", loop_coll,
                                   any(ifs), handler))
            elif isinstance(stmt, ast.Raise):
                bare = stmt.exc is None  # re-raise: not a new bail
                if not bare:
                    self.bails.append((stmt, "raise", loop_coll,
                                       any(ifs), handler))
            elif isinstance(stmt, ast.Continue):
                self.bails.append((stmt, "continue", loop_coll,
                                   any(ifs), handler))
            if isinstance(stmt, ast.If):
                governed = self._test_collective(stmt.test)
                self._walk(stmt.body, ifs + (governed,), loop_coll,
                           handler)
                self._walk(stmt.orelse, ifs + (governed,), loop_coll,
                           handler)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                has_coll = bool(_collectives_in(stmt))
                self._walk(stmt.body, ifs, loop_coll or has_coll,
                           handler)
                # a loop's else runs after normal exhaustion — past the
                # rounds, not between them
                self._walk(stmt.orelse, ifs, loop_coll, handler)
                continue
            if isinstance(stmt, ast.Try):
                self._walk(stmt.body, ifs, loop_coll, handler)
                for h in stmt.handlers:
                    self._walk(h.body, ifs, loop_coll, True)
                self._walk(stmt.orelse, ifs, loop_coll, handler)
                self._walk(stmt.finalbody, ifs, loop_coll, handler)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._walk(stmt.body, ifs, loop_coll, handler)
                continue


def _rule_jl031(linter) -> None:
    for fn in ast.walk(linter.mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        calls = _collectives_in(fn)
        loops_with_coll = any(
            isinstance(n, (ast.For, ast.AsyncFor, ast.While))
            and _collectives_in(n)
            for n in _own_walk(fn))
        if len(calls) < 2 and not loops_with_coll:
            continue  # not a multi-round protocol function
        # names carrying a collective verdict: `stop = any_flag(...)`
        verdicts: Set[str] = set()
        for n in _own_walk(fn):
            if isinstance(n, ast.Assign) and any(
                    _is_collective(s) for s in ast.walk(n.value)):
                verdicts.update(t.id for t in n.targets
                                if isinstance(t, ast.Name))
        first_l = min(c.lineno for c in calls) if calls else 0
        last_l = max(c.lineno for c in calls) if calls else 0
        for node, kind, in_loop, governed, handler in \
                _BailScan(fn, verdicts).bails:
            if governed or handler:
                continue
            between = first_l < node.lineno < last_l
            if not (in_loop or between):
                continue
            where = ("inside a collective-bearing loop" if in_loop
                     else "between collective rounds")
            linter.flag(
                "JL031", node,
                f"early {kind} {where} of protocol function "
                f"'{fn.name}' on a host-local condition — this host "
                f"skips the next round and every peer hangs in it "
                f"until timeout; make the verdict collective first "
                f"(gate the bail on any_flag/min_int agreement) or "
                f"move the bail outside the protocol")


# --------------------------------------------------------------------------
# JL032 — unbounded wait on a distributed path
# --------------------------------------------------------------------------


def _on_dist_path(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(m in p for m in _DIST_PATH_MARKERS)


def _rule_jl032(linter) -> None:
    for node in ast.walk(linter.mod.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _WAIT_ATTRS):
            continue
        if node.args:
            continue  # positional timeout (join(t), wait(t), result(t))
        bounded = False
        for kw in node.keywords:
            if kw.arg in _TIMEOUT_KWARGS:
                bounded = not (isinstance(kw.value, ast.Constant)
                               and kw.value.value is None)
        if bounded:
            continue
        linter.flag(
            "JL032", node,
            f"unbounded .{node.func.attr}() on a distributed path — "
            f"a dead peer turns this into a forever-hang that no "
            f"timeout attributes (the zombie-flush class); pass a "
            f"timeout (and handle its expiry), or bound it from the "
            f"caller")


# --------------------------------------------------------------------------
# JL033 — collective inside an exception-swallowing try
# --------------------------------------------------------------------------


def _rule_jl033(linter) -> None:
    for node in ast.walk(linter.mod.tree):
        if not isinstance(node, ast.Try):
            continue
        colls = _collectives_in_stmts(node.body)
        if not colls:
            continue
        name = _terminal_name(colls[0].func)
        for h in node.handlers:
            swallows = not any(
                isinstance(x, ast.Raise)
                for s in h.body for x in ast.walk(s))
            if not swallows:
                continue
            linter.flag(
                "JL033", h,
                f"except handler swallows a failed collective "
                f"('{name}' is inside this try) and continues — this "
                f"host's round counter falls behind its peers and "
                f"every later exchange pairs mismatched rounds; "
                f"re-raise (or escalate to a reconfiguration verdict) "
                f"so the divergence is loud")


# --------------------------------------------------------------------------
# JL034 — armed region without a finally-path release
# --------------------------------------------------------------------------


def _finally_released_roots(fn) -> Set[str]:
    """Receiver keys released (disarm/stop) inside any finally block of
    the function — function-scoped, like threadlint JL022: the
    ``arm(); try: ... finally: stop()`` idiom keeps the arm OUTSIDE
    the try."""
    out: Set[str] = set()
    for n in _own_walk(fn):
        if not isinstance(n, ast.Try):
            continue
        for s in n.finalbody:
            for c in ast.walk(s):
                if (isinstance(c, ast.Call)
                        and isinstance(c.func, ast.Attribute)
                        and c.func.attr in _RELEASE_ATTRS):
                    key = _receiver_key(c.func)
                    if key is not None:
                        out.add(key)
    return out


def _with_context_names(fn) -> Set[str]:
    """Names entered as `with` contexts in the function (the
    ``win = watch.sanctioned() if fresh else nullcontext(); with win:``
    idiom)."""
    out: Set[str] = set()
    for n in _own_walk(fn):
        if isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                if isinstance(item.context_expr, ast.Name):
                    out.add(item.context_expr.id)
    return out


def _rule_jl034(linter) -> None:
    for fn in ast.walk(linter.mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        released = None  # computed lazily: most functions never arm
        with_names = None
        with_exprs = None
        for n in _own_walk(fn):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)):
                continue
            if n.func.attr == _ARM_ATTR:
                if released is None:
                    released = _finally_released_roots(fn)
                key = _receiver_key(n.func)
                if key is not None and key in released:
                    continue
                linter.flag(
                    "JL034", n,
                    f".arm() in '{fn.name}' with no finally-path "
                    f"disarm/stop on the same receiver in the "
                    f"function — an exception mid-region leaks the "
                    f"armed contract and the next slow-but-healthy "
                    f"phase is executed as a stall; release in a "
                    f"finally (arm(); try: ... finally: "
                    f"disarm()/stop())")
            elif n.func.attr == _WINDOW_ATTR:
                if with_names is None:
                    with_names = _with_context_names(fn)
                    with_exprs = {
                        id(item.context_expr)
                        for w in _own_walk(fn)
                        if isinstance(w, (ast.With, ast.AsyncWith))
                        for item in w.items}
                if id(n) in with_exprs:
                    continue  # `with watch.sanctioned():` — scoped
                if _assigned_to_with_name(fn, n, with_names):
                    continue
                linter.flag(
                    "JL034", n,
                    f"sanctioned() window opened in '{fn.name}' "
                    f"outside a `with` — an exception inside the "
                    f"window leaks the shifted compile baseline; use "
                    f"`with watch.sanctioned():` (assigning it to a "
                    f"name later entered by `with` also counts)")


def _assigned_to_with_name(fn, call: ast.Call,
                           with_names: Set[str]) -> bool:
    for n in _own_walk(fn):
        if not isinstance(n, ast.Assign):
            continue
        if any(s is call for s in ast.walk(n.value)):
            return any(isinstance(t, ast.Name) and t.id in with_names
                       for t in n.targets)
    return False


# --------------------------------------------------------------------------
# entry point
# --------------------------------------------------------------------------


def run_rules(linter) -> None:
    """Entry point jaxlint's _Linter calls; duck-typed on (mod, flag)."""
    _rule_jl030(linter)
    _rule_jl031(linter)
    if _on_dist_path(linter.mod.path):
        _rule_jl032(linter)
    _rule_jl033(linter)
    _rule_jl034(linter)
