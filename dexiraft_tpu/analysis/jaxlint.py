"""jaxlint — AST analyzer for the JAX/TPU footguns this codebase bans.

The failure class that erases throughput wins is invisible in review:
a `float()` on the wrong value silently syncs the pipeline every step, a
reused PRNG key correlates two samplers, a jit without donation doubles
HBM for the train state, a timed region without a device sync measures
dispatch instead of compute (the bench then "improves" when the model
gets slower to enqueue). These are all *textual* patterns — this module
finds them statically so `scripts/lint_gate.py` can fail the commit
instead of a benchmark failing the quarter.

Pure stdlib (ast) on purpose: the gate must run pre-pytest in ~a second
with no jax import, and `scripts/lint_gate.py` loads this file by path
so even `dexiraft_tpu/__init__` stays out of the loop.

Rule catalog (docs/static_analysis.md has the long-form version):

  JL001 tracer-host-sync   np.asarray / np.array / jax.device_get /
                           .item() / .tolist() / float(param) inside a
                           jitted function — concretizes a tracer (trace
                           error at best, silent per-call constant at
                           worst).
  JL002 key-reuse          a PRNG key variable consumed by >= 2
                           jax.random calls without an intervening
                           split/fold_in rebind, or consumed inside a
                           loop while bound outside it.
  JL003 tracer-branch      Python `if`/`while` on a jitted function's
                           (non-static) array argument — trace-time
                           concretization / a retrace per distinct value.
                           `.shape`/`.ndim`/`.dtype` and `is None`
                           checks are static and exempt.
  JL004 untimed-bench      a perf_counter()-delimited span in a bench
                           script that dispatches device work but never
                           syncs (block_until_ready / device_get /
                           scalar fetch) before reading the timer —
                           times async dispatch, not compute.
  JL005 f64-literal        an explicit float64 dtype in a jax-importing
                           file — TPUs have no f64; under the default
                           x64-disabled config this is a silent
                           downcast, under x64 a silent 2x slowdown.
  JL006 jit-no-donate      jit over a state-threading function (a
                           leading state/opt_state/carry parameter)
                           without donate_argnums — the old state stays
                           resident and doubles the step's HBM.
  JL007 implicit-fetch     float()/int()/np.asarray() directly on the
                           result of a jitted/step function — an
                           *implicit* device->host sync. Use
                           jax.device_get(...) so the sync is explicit,
                           grep-able, and transfer-guard-clean
                           (analysis.guards.strict_mode).
  JL008 loop-sync          an unconditional per-iteration host sync
                           (device_get / block_until_ready / .item())
                           inside a for/while in library train/eval/
                           serve paths — syncs belong on a cadence
                           (`if step % N == 0`), not in the loop body.
  JL009 jit-in-loop        jax.jit(...) constructed inside a loop body —
                           a fresh wrapper (and a retrace) per
                           iteration; hoist it.

Sharding-contract rules JL010+ live in the sibling `shardlint.py` and
lock-discipline rules JL020+ in `threadlint.py` (both loaded below by
file path, so the package import and lint_gate.py's path-load pick
them up): shardlint enforces that every PartitionSpec / mesh axis /
sharding pin is drawn from the canonical layout in `parallel/layout.py`
(docs/parallel.md); threadlint enforces the serve/resilience thread
fabric's lock discipline against the central lock-order registry in
`analysis/locks.py` (docs/serving.md "Threading model").

Suppression: `# jaxlint: disable=JL00X` on the offending line, or a
reviewed entry in analysis/baseline.json (see lint_gate.py). Baseline
entries match on (rule, path, stripped source line) so they survive
unrelated line-number churn but die with the code they excused.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

RULES: Dict[str, str] = {
    "JL000": "syntax-error",
    "JL001": "tracer-host-sync",
    "JL002": "key-reuse",
    "JL003": "tracer-branch",
    "JL004": "untimed-bench",
    "JL005": "f64-literal",
    "JL006": "jit-no-donate",
    "JL007": "implicit-fetch",
    "JL008": "loop-sync",
    "JL009": "jit-in-loop",
}


def _load_rule_module(filename: str, modname: str):
    """Load a sibling rule module by file path (mirrors how lint_gate.py
    loads this file): works identically whether jaxlint was imported as
    dexiraft_tpu.analysis.jaxlint or exec'd by path."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        filename)
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_shardlint = _load_rule_module("shardlint.py", "_shardlint")
_threadlint = _load_rule_module("threadlint.py", "_threadlint")
_distlint = _load_rule_module("distlint.py", "_distlint")
RULES.update(_shardlint.RULES)
RULES.update(_threadlint.RULES)
RULES.update(_distlint.RULES)

# dotted names that mean "jax.jit" after alias resolution
_JIT_NAMES = {"jax.jit", "jax.pjit", "jit", "pjit",
              "jax.experimental.pjit.pjit"}
# jax.random producers (return a key without consuming a key argument)
_KEY_PRODUCERS = {"PRNGKey", "key"}
# jax.random functions that REBIND rather than leak (their results are
# fresh keys; passing a key to them still consumes it)
_KEY_DERIVERS = {"split", "fold_in", "clone"}
# first-parameter names that mark a jitted function as state-threading
_STATE_PARAMS = {"state", "train_state", "opt_state", "carry"}
# explicit host-sync calls (the sanctioned spellings)
_SYNC_FUNCS = {"jax.block_until_ready", "jax.device_get"}
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# timer sources that open/close a JL004 span
_TIMER_FUNCS = {"time.perf_counter", "time.monotonic", "time.time"}
# engine methods whose call dispatches device work
_ENGINE_METHODS = {"stream", "run_batch"}
_MAKE_STEP_RE = re.compile(r"^make_\w*step$")
_FN_PARAM_RE = re.compile(r"^(fn|\w*_fn)$")
_DISABLE_RE = re.compile(r"#\s*jaxlint:\s*disable=([A-Z0-9,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str  # stripped source line — the baseline match key

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.snippet)

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{RULES[self.rule]}] {self.message}")

    def baseline_entry(self) -> dict:
        """Ready-to-paste analysis/baseline.json allow entry."""
        return {"rule": self.rule, "path": self.path,
                "snippet": self.snippet, "reason": "<why this is ok>"}


# --------------------------------------------------------------------------
# module context: import aliases, jitted functions, device-valued names
# --------------------------------------------------------------------------


class _Module:
    def __init__(self, tree: ast.Module, src: str, path: str):
        self.tree = tree
        self.path = path
        self.lines = src.splitlines()
        self.aliases: Dict[str, str] = {}
        self._collect_aliases(tree)
        # function name -> (FunctionDef, jitted?, donate?, static_params)
        self.defs: Dict[str, ast.AST] = {}
        self.jitted: Set[ast.AST] = set()
        self.jit_sites: List[Tuple[ast.AST, ast.AST, bool]] = []
        # names bound to device-dispatching callables / engines
        self.device_vars: Set[str] = set()
        self.engine_vars: Set[str] = set()
        self._collect_defs(tree)
        self._collect_bindings(tree)

    # -- imports -----------------------------------------------------------

    def _collect_aliases(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    root = a.name.split(".")[0]
                    self.aliases[a.asname or root] = (
                        a.name if a.asname else root)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.aliases[a.asname or a.name] = (
                        f"{node.module}.{a.name}")

    def dotted(self, node: ast.AST) -> Optional[str]:
        """Resolve a Name/Attribute chain to a dotted path via aliases."""
        if isinstance(node, ast.Name):
            return self.aliases.get(node.id, node.id)
        if isinstance(node, ast.Attribute):
            base = self.dotted(node.value)
            return f"{base}.{node.attr}" if base else None
        return None

    @property
    def imports_jax(self) -> bool:
        return any(v == "jax" or v.startswith("jax.")
                   for v in self.aliases.values())

    # -- jit discovery -----------------------------------------------------

    def _is_jit(self, node: ast.AST) -> bool:
        return self.dotted(node) in _JIT_NAMES

    def _jit_call_info(self, call: ast.Call):
        """(wrapped_fn_node_or_name, has_donate, static_argnums) for a
        Call that applies jit — either jax.jit(...) directly or
        functools.partial(jax.jit, ...)."""
        kwargs = {k.arg for k in call.keywords if k.arg}
        statics = self._static_argnums(call)
        if self._is_jit(call.func):
            wrapped = call.args[0] if call.args else None
            return wrapped, bool(kwargs & {"donate_argnums",
                                           "donate_argnames"}), statics
        if (self.dotted(call.func) in ("functools.partial", "partial")
                and call.args and self._is_jit(call.args[0])):
            return None, bool(kwargs & {"donate_argnums",
                                        "donate_argnames"}), statics
        return NotImplemented, False, ()

    @staticmethod
    def _static_argnums(call: ast.Call) -> Tuple[int, ...]:
        for k in call.keywords:
            if k.arg == "static_argnums":
                v = k.value
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    return (v.value,)
                if isinstance(v, (ast.Tuple, ast.List)):
                    return tuple(e.value for e in v.elts
                                 if isinstance(e, ast.Constant)
                                 and isinstance(e.value, int))
        return ()

    def _collect_defs(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs[node.name] = node
                for dec in node.decorator_list:
                    if self._is_jit(dec):
                        self.jitted.add(node)
                        self.jit_sites.append((dec, node, False))
                    elif isinstance(dec, ast.Call):
                        wrapped, donate, statics = self._jit_call_info(dec)
                        if wrapped is not NotImplemented:
                            self.jitted.add(node)
                            node._jl_static = statics  # type: ignore
                            self.jit_sites.append((dec, node, donate))

    def _collect_bindings(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if not targets or not isinstance(node.value, ast.Call):
                continue
            call = node.value
            callee = self.dotted(call.func)
            wrapped, donate, statics = self._jit_call_info(call)
            if wrapped is not NotImplemented:
                # x = jax.jit(f[, ...]) — x dispatches device work; f is
                # traced code
                self.device_vars.update(targets)
                if isinstance(wrapped, ast.Name) \
                        and wrapped.id in self.defs:
                    fn = self.defs[wrapped.id]
                    self.jitted.add(fn)
                    fn._jl_static = statics  # type: ignore
                    self.jit_sites.append((call, fn, donate))
                elif isinstance(wrapped, ast.Lambda):
                    self.jitted.add(wrapped)
                    self.jit_sites.append((call, wrapped, donate))
            elif callee and _MAKE_STEP_RE.match(callee.split(".")[-1]):
                self.device_vars.update(targets)
            elif callee and callee.split(".")[-1] == "InferenceEngine":
                self.engine_vars.update(targets)
        # jitted defs dispatch when called by name
        for fn in self.jitted:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.device_vars.add(fn.name)

    # -- helpers -----------------------------------------------------------

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def suppressed(self, line: int, rule: str) -> bool:
        m = _DISABLE_RE.search(self.snippet(line))
        return bool(m) and rule in m.group(1)


class _Linter:
    def __init__(self, mod: _Module, rules: Optional[Set[str]] = None):
        self.mod = mod
        self.rules = rules or set(RULES)
        self.findings: List[Finding] = []

    def flag(self, rule: str, node: ast.AST, message: str) -> None:
        if rule not in self.rules:
            return
        line = getattr(node, "lineno", 0)
        if self.mod.suppressed(line, rule):
            return
        self.findings.append(Finding(
            rule=rule, path=self.mod.path, line=line,
            col=getattr(node, "col_offset", 0), message=message,
            snippet=self.mod.snippet(line)))

    # -- entry -------------------------------------------------------------

    def run(self) -> List[Finding]:
        mod = self.mod
        self._rule_jl006()
        if mod.imports_jax:
            self._rule_jl005()
        for fn in mod.jitted:
            self._rule_jl001(fn)
            self._rule_jl003(fn)
        # sequential, scope-aware rules
        self._rule_jl002(mod.tree.body, loop_depth=0, keys={})
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._rule_jl002(node.body, loop_depth=0, keys={})
        base = os.path.basename(mod.path)
        if "bench" in base and mod.path.replace(os.sep, "/").startswith(
                "scripts/"):
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._rule_jl004(node)
        self._rule_jl007(mod.tree)
        _shardlint.run_rules(self)   # JL010+ sharding-contract rules
        _threadlint.run_rules(self)  # JL020+ lock-discipline rules
        _distlint.run_rules(self)    # JL030+ collective-divergence rules
        rel = mod.path.replace(os.sep, "/")
        if (rel.startswith(("dexiraft_tpu/train/", "dexiraft_tpu/eval/",
                            "dexiraft_tpu/serve/"))
                or rel in ("dexiraft_tpu/train_cli.py",
                           "dexiraft_tpu/eval_cli.py")):
            self._rule_jl008(mod.tree)
        self._rule_jl009(mod.tree)
        # statement flattening + nested loops can visit a node via more
        # than one ancestor — report each site once
        seen = set()
        unique = []
        for f in self.findings:
            k = (f.rule, f.line, f.col, f.message)
            if k not in seen:
                seen.add(k)
                unique.append(f)
        return unique

    # -- JL001: host sync on tracers inside jitted code --------------------

    def _rule_jl001(self, fn: ast.AST) -> None:
        params = _param_names(fn)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                callee = self.mod.dotted(node.func)
                if callee in ("numpy.asarray", "numpy.array",
                              "jax.device_get"):
                    self.flag("JL001", node,
                              f"{callee}() inside jitted code concretizes "
                              f"the tracer (host round-trip or trace error)")
                elif callee in ("float", "int", "bool") and node.args:
                    root = _root_name(node.args[0])
                    if root in params:
                        self.flag("JL001", node,
                                  f"{callee}() on traced argument "
                                  f"'{root}' inside jitted code")
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr in ("item", "tolist")
                      and not node.args):
                    self.flag("JL001", node,
                              f".{node.func.attr}() inside jitted code "
                              f"forces a host sync on a tracer")

    # -- JL002: PRNG key reuse --------------------------------------------

    def _rule_jl002(self, stmts: Sequence[ast.stmt], loop_depth: int,
                    keys: Dict[str, dict]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # fresh scope, visited separately
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._jl002_scan_expr(stmt.iter, loop_depth, keys)
                tgt_names = _target_names(stmt.target)
                iter_is_keys = self._is_key_call(stmt.iter, derive_ok=True)
                for t in tgt_names:
                    if iter_is_keys:
                        keys[t] = {"uses": 0, "depth": loop_depth + 1,
                                   "line": stmt.lineno}
                    else:
                        keys.pop(t, None)
                self._rule_jl002(stmt.body + stmt.orelse,
                                 loop_depth + 1, keys)
            elif isinstance(stmt, ast.While):
                self._jl002_scan_expr(stmt.test, loop_depth, keys)
                self._rule_jl002(stmt.body + stmt.orelse,
                                 loop_depth + 1, keys)
            elif isinstance(stmt, ast.If):
                self._jl002_scan_expr(stmt.test, loop_depth, keys)
                self._rule_jl002(stmt.body, loop_depth, keys)
                self._rule_jl002(stmt.orelse, loop_depth, keys)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._jl002_scan_expr(item.context_expr, loop_depth, keys)
                self._rule_jl002(stmt.body, loop_depth, keys)
            elif isinstance(stmt, ast.Try):
                for blk in (stmt.body, stmt.orelse, stmt.finalbody):
                    self._rule_jl002(blk, loop_depth, keys)
                for h in stmt.handlers:
                    self._rule_jl002(h.body, loop_depth, keys)
            elif isinstance(stmt, ast.Assign):
                self._jl002_scan_expr(stmt.value, loop_depth, keys)
                fresh = self._is_key_call(stmt.value, derive_ok=True)
                for t in stmt.targets:
                    for name in _target_names(t):
                        if fresh:
                            keys[name] = {"uses": 0, "depth": loop_depth,
                                          "line": stmt.lineno}
                        else:
                            keys.pop(name, None)
            else:
                self._jl002_scan_expr(stmt, loop_depth, keys)

    def _is_key_call(self, node: ast.AST, derive_ok: bool) -> bool:
        if not isinstance(node, ast.Call):
            return False
        callee = self.mod.dotted(node.func) or ""
        if not callee.startswith("jax.random."):
            return False
        fn = callee.split(".")[-1]
        return fn in _KEY_PRODUCERS or (derive_ok and fn in _KEY_DERIVERS)

    def _jl002_scan_expr(self, node: ast.AST, loop_depth: int,
                         keys: Dict[str, dict]) -> None:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            callee = self.mod.dotted(sub.func) or ""
            if not callee.startswith("jax.random."):
                continue
            fn = callee.split(".")[-1]
            if fn in _KEY_PRODUCERS:
                continue  # produces, never consumes
            args = list(sub.args) + [k.value for k in sub.keywords]
            for arg in args:
                if not isinstance(arg, ast.Name) or arg.id not in keys:
                    continue
                rec = keys[arg.id]
                rec["uses"] += 1
                if rec["uses"] == 2:
                    self.flag(
                        "JL002", sub,
                        f"PRNG key '{arg.id}' (bound line {rec['line']}) "
                        f"consumed again without split/fold_in — "
                        f"correlated randomness")
                elif rec["uses"] == 1 and loop_depth > rec["depth"]:
                    rec["uses"] = 2  # don't double-report
                    self.flag(
                        "JL002", sub,
                        f"PRNG key '{arg.id}' bound outside this loop is "
                        f"consumed every iteration — identical randomness "
                        f"per pass; split per iteration instead")

    # -- JL003: tracer-dependent Python branching --------------------------

    def _rule_jl003(self, fn: ast.AST) -> None:
        params = _param_names(fn)
        statics = getattr(fn, "_jl_static", ())
        ordered = _positional_params(fn)
        static_names = {ordered[i] for i in statics if i < len(ordered)}
        dynamic = params - static_names
        if not dynamic or not isinstance(getattr(fn, "body", None), list):
            return
        for stmt in ast.walk(fn):
            if isinstance(stmt, (ast.If, ast.While)):
                names = _dynamic_refs(stmt.test, dynamic)
                if names:
                    kind = "if" if isinstance(stmt, ast.If) else "while"
                    self.flag(
                        "JL003", stmt,
                        f"python `{kind}` on traced argument(s) "
                        f"{sorted(names)} inside jitted code — trace-time "
                        f"concretization or a retrace per value; use "
                        f"lax.cond/jnp.where, or mark the arg static")

    # -- JL004: timed bench spans without a sync ---------------------------

    def _rule_jl004(self, fn: ast.AST) -> None:
        self._jl004_block(fn.body)

    def _jl004_block(self, stmts: Sequence[ast.stmt]) -> None:
        opens: Dict[str, Tuple[int, ast.stmt]] = {}
        for i, stmt in enumerate(stmts):
            # close: any `<timer>() - t0` inside this statement
            closed = set()
            for node in ast.walk(stmt):
                if (isinstance(node, ast.BinOp)
                        and isinstance(node.op, ast.Sub)
                        and isinstance(node.right, ast.Name)
                        and node.right.id in opens
                        and isinstance(node.left, ast.Call)
                        and self.mod.dotted(node.left.func) in _TIMER_FUNCS):
                    closed.add(node.right.id)
            for name in closed:
                start_i, open_stmt = opens.pop(name)
                span = list(stmts[start_i + 1:i])
                if (span and self._dispatches_device(span)
                        and not self._has_sync(span)):
                    self.flag(
                        "JL004", open_stmt,
                        f"timed region ('{name}', closed line "
                        f"{stmt.lineno}) dispatches device work but never "
                        f"syncs before reading the timer — add "
                        f"block_until_ready/device_get (or a scalar "
                        f"device_get fetch) inside the span")
            # open: t = time.perf_counter()
            if (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Call)
                    and self.mod.dotted(stmt.value.func) in _TIMER_FUNCS):
                opens[stmt.targets[0].id] = (i, stmt)
            # recurse into nested blocks for spans local to them
            for blk in _sub_blocks(stmt):
                self._jl004_block(blk)

    def _dispatches_device(self, stmts: Iterable[ast.stmt]) -> bool:
        for stmt in stmts:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if (isinstance(f, ast.Name)
                        and f.id in self.mod.device_vars):
                    return True
                if (isinstance(f, ast.Attribute)
                        and f.attr in _ENGINE_METHODS
                        and isinstance(f.value, ast.Name)
                        and f.value.id in self.mod.engine_vars):
                    return True
        return False

    def _has_sync(self, stmts: Iterable[ast.stmt]) -> bool:
        for stmt in stmts:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                callee = self.mod.dotted(node.func)
                if callee in _SYNC_FUNCS or callee in (
                        "float", "int", "numpy.asarray", "numpy.array"):
                    return True
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _SYNC_METHODS):
                    return True
        return False

    # -- JL005: explicit float64 ------------------------------------------

    _F64 = {"numpy.float64", "jax.numpy.float64", "numpy.double"}
    _F64_STR = {"float64", "f8", "double"}

    def _rule_jl005(self) -> None:
        def is_f64(node: ast.AST) -> bool:
            d = self.mod.dotted(node)
            if d in self._F64:
                return True
            return (isinstance(node, ast.Constant)
                    and node.value in self._F64_STR)

        for node in ast.walk(self.mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if self.mod.dotted(node.func) in self._F64:
                self.flag("JL005", node,
                          "float64 constructor in a jax-importing module "
                          "— TPUs have no f64 (silent downcast under the "
                          "default config)")
                continue
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"
                    and node.args and is_f64(node.args[0])):
                self.flag("JL005", node,
                          ".astype(float64) in a jax-importing module")
                continue
            for kw in node.keywords:
                if kw.arg == "dtype" and is_f64(kw.value):
                    self.flag("JL005", node,
                              "dtype=float64 in a jax-importing module")

    # -- JL006: jit without donation on state-threading functions ----------

    def _rule_jl006(self) -> None:
        for site, fn, donate in self.mod.jit_sites:
            if donate:
                continue
            ordered = _positional_params(fn)
            leading = set(ordered[:3])
            hit = leading & _STATE_PARAMS
            if hit:
                self.flag(
                    "JL006", site,
                    f"jit over state-threading function (params "
                    f"{sorted(hit)}) without donate_argnums — the old "
                    f"state stays resident and doubles step HBM")

    # -- JL007: implicit device->host fetch --------------------------------

    def _rule_jl007(self, tree: ast.AST) -> None:
        scopes = [tree] + [n for n in ast.walk(tree)
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))]
        for scope in scopes:
            if scope in self.mod.jitted:
                continue  # JL001's domain
            device_locals = set(self.mod.device_vars)
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                device_locals |= {p for p in _param_names(scope)
                                  if _FN_PARAM_RE.match(p)}
            # names assigned from device-fn calls (incl tuple unpack)
            device_vals: Set[str] = set()
            for stmt in _own_statements(scope):
                if (isinstance(stmt, ast.Assign)
                        and isinstance(stmt.value, ast.Call)
                        and self._is_device_call(stmt.value, device_locals)):
                    for t in stmt.targets:
                        device_vals.update(_target_names(t))
            for stmt in _own_statements(scope):
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call) or not node.args:
                        continue
                    callee = self.mod.dotted(node.func)
                    if callee not in ("float", "int", "numpy.asarray",
                                      "numpy.array"):
                        continue
                    arg = node.args[0]
                    if (isinstance(arg, ast.Call)
                            and self._is_device_call(arg, device_locals)):
                        self.flag(
                            "JL007", node,
                            f"{callee}() directly on a device computation "
                            f"result — wrap in jax.device_get(...) so the "
                            f"host sync is explicit and "
                            f"transfer-guard-clean")
                        continue
                    root = _root_name(arg)
                    if root in device_vals:
                        self.flag(
                            "JL007", node,
                            f"{callee}() on '{root}' (result of a jitted/"
                            f"step call) — an implicit device->host sync; "
                            f"use jax.device_get(...)")

    def _is_device_call(self, call: ast.Call, device_locals: Set[str]) -> bool:
        f = call.func
        if isinstance(f, ast.Name) and f.id in device_locals:
            return True
        return False

    # -- JL008: unconditional in-loop sync in library paths ----------------

    def _rule_jl008(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, (ast.For, ast.While)):
                self._jl008_body(node.body)

    def _jl008_body(self, stmts: Sequence[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                continue  # cadence-gated syncs are the sanctioned shape
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(stmt):
                if isinstance(node, (ast.If, ast.FunctionDef, ast.Lambda)):
                    continue
                if not isinstance(node, ast.Call):
                    continue
                callee = self.mod.dotted(node.func)
                if callee in _SYNC_FUNCS or (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item" and not node.args):
                    self.flag(
                        "JL008", node,
                        f"unconditional per-iteration host sync "
                        f"({callee or node.func.attr}) in a library "
                        f"train/eval/serve loop — gate it on a cadence "
                        f"(`if step % N == 0`)")

    # -- JL009: jit constructed inside a loop ------------------------------

    def _rule_jl009(self, tree: ast.AST) -> None:
        for loop in ast.walk(tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for stmt in loop.body + loop.orelse:
                for node in ast.walk(stmt):
                    if (isinstance(node, ast.Call)
                            and self.mod._is_jit(node.func)):
                        self.flag(
                            "JL009", node,
                            "jax.jit(...) constructed inside a loop — a "
                            "fresh wrapper (and retrace) per iteration; "
                            "hoist it out")


# --------------------------------------------------------------------------
# AST utilities
# --------------------------------------------------------------------------


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return {n for n in names if n != "self"}


def _positional_params(fn: ast.AST) -> List[str]:
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args if p.arg != "self"]


def _target_names(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in node.elts:
            out.extend(_target_names(e))
        return out
    if isinstance(node, ast.Starred):
        return _target_names(node.value)
    return []


def _root_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, (ast.Subscript, ast.Attribute, ast.Starred)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding"}


def _dynamic_refs(test: ast.AST, params: Set[str]) -> Set[str]:
    """Names in `test` referencing params *dynamically* (value-dependent).
    `.shape`-style attribute reads and `is (not) None` checks are static
    at trace time and exempt."""
    out: Set[str] = set()

    def visit(node: ast.AST) -> None:
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return  # x.shape[...] is static
            visit(node.value)
        elif isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return  # `x is None`
            visit(node.left)
            for c in node.comparators:
                visit(c)
        elif isinstance(node, ast.Name):
            if node.id in params:
                out.add(node.id)
        else:
            for child in ast.iter_child_nodes(node):
                visit(child)

    visit(test)
    return out


def _sub_blocks(stmt: ast.stmt) -> List[List[ast.stmt]]:
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return []  # fresh scope — visited on its own
    blocks = []
    for attr in ("body", "orelse", "finalbody"):
        blk = getattr(stmt, attr, None)
        if isinstance(blk, list) and blk and isinstance(blk[0], ast.stmt):
            blocks.append(blk)
    for h in getattr(stmt, "handlers", []) or []:
        blocks.append(h.body)
    return blocks


def _own_statements(scope: ast.AST) -> List[ast.stmt]:
    """Statements of `scope` excluding nested function bodies."""
    out: List[ast.stmt] = []

    def collect(stmts: Sequence[ast.stmt]) -> None:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            out.append(s)
            for blk in _sub_blocks(s):
                collect(blk)

    collect(scope.body)
    return out


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------


def lint_source(src: str, path: str = "<string>",
                rules: Optional[Set[str]] = None) -> List[Finding]:
    """Lint one module's source text. `path` should be repo-relative with
    forward slashes — several rules scope on it."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(rule="JL000", path=path, line=e.lineno or 0,
                        col=e.offset or 0,
                        message=f"syntax error: {e.msg}", snippet="")]
    mod = _Module(tree, src, path)
    return _Linter(mod, rules).run()


def lint_file(abspath: str, relpath: str,
              rules: Optional[Set[str]] = None) -> List[Finding]:
    with open(abspath, encoding="utf-8") as f:
        return lint_source(f.read(), relpath, rules)


@dataclasses.dataclass
class Baseline:
    """analysis/baseline.json: the gate's determinism config.

    exclude — ruff-style glob list of repo-relative paths the linter
    skips entirely (archived one-off probe scripts).
    allow   — reviewed findings, matched on (rule, path, stripped source
    line); each entry carries a human `reason`. An entry that matches
    nothing is itself an error (stale excuses rot)."""

    exclude: List[str] = dataclasses.field(default_factory=list)
    allow: List[dict] = dataclasses.field(default_factory=list)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
        return cls(exclude=list(raw.get("exclude", [])),
                   allow=list(raw.get("allow", [])))

    def excludes(self, relpath: str) -> bool:
        return any(fnmatch.fnmatch(relpath, pat) for pat in self.exclude)

    def exclude_matches(self, relpath: str) -> List[str]:
        """Which exclude patterns this path satisfies (for the gate's
        stale-exclude detection: a pattern matching no file in a full
        tree walk excuses nothing and must be removed)."""
        return [p for p in self.exclude if fnmatch.fnmatch(relpath, p)]

    def split(self, findings: List[Finding]
              ) -> Tuple[List[Finding], List[Finding], List[dict]]:
        """(kept, allowlisted, stale_entries)."""
        keys = {(e.get("rule"), e.get("path"), e.get("snippet")): e
                for e in self.allow}
        used = set()
        kept, allowed = [], []
        for f in findings:
            if f.key() in keys:
                allowed.append(f)
                used.add(f.key())
            else:
                kept.append(f)
        stale = [e for k, e in keys.items() if k not in used]
        return kept, allowed, stale


def iter_py_files(root: str, subdirs: Sequence[str]) -> Iterable[Tuple[str, str]]:
    """Yield (abspath, repo-relative posix path) for every .py under the
    given subdirs of root, sorted for determinism. An entry that IS a
    .py file (the repo-root driver entry points) yields itself."""
    for sub in subdirs:
        base = os.path.join(root, sub)
        if sub.endswith(".py"):
            if os.path.isfile(base):
                yield base, sub.replace(os.sep, "/")
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                ab = os.path.join(dirpath, name)
                rel = os.path.relpath(ab, root).replace(os.sep, "/")
                yield ab, rel


DEFAULT_SUBDIRS = ("dexiraft_tpu", "scripts",
                   # repo-root driver entries: the multichip dryrun
                   # builds meshes and bench constructs step fns — both
                   # inside the sharding contract's enforcement scope
                   "__graft_entry__.py", "bench.py")


def lint_tree(root: str, subdirs: Sequence[str] = DEFAULT_SUBDIRS,
              baseline: Optional[Baseline] = None,
              rules: Optional[Set[str]] = None):
    """Lint the tree; returns (kept, allowed, stale_entries, stats)."""
    findings: List[Finding] = []
    n_files = n_excluded = 0
    matched_excludes: Set[str] = set()
    for ab, rel in iter_py_files(root, subdirs):
        if baseline is not None:
            hits = baseline.exclude_matches(rel)
            if hits:
                matched_excludes.update(hits)
                n_excluded += 1
                continue
        n_files += 1
        findings.extend(lint_file(ab, rel, rules))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    # an explicit .py scope entry naming a vanished file must FAIL the
    # gate, not silently shrink its coverage (same principle as stale
    # excludes: the gate's reach never narrows without a signal)
    missing_scope = [sub for sub in subdirs if sub.endswith(".py")
                     and not os.path.isfile(os.path.join(root, sub))]
    if baseline is None:
        return findings, [], [], {"files": n_files, "excluded": n_excluded,
                                  "stale_excludes": [],
                                  "missing_scope": missing_scope}
    kept, allowed, stale = baseline.split(findings)
    stats = {"files": n_files, "excluded": n_excluded,
             # a full tree walk saw no file for these patterns: the
             # excused code is gone, so the excuse must go too
             "stale_excludes": [p for p in baseline.exclude
                                if p not in matched_excludes],
             "missing_scope": missing_scope}
    return kept, allowed, stale, stats
