"""Static + runtime guards against the JAX/TPU footgun class.

Two halves, deliberately decoupled:

- ``jaxlint`` — pure-stdlib AST linter (no jax import) run by
  ``scripts/lint_gate.py`` as the pre-pytest CI gate. Import it by file
  path or as ``dexiraft_tpu.analysis.jaxlint``. Sharding-contract
  rules (JL010+) live in ``shardlint``; lock-discipline rules (JL020+)
  in ``threadlint`` — both pure stdlib, loaded by jaxlint by file path.
- ``guards`` — the runtime side (imports jax): ``strict_mode()`` arms
  ``jax.transfer_guard`` plus a recompile-count sentinel so steady-state
  retraces and implicit host transfers raise instead of silently
  degrading throughput; ``RecompileWatch`` is the observe-only variant
  that powers the non-strict drift warnings.
- ``locks`` — the concurrency runtime (pure stdlib): every fleet lock
  is a named, rank-carrying ``OrderedLock`` feeding a per-process
  acquisition graph, so rank inversions and ABBA deadlock cycles raise
  at the second acquisition under strict mode, with contention /
  held-span gauges on the serve tier's ``/stats`` ``locks`` block.

This ``__init__`` imports nothing so the lint gate and tests can load
``jaxlint`` without paying (or even having) the jax import.

See docs/static_analysis.md for the rule catalog and --strict
semantics, and docs/serving.md "Threading model" for the declared
lock order.
"""
