"""shardlint — jaxlint's sharding-contract rules (JL010+).

PR 5 proved the pattern: the JAX/TPU footguns that erase throughput are
textual, so a pure-stdlib AST pass can fail the commit instead of a
bench failing the quarter. This module extends that machinery from
"JAX footguns" to "sharding contracts": the canonical layout
(``parallel/layout.py``) is the single source of truth for mesh axis
names and PartitionSpecs, and these rules make that a property of the
tree rather than a convention. Spec drift, ad-hoc mesh axes, and
unpinned mesh-path jits become CI failures before they become
silently-replicated multi-hundred-MB arrays on a pod.

Rule catalog (docs/static_analysis.md has the long-form version):

  JL010 inline-spec        PartitionSpec / NamedSharding constructed
                           outside parallel/layout.py — every spec must
                           be drawn from the frozen SpecLayout, or the
                           shard audit's golden can no longer account
                           for it.
  JL011 adhoc-mesh-axis    a Mesh (or mesh_utils/jax.make_mesh)
                           constructed outside parallel/layout.py, or a
                           mesh-axis-name STRING literal ('data' /
                           'fsdp' / 'seq') passed to a sharding or
                           collective API — axis names come from the
                           layout's constants, never re-spelled.
  JL012 raw-spec-constraint with_sharding_constraint called with an
                           inline spec literal — constraints must name
                           a layout spec so the audit can diff them.
  JL013 unpinned-mesh-jit  inside a mesh-parameterized step builder, a
                           jit over a state/variables-threading fn
                           without BOTH in_shardings and out_shardings
                           (the `if mesh is None` single-chip branch is
                           the one sanctioned unpinned form) — an
                           unpinned mesh-path jit lets GSPMD infer
                           layouts the golden never sees.

This module is pure stdlib and is loaded BY ``jaxlint.py`` (by file
path, like lint_gate loads jaxlint itself): jaxlint merges RULES and
calls :func:`run_rules` from its linter, so the gate, the baseline
allowlist, and ``# jaxlint: disable=JL01X`` suppression all work
unchanged for these rules.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

RULES = {
    "JL010": "inline-spec",
    "JL011": "adhoc-mesh-axis",
    "JL012": "raw-spec-constraint",
    "JL013": "unpinned-mesh-jit",
}

#: The one module allowed to construct sharding objects.
LAYOUT_PATH = "dexiraft_tpu/parallel/layout.py"

#: Mirror of SpecLayout's axis names (parallel/layout.py). shardlint
#: must stay jax-free, so the names are pinned here and a test asserts
#: they equal the live layout's axes (tests/test_zzzshardlayout.py).
LAYOUT_AXES = frozenset({"data", "fsdp", "seq"})

# dotted names (post alias-resolution) that construct specs / meshes
_SPEC_CTORS = {
    "jax.sharding.PartitionSpec", "PartitionSpec",
    "jax.sharding.NamedSharding", "NamedSharding",
}
_MESH_CTORS = {
    "jax.sharding.Mesh", "Mesh", "jax.make_mesh",
    "jax.experimental.mesh_utils.create_device_mesh",
    "mesh_utils.create_device_mesh",
}
_CONSTRAINT_FNS = {
    "jax.lax.with_sharding_constraint", "with_sharding_constraint",
    "jax.experimental.pjit.with_sharding_constraint",
}
# collective/sharding APIs whose string args are axis names (JL011's
# second half); matched on the resolved dotted name OR the final attr
_AXIS_API_ATTRS = {
    "axis_index", "ppermute", "psum", "pmean", "pmax", "pmin",
    "all_gather", "all_to_all", "axis_size", "pshuffle",
}
_AXIS_KEYWORDS = {"axis", "axis_name", "axis_names", "mesh_axes"}
# leading-parameter names that mark a jitted fn as threading sharded
# state through a mesh-parameterized builder (superset of jaxlint's
# _STATE_PARAMS: eval/serve steps thread `variables`)
_STATE_LIKE = {"state", "train_state", "opt_state", "carry",
               "variables", "params"}


def _is_layout(path: str) -> bool:
    return path.replace("\\", "/") == LAYOUT_PATH


def _spec_ctor(linter, node: ast.AST) -> Optional[str]:
    """Resolved spec-constructor name if `node` is a PartitionSpec /
    NamedSharding call, else None."""
    if not isinstance(node, ast.Call):
        return None
    callee = linter.mod.dotted(node.func)
    return callee if callee in _SPEC_CTORS else None


# --------------------------------------------------------------------------
# JL010 / JL011 / JL012 — whole-module scans
# --------------------------------------------------------------------------


def _rule_jl010(linter) -> None:
    if _is_layout(linter.mod.path):
        return
    for node in ast.walk(linter.mod.tree):
        callee = _spec_ctor(linter, node)
        if callee:
            linter.flag(
                "JL010", node,
                f"{callee.split('.')[-1]}(...) constructed outside "
                f"{LAYOUT_PATH} — draw the spec from the frozen "
                f"SpecLayout (parallel.layout.LAYOUT) so the shard "
                f"audit's golden accounts for it")


def _rule_jl011(linter) -> None:
    if _is_layout(linter.mod.path):
        return
    for node in ast.walk(linter.mod.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = linter.mod.dotted(node.func) or ""
        if callee in _MESH_CTORS:
            linter.flag(
                "JL011", node,
                f"{callee.split('.')[-1]}(...) constructed outside "
                f"{LAYOUT_PATH} — mesh construction belongs to the "
                f"layout (make_mesh/make_mesh_2d/make_serve_mesh/"
                f"make_train_mesh)")
            continue
        # axis-name string literal fed to a sharding/collective API.
        # _SPEC_CTORS are deliberately NOT in this set: an inline
        # PartitionSpec('data') is ONE defect and JL010 already owns
        # it — double-flagging would demand two suppressions per line
        is_axis_api = (
            callee in _MESH_CTORS
            or callee in ("jax.shard_map", "shard_map",
                          "jax.experimental.shard_map.shard_map")
            or (isinstance(node.func, ast.Attribute)
                and node.func.attr in _AXIS_API_ATTRS)
            or callee.split(".")[-1] in _AXIS_API_ATTRS)
        for arg in node.args:
            if is_axis_api:
                _flag_axis_strings(linter, arg)
        for kw in node.keywords:
            if is_axis_api or kw.arg in _AXIS_KEYWORDS:
                _flag_axis_strings(linter, kw.value)


def _flag_axis_strings(linter, node: ast.AST) -> None:
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Constant) and isinstance(sub.value, str)
                and sub.value in LAYOUT_AXES):
            linter.flag(
                "JL011", sub,
                f"mesh-axis name {sub.value!r} spelled as a string "
                f"literal — use the layout's constants "
                f"(parallel.layout.LAYOUT.{sub.value}_axis / "
                f"DATA_AXIS/SEQ_AXIS/FSDP_AXIS)")


def _rule_jl012(linter) -> None:
    for node in ast.walk(linter.mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if linter.mod.dotted(node.func) not in _CONSTRAINT_FNS:
            continue
        raw_args = list(node.args[1:]) + [k.value for k in node.keywords]
        for arg in raw_args:
            for sub in ast.walk(arg):
                if _spec_ctor(linter, sub):
                    linter.flag(
                        "JL012", node,
                        "with_sharding_constraint with an inline spec "
                        "literal — name a layout spec "
                        "(parallel.layout.LAYOUT / named(mesh, ...)) "
                        "so the constraint participates in the audit "
                        "golden")
                    break


# --------------------------------------------------------------------------
# JL013 — unpinned jit on the mesh path of a step builder
# --------------------------------------------------------------------------


def _mesh_none_spans(fn: ast.AST) -> List[Tuple[int, int]]:
    """Line spans of `if mesh is None:` bodies inside fn — the one
    sanctioned place for an unpinned state-threading jit."""
    spans = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.If):
            continue
        t = node.test
        if (isinstance(t, ast.Compare) and isinstance(t.left, ast.Name)
                and t.left.id == "mesh" and len(t.ops) == 1
                and isinstance(t.ops[0], ast.Is)
                and len(t.comparators) == 1
                and isinstance(t.comparators[0], ast.Constant)
                and t.comparators[0].value is None):
            start = node.body[0].lineno
            end = max(getattr(s, "end_lineno", s.lineno)
                      for s in node.body)
            spans.append((start, end))
    return spans


def _jit_wrapped_leading_param(local_defs, call: ast.Call) -> Optional[str]:
    """Leading positional param name of the fn a jit call wraps, resolved
    against the ENCLOSING builder's own defs (module-level resolution
    would collide: every builder names its inner fn `step`)."""
    if not call.args:
        return None
    wrapped = call.args[0]
    fn = None
    if isinstance(wrapped, ast.Name):
        fn = local_defs.get(wrapped.id)
    elif isinstance(wrapped, ast.Lambda):
        fn = wrapped
    if fn is None:
        return None
    a = fn.args
    ordered = [p.arg for p in a.posonlyargs + a.args if p.arg != "self"]
    return ordered[0] if ordered else None


def _rule_jl013(linter) -> None:
    for fn in ast.walk(linter.mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        a = fn.args
        param_names = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
        if "mesh" not in param_names:
            continue
        exempt = _mesh_none_spans(fn)
        local_defs = {d.name: d for d in ast.walk(fn)
                      if isinstance(d, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if not linter.mod._is_jit(node.func):
                continue
            leading = _jit_wrapped_leading_param(local_defs, node)
            if leading not in _STATE_LIKE:
                continue
            kwargs = {k.arg for k in node.keywords if k.arg}
            if {"in_shardings", "out_shardings"} <= kwargs:
                continue
            if any(s <= node.lineno <= e for s, e in exempt):
                continue  # the single-chip branch
            missing = sorted({"in_shardings", "out_shardings"} - kwargs)
            linter.flag(
                "JL013", node,
                f"jit over state-threading fn (leading param "
                f"{leading!r}) in a mesh-parameterized builder without "
                f"{'/'.join(missing)} — pin the layout's shardings on "
                f"the mesh path (unpinned jit is only sanctioned "
                f"inside `if mesh is None`)")


def run_rules(linter) -> None:
    """Entry point jaxlint's _Linter calls; duck-typed on (mod, flag)."""
    _rule_jl010(linter)
    _rule_jl011(linter)
    _rule_jl012(linter)
    _rule_jl013(linter)
