"""Runtime guards: the dynamic half of the jaxlint story.

jaxlint (the static half) catches the footguns visible in source text;
this module catches the two that only exist at run time:

- **steady-state recompiles** — a shape/dtype drift after warmup silently
  retraces the step and erases the throughput the benches measured. The
  process-wide `compile_count()` counter (fed by jax.monitoring's
  ``/jax/core/compile/backend_compile_duration`` event — one firing per
  backend compile, cache hits excluded) makes "compile count must stay
  flat after warmup" an assertable property.
- **implicit host<->device transfers** — a ``float()``/``np.asarray()``
  on the wrong value syncs the pipeline every step.
  ``jax.transfer_guard("disallow")`` turns those into errors while the
  sanctioned explicit spellings (``jax.device_put``/``jax.device_get``)
  pass.

``strict_mode()`` arms both and RAISES on violation — wired behind
``--strict`` in train_cli/eval_cli and always-on for the steady-state
window of serve_bench/train_bench. ``RecompileWatch`` observes without
raising — it powers the one-line drift warning non-strict runs emit.

Monitoring listeners cannot be unregistered (jax.monitoring has no
per-listener removal), so ONE module-level listener is installed lazily
on first use and only ever increments a counter; entering/leaving
strict_mode snapshots it.
"""

from __future__ import annotations

import contextlib
import sys
from typing import Iterator, Optional

import jax

from dexiraft_tpu.analysis.locks import OrderedLock

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_lock = OrderedLock("analysis.guards.listener")
_installed = False
_count = 0


class RecompileBudgetExceeded(RuntimeError):
    """Raised when a strict_mode region compiles past its pinned budget."""


def _listener(event: str, durations: float, **_kw) -> None:
    global _count
    if event == _COMPILE_EVENT:
        _count += 1


def _ensure_listener() -> None:
    global _installed
    with _lock:
        if not _installed:
            jax.monitoring.register_event_duration_secs_listener(_listener)
            _installed = True


def compile_count() -> int:
    """Backend compiles observed in this process so far (monotone).

    Counts actual XLA backend compiles — executable-cache hits and
    persistent-cache deserializations do not fire the event twice for
    the same executable, so a flat count across a window means XLA
    re-used executables for every dispatch in it.
    """
    _ensure_listener()
    return _count


class RecompileWatch:
    """Observe-only recompile sentinel for non-strict runs.

    Usage::

        watch = RecompileWatch("train")
        ... warmup (compiles expected) ...
        watch.mark_warm()
        ... steady state ...
        watch.warn_if_drifted()   # one line on stderr, once, if any
                                  # post-warmup compile happened

    ``mark_warm()`` may be called repeatedly (e.g. once per new bucket
    the caller *expects* to compile); drift is measured from the last
    call.
    """

    def __init__(self, label: str = "run", budget: int = 0):
        self.label = label
        self.budget = budget
        _ensure_listener()
        self._warm_at: Optional[int] = None
        self._warned = False
        # open sanctioned() windows (possibly on OTHER threads): the
        # compile counter is process-global, so a check() racing an
        # in-progress expected compile would read it as drift before
        # the window's exit shifts the baseline
        self._slock = OrderedLock("analysis.guards.watch")
        self._sanctioned_depth = 0
        self._win_base = 0   # compile_count at the 0->1 depth transition

    def mark_warm(self) -> None:
        # read AND write under the window lock: engines call this from
        # dispatcher and handler threads, and a count read before the
        # lock can go stale against a concurrent sanctioned() exit's
        # re-baseline — writing the stale count would re-expose the
        # window's own compiles as drift. watch -> listener (via
        # compile_count) is the declared LOCK_ORDER direction.
        with self._slock:
            self._warm_at = compile_count()

    @property
    def drift(self) -> int:
        """Compiles since mark_warm() (0 before it is called)."""
        if self._warm_at is None:
            return 0
        return compile_count() - self._warm_at

    def check(self, budget: Optional[int] = None) -> None:
        """Raise :class:`RecompileBudgetExceeded` when drift exceeds the
        budget (defaults to the watch's own). The strict-mode teeth; the
        observe-only path uses :meth:`warn_if_drifted` instead."""
        budget = self.budget if budget is None else budget
        with self._slock:
            if self._sanctioned_depth > 0:
                # a sanctioned window is open (engines share one watch
                # across threads: a cold streaming bucket compiling in
                # a handler thread must not fail the pair dispatcher's
                # concurrent check, and vice versa) — its exit shifts
                # the baseline past its compiles; the next check has
                # teeth again
                return
            # read drift under the same lock as the depth check: a
            # window opening (or exiting) in between would hand us a
            # count that includes its sanctioned compiles
            d = self.drift
        if d > budget:
            raise RecompileBudgetExceeded(
                f"[guards] {self.label}: {d} backend compile(s) "
                f"in a strict region with budget {budget} — steady state "
                f"retraced (shape/dtype drift). Enable jax.log_compiles() "
                f"to see what; docs/static_analysis.md has the playbook")

    @contextlib.contextmanager
    def sanctioned(self) -> Iterator[None]:
        """Absorb the compiles of a sanctioned window — the compile-side
        twin of ``jax.transfer_guard("allow")`` around planned host I/O.

        The baseline shifts by exactly the window's compile count, so
        drift observed OUTSIDE the window still counts: a checkpoint
        save's one-time per-shape device copies (the fsdp per-shard
        snapshot) pass, a train-step retrace before or after does not.
        No-op before ``mark_warm()``. Thread-aware: while any window is
        open, concurrent :meth:`check`/:meth:`warn_if_drifted` calls
        (the other engine's dispatch on its own thread) defer rather
        than read the in-progress expected compile as drift.
        OVERLAPPING windows (both engines compiling fresh buckets at
        once) merge into one span: the baseline snapshots at the 0->1
        depth transition and shifts once at 1->0, so a compile landing
        inside two open windows is absorbed once, not twice (a double
        shift would drive drift negative and silently extend the
        blind spot past the windows' exit).

        Known blind spot, accepted: the compile counter is
        process-GLOBAL, so another thread's genuine drift landing inside
        an open window is absorbed with it (``mark_warm()`` has the same
        property — it baselines past everything). Attribution would need
        per-thread counts the jax.monitoring listener does not expose;
        windows are short (cold-bucket compiles), and steady-state drift
        recurs, so the next post-window check catches a real leak."""
        with self._slock:
            if self._sanctioned_depth == 0:
                self._win_base = compile_count()
            self._sanctioned_depth += 1
        try:
            yield
        finally:
            with self._slock:
                self._sanctioned_depth -= 1
                if self._sanctioned_depth == 0 and self._warm_at is not None:
                    now = compile_count()
                    # the min-cap keeps a mark_warm() issued while the
                    # window was open from compounding with the shift:
                    # the baseline may land ON the current count, never
                    # past it (negative drift would mask real retraces)
                    self._warm_at = min(self._warm_at
                                        + (now - self._win_base), now)

    def warn_if_drifted(self, file=None) -> bool:
        """One-line, once-only warning when post-warmup compiles exist.

        Returns True if drift was (ever) reported — callers embedding
        this in a loop get the cadence for free.
        """
        report = False
        with self._slock:
            if self._sanctioned_depth > 0:
                return self._warned
            # drift is read INSIDE the lock, after the depth check: a
            # sanctioned window exiting between an early read and the
            # check would leave a stale pre-rebaseline count here — a
            # bogus warning that latches _warned and silences every
            # future real one. (watch -> listener nesting via
            # compile_count() is the declared LOCK_ORDER direction.)
            d = self.drift
            if d > 0 and not self._warned:
                # claim the once-only slot under the lock (two engine
                # threads drifting together must not both print); the
                # print itself happens after release — I/O under a lock
                # is the JL023 shape this module now lints against
                self._warned = True
                report = True
        if report:
            print(f"[guards] {self.label}: {d} recompile(s) after warmup "
                  f"— shape/dtype drift is erasing throughput; rerun "
                  f"with --strict to fail fast (docs/static_analysis.md)",
                  file=file or sys.stderr)
        return self._warned


@contextlib.contextmanager
def strict_mode(compile_budget: int = 0,
                transfer: str = "disallow",
                label: str = "strict") -> Iterator[RecompileWatch]:
    """Arm transfer_guard + the recompile sentinel for a region.

    Inside the region:
      - implicit host<->device transfers raise immediately (jax's own
        transfer_guard error names the offending aval); explicit
        ``jax.device_put``/``jax.device_get`` still pass,
      - backend compiles are counted; leaving the region (or calling
        ``check()`` on the yielded watch) raises
        :class:`RecompileBudgetExceeded` if more than ``compile_budget``
        happened.

    ``compile_budget=0`` is the steady-state contract: run warmup
    *before* entering. A warmup-inclusive region should pass its known
    compile count (e.g. one per serve bucket).

    ``transfer`` is any jax transfer-guard level ("allow", "log",
    "disallow"); "log" is the diagnose-without-failing mode.

    The yielded object is a :class:`RecompileWatch` pre-marked at entry,
    so ``watch.drift`` is live inside the region, ``watch.check()`` can
    assert mid-region (e.g. per bench rep), and ``watch.mark_warm()``
    can absorb an *expected* compile (a planned new bucket) without
    widening the budget for the unplanned ones.
    """
    watch = RecompileWatch(label, budget=compile_budget)
    watch.mark_warm()
    with jax.transfer_guard(transfer):
        yield watch
    watch.check()
