"""Profiling / tracing (SURVEY.md §5 — absent in the reference, where the
only timing is DexiNed's per-image time.time() deltas, main.py:133-147).

Two tools:
  * trace(log_dir): context manager around jax.profiler for a window of
    steps — inspect with TensorBoard's profile plugin or Perfetto.
  * StepTimer: wall-clock per-step timing with warmup exclusion; the
    train Logger separately reports steps/sec and iters/sec (the
    north-star throughput metric).
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a device+host profiler trace into log_dir."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Wall-clock step timing; ignores the first `warmup` laps (compile)."""

    def __init__(self, warmup: int = 1):
        self.warmup = warmup
        self.times: list = []
        self._t: Optional[float] = None
        self._laps = 0

    def __enter__(self):
        self._t = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t
        self._laps += 1
        if self._laps > self.warmup:
            self.times.append(dt)
        return False

    @property
    def mean(self) -> float:
        return sum(self.times) / len(self.times) if self.times else 0.0

    def summary(self) -> str:
        if not self.times:
            return "no timed laps"
        lo, hi = min(self.times), max(self.times)
        return (f"{len(self.times)} laps: mean {self.mean * 1e3:.2f} ms "
                f"(min {lo * 1e3:.2f}, max {hi * 1e3:.2f})")


def annotate(name: str):
    """Named region for profile traces (shows up in the trace viewer)."""
    return jax.profiler.TraceAnnotation(name)
