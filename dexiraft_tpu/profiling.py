"""Profiling / tracing (SURVEY.md §5 — absent in the reference, where the
only timing is DexiNed's per-image time.time() deltas, main.py:133-147).

Tools:
  * trace(log_dir): context manager around jax.profiler for a window of
    steps — inspect with TensorBoard's profile plugin or Perfetto.
  * StepTimer: wall-clock per-step timing with warmup exclusion; the
    train Logger separately reports steps/sec and iters/sec (the
    north-star throughput metric).
  * enable_persistent_cache(dir): persistent XLA compilation cache —
    repeat launches of the same program skip the multi-minute compile.
  * ThroughputReport: steps/s, pixel-iters/s (the tokens/s analog for
    this workload), and MFU from counted FLOPs — the record format
    scripts/train_bench.py emits per config.
  * ServeStats: dispatch/fetch/in-flight accounting for the serving
    engine (dexiraft_tpu.serve) — the record scripts/serve_bench.py
    emits per config.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Iterator, Optional

# jax is imported inside the three functions that touch it: this module
# sits on the serve package's import path, and the serve CLI's parser /
# --workers pool parent must stay jax-free (seconds of import on a TPU
# host for a process that never runs the model)

# default persistent-cache location (train_cli --compile_cache,
# scripts/train_bench.py); relative to the process CWD like logs/
DEFAULT_CACHE_DIR = os.path.join("logs", "xla_cache")


def enable_persistent_cache(cache_dir: Optional[str] = None) -> str:
    """Turn on XLA's persistent compilation cache at cache_dir.

    Every fresh process pays full XLA compile time for the train step
    (multi-minute at production geometry); with the cache, the second
    and later launches deserialize the compiled executable from disk in
    seconds. The thresholds are zeroed so even sub-second compiles cache
    — this repo's jitted steps are exactly the artifacts worth keeping.
    Safe to call more than once; returns the directory used.
    """
    import jax

    cache_dir = cache_dir or DEFAULT_CACHE_DIR
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    return cache_dir


class ThroughputReport:
    """Training-throughput record: steps/s, pixel-iters/s, MFU.

    pixel-iters/s = batch * H * W * iters * steps/s — the tokens/s
    analog for iterative-refinement optical flow (each refinement
    iteration touches every pixel once, like a decode step touches
    every position). MFU = counted_flops / step_time / chip_peak, with
    both inputs named in the record (docs/perf.md "MFU accounting").
    """

    def __init__(self, *, batch: int, height: int, width: int, iters: int):
        self.batch = batch
        self.height = height
        self.width = width
        self.iters = iters

    def fields(self, step_s: float, flops: Optional[int] = None,
               peak_flops: Optional[float] = None) -> dict:
        out = {
            "step_ms": round(step_s * 1e3, 2),
            "steps_per_sec": round(1.0 / step_s, 3),
            "pixel_iters_per_sec": round(
                self.batch * self.height * self.width * self.iters / step_s),
        }
        if flops:
            out["step_flops"] = int(flops)
            out["tflops_per_sec"] = round(flops / step_s / 1e12, 2)
            if peak_flops:
                out["mfu"] = round(flops / step_s / peak_flops, 4)
                out["chip_peak_bf16_flops"] = int(peak_flops)
        return out


class ServeStats:
    """Honest dispatch/fetch accounting for the throughput-mode inference
    engine (dexiraft_tpu.serve.InferenceEngine).

    The engine's dispatch is asynchronous: eval_fn() enqueues device work
    and returns array FUTURES; the only host-blocking operation is the
    np.asarray fetch when a ticket leaves the in-flight window. So:

      * fetch_s       — wall time the host spent BLOCKED inside fetches
                        (device compute the in-flight window failed to
                        hide; the serving analog of prefetch_stall)
      * dispatch_s    — host-side pad/stack/put/enqueue time (never
                        blocks on device compute)
      * batch_latency — per-batch dispatch→fetch-complete wall time;
                        p50/p99 come from these samples
      * peak_inflight — max dispatched-unfetched batches observed
      * pad_frames    — tail filler items (dispatched for shape
                        stability, masked out of results)

    The latency sample window is BOUNDED (maxlen, default 4096 batches):
    a long-lived server accumulating every batch latency forever would
    grow without bound between /stats scrapes, and percentiles over the
    recent window are what an SLO dashboard wants anyway.
    """

    def __init__(self, maxlen: int = 4096) -> None:
        self.maxlen = maxlen
        self.reset()

    def reset(self) -> None:
        import collections

        self.batches = 0
        self.frames = 0          # real frame pairs yielded
        self.pad_frames = 0      # partial-batch tail filler (masked out)
        self.dispatch_s = 0.0
        self.fetch_s = 0.0
        self.fetches = 0
        self.peak_inflight = 0
        # warm-start carry transfer accounting (the PR 6 round-trip the
        # device-resident handoff removes): H2D = host flow_init rows
        # ridden up with a dispatch, D2H = flow_low bytes fetched to
        # host for the carry. Both stay 0 on the device-carry path —
        # scripts/video_bench.py pins the before/after.
        self.carry_h2d_bytes = 0
        self.carry_d2h_bytes = 0
        self.batch_latency_s: "collections.deque" = collections.deque(
            maxlen=self.maxlen)
        # adaptive-iteration accounting (engine adaptive mode): per-ITEM
        # samples of how many refinement updates each real (non-pad)
        # frame pair actually applied, and its last pre-freeze flow-delta
        # norm — the convergence evidence the /stats adaptive block and
        # the serve_bench frontier record serialize. Bounded like the
        # latency window, and 0-length on fixed-iteration engines.
        self.iters_used: "collections.deque" = collections.deque(
            maxlen=self.maxlen)
        self.final_delta: "collections.deque" = collections.deque(
            maxlen=self.maxlen)

    def latency_ms(self, p: float) -> float:
        import numpy as np

        if not self.batch_latency_s:
            return 0.0
        return float(np.percentile(self.batch_latency_s, p)) * 1e3

    def iters_used_pctl(self, p: float) -> float:
        import numpy as np

        if not self.iters_used:
            return 0.0
        return float(np.percentile(self.iters_used, p))

    def iters_used_mean(self) -> float:
        if not self.iters_used:
            return 0.0
        return sum(self.iters_used) / len(self.iters_used)

    def final_delta_pctl(self, p: float) -> float:
        import numpy as np

        if not self.final_delta:
            return 0.0
        return float(np.percentile(self.final_delta, p))

    def summary(self) -> str:
        return (f"{self.batches} batches / {self.frames} frame pairs "
                f"(+{self.pad_frames} tail pad), peak in-flight "
                f"{self.peak_inflight}, fetch-blocked "
                f"{self.fetch_s * 1e3:.1f} ms total, batch latency "
                f"p50 {self.latency_ms(50):.1f} / "
                f"p99 {self.latency_ms(99):.1f} ms")


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a device+host profiler trace into log_dir."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Wall-clock step timing; ignores the first `warmup` laps (compile)."""

    def __init__(self, warmup: int = 1):
        self.warmup = warmup
        self.times: list = []
        self._t: Optional[float] = None
        self._laps = 0

    def __enter__(self):
        self._t = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t
        self._laps += 1
        if self._laps > self.warmup:
            self.times.append(dt)
        return False

    @property
    def mean(self) -> float:
        return sum(self.times) / len(self.times) if self.times else 0.0

    def summary(self) -> str:
        if not self.times:
            return "no timed laps"
        lo, hi = min(self.times), max(self.times)
        return (f"{len(self.times)} laps: mean {self.mean * 1e3:.2f} ms "
                f"(min {lo * 1e3:.2f}, max {hi * 1e3:.2f})")


def annotate(name: str):
    """Named region for profile traces (shows up in the trace viewer)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
