"""Package dispatcher: python -m dexiraft_tpu
{train,eval,serve,router,dexined,viz} ..."""

import sys


def main() -> None:
    cmds = ("train", "eval", "serve", "router", "dexined", "viz")
    if len(sys.argv) < 2 or sys.argv[1] not in cmds:
        print(f"usage: python -m dexiraft_tpu {{{','.join(cmds)}}} [args...]",
              file=sys.stderr)
        raise SystemExit(2)
    cmd, argv = sys.argv[1], sys.argv[2:]
    if cmd == "train":
        from dexiraft_tpu.train_cli import main as run
    elif cmd == "eval":
        from dexiraft_tpu.eval_cli import main as run
    elif cmd == "serve":
        from dexiraft_tpu.serve_cli import main as run
    elif cmd == "router":
        from dexiraft_tpu.router_cli import main as run
    elif cmd == "viz":
        from dexiraft_tpu.viz_cli import main as run
    else:
        from dexiraft_tpu.dexined_cli import main as run
    run(argv)


if __name__ == "__main__":
    main()
