"""Package dispatcher: python -m dexiraft_tpu {train,eval,dexined} ..."""

import sys


def main() -> None:
    if len(sys.argv) < 2 or sys.argv[1] not in ("train", "eval", "dexined"):
        print("usage: python -m dexiraft_tpu {train,eval,dexined} [args...]",
              file=sys.stderr)
        raise SystemExit(2)
    cmd, argv = sys.argv[1], sys.argv[2:]
    if cmd == "train":
        from dexiraft_tpu.train_cli import main as run
    elif cmd == "eval":
        from dexiraft_tpu.eval_cli import main as run
    else:
        from dexiraft_tpu.dexined_cli import main as run
    run(argv)


if __name__ == "__main__":
    main()
