"""dexiraft_tpu — a TPU-native optical-flow framework.

A ground-up JAX/XLA/Pallas/pjit re-design with the capabilities of the
Dexi-RAFT reference (RAFT optical flow fused with DexiNed edge detection):
all-pairs 4D correlation volumes, iterative ConvGRU refinement, dual
image/edge streams, the full Chairs->Things->Sintel->KITTI curriculum,
and data-parallel scaling over TPU device meshes.

Layout (bottom-up, mirroring the reference's layer map, SURVEY.md §1):
  ops/       pure-function building blocks (sampling, correlation, upsample, losses)
  models/    flax modules (encoders, update blocks, DexiNed, RAFT variants)
  data/      host-side dataset pipeline (flow file I/O, augmentors, curriculum)
  parallel/  device meshes, sharding rules, collective helpers
  train/     jitted train step, optimizer/schedule, checkpointing, logging
  evaluation/ validators and benchmark-submission writers
  utils/     padding, flow visualization, warm-start interpolation

All arrays are NHWC (channel-last), the natural TPU layout.
"""

__version__ = "0.1.0"
