"""Edge-detection evaluation: ODS / OIS / AP.

The reference reports these for DexiNed on BIPED (core/DexiNed/README.md,
BASELINE.md) but computes them with an external MATLAB/BSDS toolbox; here
they are first-class, with the toolbox's matching rule implemented
exactly:

  * ``matching="assignment"`` (default) — the BSDS correspondPixels
    protocol. A predicted edge pixel is a true positive iff it is paired
    with a distinct ground-truth edge pixel within ``tolerance * diag``
    (Euclidean); the pairing is ONE-TO-ONE, so a cluster of predictions
    around a single GT pixel yields one TP, not many. correspondPixels
    solves a min-cost assignment with an outlier cost, whose matched
    COUNT (the only thing entering P/R) equals the maximum-cardinality
    bipartite matching on the within-tolerance graph — computed here
    with a KD-tree neighborhood graph + Hopcroft-Karp. Verified against
    a brute-force assignment in tests/test_edge_metrics.py.
  * ``matching="dilation"`` — the fast morphological surrogate (a pred
    pixel counts if ANY GT pixel is within tolerance, and symmetrically
    for recall). Upper-bounds the assignment scores; the bias is
    quantified in docs/parity.md. Use for quick in-training validation.

As in the toolbox, predictions should be thinned/NMS'd beforehand (the
reference pipeline applies NMS before evaluation, DexiNed README:123-140);
thick unthinned boundaries lower assignment-precision by construction.

  ODS: best F-measure over thresholds with ONE dataset-wide threshold
  OIS: mean of each image's best F-measure
  AP:  area under the dataset precision-recall curve
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

DEFAULT_THRESHOLDS = np.linspace(0.01, 0.99, 33)


def _dilate(mask: np.ndarray, radius: int) -> np.ndarray:
    if radius <= 0:
        return mask
    import cv2

    kernel = cv2.getStructuringElement(cv2.MORPH_ELLIPSE,
                                       (2 * radius + 1, 2 * radius + 1))
    return cv2.dilate(mask.astype(np.uint8), kernel).astype(bool)


def _tolerance_radius(shape: Sequence[int], frac: float = 0.0075) -> float:
    """BSDS maxDist: fraction of the image diagonal (Euclidean, float)."""
    return max(1.0, frac * float(np.hypot(shape[0], shape[1])))


def match_count(pred_mask: np.ndarray, gt_mask: np.ndarray,
                radius: float, gt_tree=None) -> int:
    """Maximum number of one-to-one (pred pixel, GT pixel) pairs within
    Euclidean ``radius`` — the correspondPixels matched count.

    Maximum-cardinality matching via Hopcroft-Karp on the KD-tree
    neighborhood graph; exact, and sparse enough to scale to real edge
    maps (edges only between pixels closer than a few px). ``gt_tree``
    lets a threshold sweep reuse one (n_gt, cKDTree) build per image."""
    from scipy.sparse import csr_matrix
    from scipy.sparse.csgraph import maximum_bipartite_matching
    from scipy.spatial import cKDTree

    pred_pts = np.argwhere(pred_mask)
    if gt_tree is None:
        gt_pts = np.argwhere(gt_mask)
        gt_tree = cKDTree(gt_pts) if len(gt_pts) else None
    n_gt = gt_tree.n if gt_tree is not None else 0
    if len(pred_pts) == 0 or n_gt == 0:
        return 0
    pairs = cKDTree(pred_pts).query_ball_tree(gt_tree, r=radius)
    indptr = np.zeros(len(pred_pts) + 1, np.int64)
    indptr[1:] = np.cumsum([len(p) for p in pairs])
    indices = np.fromiter((j for p in pairs for j in p), np.int64,
                          count=indptr[-1])
    graph = csr_matrix(
        (np.ones(len(indices), np.uint8), indices, indptr),
        shape=(len(pred_pts), n_gt))
    match = maximum_bipartite_matching(graph, perm_type="column")
    return int((match >= 0).sum())


def edge_counts(pred: np.ndarray, gt: np.ndarray,
                thresholds: np.ndarray = DEFAULT_THRESHOLDS,
                tolerance: float = 0.0075,
                matching: str = "assignment") -> np.ndarray:
    """Per-threshold match counts for one image.

    pred: (H, W) probabilities in [0, 1]; gt: (H, W) binary edge map.
    Returns (T, 4) int64 columns [tp, n_pred, matched_gt, n_gt].
    """
    if matching not in ("assignment", "dilation"):
        raise ValueError(f"unknown matching {matching!r}; "
                         "expected 'assignment' or 'dilation'")
    pred = np.asarray(pred, np.float32)
    gt = np.asarray(gt) > 0.5
    r = _tolerance_radius(pred.shape, tolerance)
    n_gt = int(gt.sum())
    if matching == "dilation":
        gt_dil = _dilate(gt, int(round(r)))
    else:
        # GT is loop-invariant across the threshold sweep: one tree build
        from scipy.spatial import cKDTree

        gt_tree = cKDTree(np.argwhere(gt)) if n_gt else None

    out = np.zeros((len(thresholds), 4), np.int64)
    for i, t in enumerate(thresholds):
        p = pred >= t
        n_pred = int(p.sum())
        if matching == "assignment":
            # one-to-one: matched pred count == matched GT count
            tp = matched_gt = match_count(p, gt, r, gt_tree=gt_tree)
        else:
            tp = int((p & gt_dil).sum())  # predictions near a GT edge
            p_dil = _dilate(p, int(round(r)))
            matched_gt = int((gt & p_dil).sum())  # GT edges found
        out[i] = (tp, n_pred, matched_gt, n_gt)
    return out


def _prf(tp: float, n_pred: float, matched: float, n_gt: float
         ) -> Tuple[float, float, float]:
    precision = tp / n_pred if n_pred else 0.0
    recall = matched / n_gt if n_gt else 0.0
    f = (2 * precision * recall / (precision + recall)
         if precision + recall else 0.0)
    return precision, recall, f


def evaluate_edges(preds: Sequence[np.ndarray], gts: Sequence[np.ndarray],
                   thresholds: np.ndarray = DEFAULT_THRESHOLDS,
                   tolerance: float = 0.0075,
                   matching: str = "assignment") -> Dict[str, float]:
    """ODS / OIS / AP over a dataset of (probability map, binary GT)."""
    return evaluate_from_counts(
        [edge_counts(p, g, thresholds, tolerance, matching)
         for p, g in zip(preds, gts)],
        thresholds)


def evaluate_from_counts(per_image: Sequence[np.ndarray],
                         thresholds: np.ndarray = DEFAULT_THRESHOLDS
                         ) -> Dict[str, float]:
    """Score from per-image (T, 4) count matrices (edge_counts) — lets a
    streaming caller hold O(T) state per image instead of full maps."""
    totals = np.sum(per_image, axis=0)  # (T, 4)

    # ODS: one threshold for the whole dataset
    dataset_f = [_prf(*totals[i])[2] for i in range(len(thresholds))]
    ods = float(np.max(dataset_f))

    # OIS: per-image best threshold
    ois_scores = [max(_prf(*c[i])[2] for i in range(len(thresholds)))
                  for c in per_image]
    ois = float(np.mean(ois_scores)) if ois_scores else 0.0

    # AP: area under the dataset PR curve (recall-sorted trapezoid,
    # anchored at recall 0 with the lowest-recall precision so a
    # single-point curve still integrates)
    pr = np.array([_prf(*totals[i])[:2] for i in range(len(thresholds))])
    order = np.argsort(pr[:, 1])
    recall_sorted = np.concatenate([[0.0], pr[order, 1]])
    precision_sorted = np.concatenate([[pr[order[0], 0]], pr[order, 0]])
    trapezoid = getattr(np, "trapezoid", None) or np.trapz  # numpy<2
    ap = float(trapezoid(precision_sorted, recall_sorted))

    return {"ODS": ods, "OIS": ois, "AP": ap}
