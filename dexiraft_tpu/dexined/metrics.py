"""Edge-detection evaluation: ODS / OIS / AP.

The reference reports these for DexiNed on BIPED (core/DexiNed/README.md,
BASELINE.md) but computes them with an external MATLAB/BSDS toolbox; here
they are first-class. Matching uses the standard distance-tolerant
protocol in its morphological approximation: a predicted edge pixel is a
true positive if a ground-truth edge lies within `tolerance` pixels
(dilated-mask matching), and symmetrically for recall — the common fast
surrogate for the BSDS correspondPixels bipartite assignment (documented
divergence: scores trend a few tenths of a point higher).

  ODS: best F-measure over thresholds with ONE dataset-wide threshold
  OIS: mean of each image's best F-measure
  AP:  area under the dataset precision-recall curve
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

DEFAULT_THRESHOLDS = np.linspace(0.01, 0.99, 33)


def _dilate(mask: np.ndarray, radius: int) -> np.ndarray:
    if radius <= 0:
        return mask
    import cv2

    kernel = cv2.getStructuringElement(cv2.MORPH_ELLIPSE,
                                       (2 * radius + 1, 2 * radius + 1))
    return cv2.dilate(mask.astype(np.uint8), kernel).astype(bool)


def _tolerance_radius(shape: Sequence[int], frac: float = 0.0075) -> int:
    """BSDS maxDist: fraction of the image diagonal."""
    diag = float(np.hypot(shape[0], shape[1]))
    return max(1, int(round(frac * diag)))


def edge_counts(pred: np.ndarray, gt: np.ndarray,
                thresholds: np.ndarray = DEFAULT_THRESHOLDS,
                tolerance: float = 0.0075) -> np.ndarray:
    """Per-threshold match counts for one image.

    pred: (H, W) probabilities in [0, 1]; gt: (H, W) binary edge map.
    Returns (T, 4) int64 columns [tp, n_pred, matched_gt, n_gt].
    """
    pred = np.asarray(pred, np.float32)
    gt = np.asarray(gt) > 0.5
    r = _tolerance_radius(pred.shape, tolerance)
    gt_dil = _dilate(gt, r)
    n_gt = int(gt.sum())

    out = np.zeros((len(thresholds), 4), np.int64)
    for i, t in enumerate(thresholds):
        p = pred >= t
        n_pred = int(p.sum())
        tp = int((p & gt_dil).sum())  # predictions near a GT edge
        p_dil = _dilate(p, r)
        matched_gt = int((gt & p_dil).sum())  # GT edges found
        out[i] = (tp, n_pred, matched_gt, n_gt)
    return out


def _prf(tp: float, n_pred: float, matched: float, n_gt: float
         ) -> Tuple[float, float, float]:
    precision = tp / n_pred if n_pred else 0.0
    recall = matched / n_gt if n_gt else 0.0
    f = (2 * precision * recall / (precision + recall)
         if precision + recall else 0.0)
    return precision, recall, f


def evaluate_edges(preds: Sequence[np.ndarray], gts: Sequence[np.ndarray],
                   thresholds: np.ndarray = DEFAULT_THRESHOLDS,
                   tolerance: float = 0.0075) -> Dict[str, float]:
    """ODS / OIS / AP over a dataset of (probability map, binary GT)."""
    return evaluate_from_counts(
        [edge_counts(p, g, thresholds, tolerance)
         for p, g in zip(preds, gts)],
        thresholds)


def evaluate_from_counts(per_image: Sequence[np.ndarray],
                         thresholds: np.ndarray = DEFAULT_THRESHOLDS
                         ) -> Dict[str, float]:
    """Score from per-image (T, 4) count matrices (edge_counts) — lets a
    streaming caller hold O(T) state per image instead of full maps."""
    totals = np.sum(per_image, axis=0)  # (T, 4)

    # ODS: one threshold for the whole dataset
    dataset_f = [_prf(*totals[i])[2] for i in range(len(thresholds))]
    ods = float(np.max(dataset_f))

    # OIS: per-image best threshold
    ois_scores = [max(_prf(*c[i])[2] for i in range(len(thresholds)))
                  for c in per_image]
    ois = float(np.mean(ois_scores)) if ois_scores else 0.0

    # AP: area under the dataset PR curve (recall-sorted trapezoid,
    # anchored at recall 0 with the lowest-recall precision so a
    # single-point curve still integrates)
    pr = np.array([_prf(*totals[i])[:2] for i in range(len(thresholds))])
    order = np.argsort(pr[:, 1])
    recall_sorted = np.concatenate([[0.0], pr[order, 1]])
    precision_sorted = np.concatenate([[pr[order[0], 0]], pr[order, 0]])
    trapezoid = getattr(np, "trapezoid", None) or np.trapz  # numpy<2
    ap = float(trapezoid(precision_sorted, recall_sorted))

    return {"ODS": ods, "OIS": ois, "AP": ap}
