"""DexiNed standalone edge-detection workload (reference core/DexiNed/):
losses, BIPED-family datasets, and the train/test CLI driver."""

from dexiraft_tpu.dexined.losses import (
    bdcn_loss2,
    bdcn_loss_ori,
    cats_loss,
    hed_loss2,
    rcf_loss,
    weighted_multiscale_loss,
)

__all__ = [
    "bdcn_loss2",
    "bdcn_loss_ori",
    "hed_loss2",
    "rcf_loss",
    "cats_loss",
    "weighted_multiscale_loss",
]
