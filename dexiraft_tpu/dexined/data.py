"""Edge-detection datasets (reference core/DexiNed/datasets.py).

A registry of the benchmark datasets the reference supports (:9-149) plus
numpy dataset objects:

  BipedDataset  — training pairs (BGR image - mean, edge-map label with
                  the >0.2 += 0.5 ground-truth boost, 50% random 256-crop
                  then resize to the train size; datasets.py:288-433)
  TestDataset   — eval images resized to /16-divisible shapes
                  (datasets.py:254-259), original shape kept for unpadding

Samples are HWC float32 BGR (the DexiNed convention — cv2 imread order,
mean-BGR subtracted); labels are (H, W, 1) in [0, 1].
"""

from __future__ import annotations

import os
import os.path as osp
from dataclasses import dataclass
from glob import glob
from typing import Dict, List, Optional, Tuple

import numpy as np

IMAGENET_MEAN_BGR = (103.939, 116.779, 123.68)


@dataclass(frozen=True)
class EdgeDatasetInfo:
    img_height: int
    img_width: int
    mean_bgr: Tuple[float, ...] = IMAGENET_MEAN_BGR
    train_list: Optional[str] = None
    test_list: Optional[str] = None
    data_dir: str = ""


# the 9 datasets of the reference registry (datasets.py:9-149); sizes are
# the /16-divisible eval resolutions it uses
DATASET_INFO: Dict[str, EdgeDatasetInfo] = {
    "BIPED": EdgeDatasetInfo(720, 1280, data_dir="BIPED/edges"),
    "BSDS": EdgeDatasetInfo(512, 512, train_list="train_pair.lst",
                            test_list="test_pair.lst", data_dir="BSDS"),
    "BSDS300": EdgeDatasetInfo(512, 512, test_list="test_pair.lst",
                               data_dir="BSDS300"),
    "CID": EdgeDatasetInfo(512, 512, test_list="test_pair.lst", data_dir="CID"),
    "MDBD": EdgeDatasetInfo(720, 1280, train_list="train_pair.lst",
                            test_list="test_pair.lst", data_dir="MDBD"),
    "NYUD": EdgeDatasetInfo(448, 560, test_list="test_pair.lst", data_dir="NYUD"),
    "PASCAL": EdgeDatasetInfo(416, 512, test_list="test_pair.lst",
                              data_dir="PASCAL"),
    "DCD": EdgeDatasetInfo(352, 480, test_list="test_pair.lst", data_dir="DCD"),
    "CLASSIC": EdgeDatasetInfo(512, 512, data_dir="data"),
}

DATASET_NAMES = sorted(DATASET_INFO)


def _read_bgr(path: str) -> np.ndarray:
    import cv2

    img = cv2.imread(path, cv2.IMREAD_COLOR)
    if img is None:
        raise FileNotFoundError(path)
    return img.astype(np.float32)


def _read_gray(path: str) -> np.ndarray:
    import cv2

    img = cv2.imread(path, cv2.IMREAD_GRAYSCALE)
    if img is None:
        raise FileNotFoundError(path)
    return img.astype(np.float32)


class BipedDataset:
    """Training pairs for BIPED-style trees:
    <root>/imgs/train/rgbr/aug/<seq>/*.jpg with labels under
    <root>/edge_maps/train/rgbr/aug/<seq>/*.png; list-file datasets
    (BSDS/MDBD) pass train_list with '<img> <gt>' lines."""

    def __init__(self, data_root: str, img_size: int = 352,
                 mean_bgr=IMAGENET_MEAN_BGR, train_list: Optional[str] = None,
                 crop_size: int = 256):
        self.img_size = img_size
        self.mean_bgr = np.asarray(mean_bgr, np.float32)
        self.crop_size = crop_size
        self.pairs: List[Tuple[str, str]] = []
        if train_list:
            with open(osp.join(data_root, train_list)) as f:
                for line in f:
                    if line.strip():
                        img, gt = line.split()[:2]
                        self.pairs.append((osp.join(data_root, img),
                                           osp.join(data_root, gt)))
        else:
            images_path = osp.join(data_root, "imgs", "train", "rgbr", "aug")
            labels_path = osp.join(data_root, "edge_maps", "train", "rgbr", "aug")
            for d in sorted(os.listdir(images_path)):
                for f in sorted(os.listdir(osp.join(images_path, d))):
                    stem = osp.splitext(f)[0]
                    self.pairs.append(
                        (osp.join(images_path, d, f),
                         osp.join(labels_path, d, stem + ".png")))
        if not self.pairs:
            raise FileNotFoundError(f"no training pairs under {data_root}")

    def __len__(self) -> int:
        return len(self.pairs)

    def sample(self, index: int, rng: Optional[np.random.Generator] = None
               ) -> Dict[str, np.ndarray]:
        import cv2

        rng = rng or np.random.default_rng()
        img_path, gt_path = self.pairs[index % len(self.pairs)]
        img = _read_bgr(img_path) - self.mean_bgr
        gt = _read_gray(gt_path) / 255.0

        size = self.img_size
        if rng.random() >= 0.5:  # 50%: random crop then upscale
            h, w = gt.shape[:2]
            c = self.crop_size
            i = rng.integers(0, max(h - c, 1))
            j = rng.integers(0, max(w - c, 1))
            img = img[i:i + c, j:j + c]
            gt = gt[i:i + c, j:j + c]
        img = cv2.resize(img, (size, size))
        gt = cv2.resize(gt, (size, size))

        # ground-truth boost: weak annotations count as edges
        # (datasets.py:419)
        gt = np.where(gt > 0.2, gt + 0.5, gt)
        gt = np.clip(gt, 0.0, 1.0)
        return {"images": img.astype(np.float32),
                "labels": gt[..., None].astype(np.float32)}

    __getitem__ = sample


class TestDataset:
    """Eval images resized to /16-divisible shapes; original size kept so
    predictions can be restored (datasets.py:152-285)."""

    def __init__(self, data_root: str, img_height: Optional[int] = None,
                 img_width: Optional[int] = None, mean_bgr=IMAGENET_MEAN_BGR,
                 test_list: Optional[str] = None):
        self.mean_bgr = np.asarray(mean_bgr, np.float32)
        self.img_height = img_height
        self.img_width = img_width
        if test_list:
            with open(osp.join(data_root, test_list)) as f:
                self.files = [osp.join(data_root, line.split()[0])
                              for line in f if line.strip()]
        else:
            exts = ("*.jpg", "*.png", "*.jpeg", "*.JPG")
            self.files = sorted(sum((glob(osp.join(data_root, e)) for e in exts),
                                    []))
        if not self.files:
            raise FileNotFoundError(f"no test images under {data_root}")

    def __len__(self) -> int:
        return len(self.files)

    def sample(self, index: int, rng=None) -> Dict[str, np.ndarray]:
        import cv2

        path = self.files[index]
        img = _read_bgr(path)
        shape = img.shape[:2]
        if self.img_height and self.img_width:
            h, w = self.img_height, self.img_width
        else:
            h = (shape[0] // 16) * 16
            w = (shape[1] // 16) * 16
        img = cv2.resize(img, (w, h)) - self.mean_bgr
        return {"images": img.astype(np.float32),
                "file_name": osp.basename(path),
                "image_shape": shape}

    __getitem__ = sample
