"""Edge-detection losses (reference core/DexiNed/losses.py), in JAX.

All take NHWC logits (B, H, W, 1) and targets in [0, 1] (same layout) and
return a scalar; class balancing statistics are computed over the whole
batch tensor, matching the torch versions — except bdcn_loss_ori, which
balances per sample like the reference's bdcn_lossORI. The RCF convention
reserves target==2 for don't-care pixels.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

_EPS = 1e-10


def _bce_logits_sum(logits: jax.Array, targets: jax.Array,
                    weights: jax.Array) -> jax.Array:
    """Weighted BCE in LOGITS space: softplus(x) - t*x.

    Exactly -t*log(p) - (1-t)*log(1-p) for p = sigmoid(x), but stable
    at saturation. The clipped-probability form NaN'd in fp32: the
    upper clip bound 1 - 1e-10 rounds to exactly 1.0 (fp32 eps ~1.2e-7),
    so a saturated-positive logit at a positive pixel produced
    (1-t)*log(1-p) = 0 * (-inf) = NaN — observed live at step ~316 of
    the CPU DexiNed demo. The torch reference survives the same regime
    because F.binary_cross_entropy clamps its logs at -100 internally."""
    ce = jax.nn.softplus(logits) - targets * logits
    return jnp.sum(weights * ce)


def bdcn_loss2(logits: jax.Array, targets: jax.Array,
               l_weight: float = 1.1) -> jax.Array:
    """Class-balanced BCE, BDCN/RCF weighting (losses.py:22-35):
    positives (t > 0) weighted num_neg/total, negatives 1.1*num_pos/total.

    The torch version first casts targets.long(), truncating every
    sub-1.0 annotation to 0 — only exactly-1.0 pixels are positives and
    the BCE target itself is the binarized map; floor() reproduces that.
    """
    t = jnp.floor(targets.astype(jnp.float32))
    pos = (t > 0.0).astype(jnp.float32)
    num_pos = jnp.sum(pos)
    num_neg = jnp.sum((t <= 0.0).astype(jnp.float32))
    total = num_pos + num_neg
    w = jnp.where(pos > 0, num_neg / total, 1.1 * num_pos / total)
    return l_weight * _bce_logits_sum(logits, t, w)


def hed_loss2(logits: jax.Array, targets: jax.Array,
              l_weight: float = 1.1) -> jax.Array:
    """HED variant: positive threshold at 0.1 (losses.py:6-19); same
    targets.long() binarization as bdcn_loss2."""
    t = jnp.floor(targets.astype(jnp.float32))
    pos = (t > 0.1).astype(jnp.float32)
    num_pos = jnp.sum(pos)
    num_neg = jnp.sum((t <= 0.0).astype(jnp.float32))
    total = num_pos + num_neg
    w = jnp.where(pos > 0, num_neg / total, 1.1 * num_pos / total)
    return l_weight * _bce_logits_sum(logits, t, w)


def bdcn_loss_ori(logits: jax.Array, targets: jax.Array,
                  l_weight: float = 1.1) -> jax.Array:
    """Original BDCN loss (losses.py:37-58 ``bdcn_lossORI``): class
    balancing PER SAMPLE instead of over the batch — for image i,
    exactly-1 pixels weigh num_neg_i/valid_i, exactly-0 pixels
    1.1*num_pos_i/valid_i, everything else weight 0 (the torch version's
    weights array starts as zeros and only those two masks are filled)."""
    t = targets.astype(jnp.float32)
    pos = (t == 1.0).astype(jnp.float32)
    neg = (t == 0.0).astype(jnp.float32)
    axes = tuple(range(1, t.ndim))  # per-sample statistics
    num_pos = jnp.sum(pos, axis=axes, keepdims=True)
    num_neg = jnp.sum(neg, axis=axes, keepdims=True)
    valid = jnp.maximum(num_pos + num_neg, 1.0)
    w = pos * (num_neg / valid) + neg * (1.1 * num_pos / valid)
    return l_weight * _bce_logits_sum(logits, t, w)


def rcf_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """RCF: strict positives (t > 0.5), zeros negative, t == 2 ignored
    (losses.py:60-74); targets.long()-binarized like the torch version."""
    t = jnp.floor(targets.astype(jnp.float32))
    pos = (t > 0.5) & (t < 1.5)
    neg = t == 0.0
    num_pos = jnp.sum(pos.astype(jnp.float32))
    num_neg = jnp.sum(neg.astype(jnp.float32))
    total = num_pos + num_neg
    w = jnp.where(pos, num_neg / total,
                  jnp.where(neg, 1.1 * num_pos / total, 0.0))
    return _bce_logits_sum(logits, jnp.where(pos, 1.0, 0.0), w)


def _box_sum(x: jax.Array, radius: int) -> jax.Array:
    """Sum over a (2r+1)^2 window, SAME padding — NHWC ones-kernel conv
    (replaces F.conv2d(filt=ones); conv, not reduce_window, for clean
    reverse-mode on every backend)."""
    k = 2 * radius + 1
    kernel = jnp.ones((k, k, 1, 1), x.dtype)
    return jax.lax.conv_general_dilated(
        x, kernel, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def bdrloss(prediction: jax.Array, label: jax.Array, radius: int) -> jax.Array:
    """Boundary tracing loss (losses.py:77-104). prediction: probabilities."""
    bdr_pred = prediction * label
    pred_bdr_sum = label * _box_sum(bdr_pred, radius)

    texture_mask = _box_sum(label, radius)
    mask = ((texture_mask != 0.0) & (label != 1.0)).astype(jnp.float32)
    pred_texture_sum = _box_sum(prediction * (1.0 - label) * mask, radius)

    softmax_map = jnp.clip(
        pred_bdr_sum / (pred_texture_sum + pred_bdr_sum + _EPS),
        _EPS, 1.0 - _EPS)
    cost = jnp.where(label == 0.0, 0.0, -label * jnp.log(softmax_map))
    return jnp.sum(cost)


def textureloss(prediction: jax.Array, label: jax.Array,
                mask_radius: int) -> jax.Array:
    """Texture suppression loss (losses.py:107-127). prediction: probs."""
    pred_sums = _box_sum(prediction, 1)
    label_sums = _box_sum(label, mask_radius)
    mask = (label_sums == 0.0).astype(jnp.float32)
    loss = -jnp.log(jnp.clip(1.0 - pred_sums / 9.0, _EPS, 1.0 - _EPS))
    return jnp.sum(loss * mask)


def cats_loss(logits: jax.Array, targets: jax.Array,
              l_weight: Tuple[float, float] = (0.0, 0.0)) -> jax.Array:
    """CATS: balanced BCE + boundary tracing + texture suppression
    (losses.py:130-150). l_weight = (texture_factor, boundary_factor)."""
    tex_factor, bdr_factor = l_weight
    balanced_w = 1.1
    t = targets.astype(jnp.float32)
    num_pos = jnp.sum((t == 1.0).astype(jnp.float32))
    num_neg = jnp.sum((t == 0.0).astype(jnp.float32))
    beta = num_neg / (num_pos + num_neg + _EPS)
    mask = jnp.where(t == 1.0, beta,
                     jnp.where(t == 0.0, balanced_w * (1.0 - beta), 0.0))
    prediction = jax.nn.sigmoid(logits)
    cost = _bce_logits_sum(logits, t, mask)

    label_w = (t != 0.0).astype(jnp.float32)
    return (cost
            + bdr_factor * bdrloss(prediction, label_w, radius=4)
            + tex_factor * textureloss(prediction, label_w, mask_radius=4))


# per-scale weights for the 7 DexiNed outputs (main.py:29)
BDCN_SCALE_WEIGHTS = (0.7, 0.7, 1.1, 1.1, 0.3, 0.3, 1.3)


def weighted_multiscale_loss(preds: Sequence[jax.Array], targets: jax.Array,
                             weights: Sequence[float] = BDCN_SCALE_WEIGHTS,
                             loss_fn=bdcn_loss2) -> jax.Array:
    """sum_i loss_fn(preds[i], targets, w_i) (main.py:39)."""
    return sum(loss_fn(p, targets, w) for p, w in zip(preds, weights))
