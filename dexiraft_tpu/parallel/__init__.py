"""Device meshes, sharding rules, and collective helpers.

The reference's entire parallelism story is single-process
torch.nn.DataParallel (train.py:139, SURVEY.md §2.7). The TPU-native
equivalent is declarative: build a jax.sharding.Mesh over the chips,
shard the batch over the layout's data axis, and let the SPMD
partitioner insert the gradient all-reduce over ICI. Parameters and
optimizer state replicate by default, or live SHARDED over the live
``fsdp`` axis (``make_train_mesh(batch, fsdp=...)`` — storage
sharding with per-shard checkpoints; the train step gathers at entry,
docs/parallel.md).

``parallel.layout`` is the single source of truth: the frozen
:class:`SpecLayout` owns every mesh axis name and canonical
PartitionSpec (docs/parallel.md), enforced statically by the jaxlint
sharding rules (JL010+) and dynamically by ``analysis/shardaudit.py``'s
golden diff. ``parallel.mesh`` remains as the compat import path.
"""

from dexiraft_tpu.parallel.layout import (
    DATA_AXIS,
    LAYOUT,
    SpecLayout,
    batch_sharding,
    gather_state,
    make_mesh,
    replicated_sharding,
    shard_batch,
    shard_state,
    state_sharding,
)

__all__ = [
    "DATA_AXIS",
    "LAYOUT",
    "SpecLayout",
    "batch_sharding",
    "gather_state",
    "make_mesh",
    "replicated_sharding",
    "shard_batch",
    "shard_state",
    "state_sharding",
]
