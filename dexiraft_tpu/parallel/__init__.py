"""Device meshes, sharding rules, and collective helpers.

The reference's entire parallelism story is single-process
torch.nn.DataParallel (train.py:139, SURVEY.md §2.7). The TPU-native
equivalent is declarative: build a jax.sharding.Mesh over the chips,
shard the batch over the layout's data axis, replicate parameters, and
let the SPMD partitioner insert the gradient all-reduce over ICI.

``parallel.layout`` is the single source of truth: the frozen
:class:`SpecLayout` owns every mesh axis name and canonical
PartitionSpec (docs/parallel.md), enforced statically by the jaxlint
sharding rules (JL010+) and dynamically by ``analysis/shardaudit.py``'s
golden diff. ``parallel.mesh`` remains as the compat import path.
"""

from dexiraft_tpu.parallel.layout import (
    DATA_AXIS,
    LAYOUT,
    SpecLayout,
    batch_sharding,
    make_mesh,
    replicated_sharding,
    shard_batch,
)

__all__ = [
    "DATA_AXIS",
    "LAYOUT",
    "SpecLayout",
    "batch_sharding",
    "make_mesh",
    "replicated_sharding",
    "shard_batch",
]
