"""Device meshes, sharding rules, and collective helpers.

The reference's entire parallelism story is single-process
torch.nn.DataParallel (train.py:139, SURVEY.md §2.7). The TPU-native
equivalent is declarative: build a jax.sharding.Mesh over the chips,
shard the batch over the 'data' axis, replicate parameters, and let the
SPMD partitioner insert the gradient all-reduce over ICI.
"""

from dexiraft_tpu.parallel.mesh import (
    DATA_AXIS,
    batch_sharding,
    make_mesh,
    replicated_sharding,
    shard_batch,
)

__all__ = [
    "DATA_AXIS",
    "batch_sharding",
    "make_mesh",
    "replicated_sharding",
    "shard_batch",
]
