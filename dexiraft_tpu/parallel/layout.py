"""Canonical sharding layout — the single source of truth for GSPMD.

Every mesh axis name, every ``PartitionSpec``, and every
``Mesh``/``NamedSharding`` construction in this codebase lives HERE and
only here. The static gate enforces it: jaxlint rules JL010+ (see
``analysis/shardlint.py``) fail the commit on any inline spec literal,
ad-hoc mesh-axis string, or unpinned mesh-path jit outside this module,
and ``analysis/shardaudit.py`` diffs the compiled train/eval/serve
steps' resolved shardings against ``analysis/layout_golden.json`` so
spec drift is a CI failure, not a pod-debugging session.

Why one frozen layout object: the sharding story grew organically
(mesh.py helpers, per-CLI glue, context.py shard_map specs) and the
ROADMAP's pod-scale item is blocked on collapsing it — the SNIPPETS.md
exemplar ("8-chip v4 to 6000-chip v5p without changing application
code") is a frozen ``SpecLayout`` dataclass exactly like this one.
Application code asks the layout for *meaning* ("the batch's sharding
on this mesh"), never spells axes.

Axes (``SpecLayout``):

  data  — batch data-parallelism. Every mesh has it; gradients
          all-reduce over it (the SPMD partitioner inserts the psum).
  seq   — context parallelism: image rows (and with them the quadratic
          correlation volume's query axis) shard over it on 2-D train
          meshes (parallel/context.py has the math).
  fsdp  — parameter/optimizer-state sharding (LIVE since the fsdp PR).
          ``make_train_mesh(batch, fsdp=...)`` grows the axis over the
          devices left after data takes its largest batch divisor;
          ``params(mesh)``/``opt_state(mesh)`` resolve to the fsdp spec
          on such meshes, with the per-leaf divisibility fallback
          decided HERE (``param_leaf_spec``) — small leaves (biases,
          norm params, scalars) and leaves with no dividing dim stay
          replicated, and call sites never decide.

fsdp is a STORAGE axis by default: the fence-mode train step gathers
the state to replicated at entry and re-shards at exit (train/step.py's
fence pattern — see docs/perf.md "Sharded state (fsdp)" for why the
GSPMD partitioner must never see fsdp-sharded tensors inside the model:
feature-dim-partitioned convolutions miscompile under this backend's
GSPMD, pinned by tests/test_zzzfsdp.py). The halo compute-sharding mode
(parallel/halo.py, ``make_train_step(compute_sharding="halo")``) keeps
fsdp sharded DURING compute too — per-block all-gather inside a
shard_map body, where GSPMD never sees the gathered tensors — and
shards the spatial compute itself over 'seq' with explicit ppermute
halo exchange (:meth:`SpecLayout.batch_spatial_compute`,
:func:`seq_halo_perms`). The persistent HBM win — params + Adam
moments at ~1/N per device between steps, and per-shard checkpoint I/O
— is exactly what the ``state_bytes_per_device`` bench metric records.

The compat surface ``parallel/mesh.py`` re-exports everything below, so
existing imports keep working; new code should import from here.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import numpy as np

# jax import kept function-local where possible is NOT viable here: the
# module's whole job is constructing jax.sharding objects. Callers that
# must stay jax-free (data/__init__, loaders) already import lazily.
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec


@dataclasses.dataclass(frozen=True)
class SpecLayout:
    """Frozen mesh-axis names + canonical PartitionSpecs.

    Methods return ``PartitionSpec``s (mesh-independent); pair them with
    a mesh via :func:`named`. Specs that depend on the mesh's rank
    (batch, correlation volume) take the mesh and pick the 1-D or 2-D
    form — call sites never branch on axis names themselves.
    """

    data_axis: str = "data"
    fsdp_axis: str = "fsdp"
    seq_axis: str = "seq"

    # ---- mesh-independent specs ---------------------------------------

    #: Leaves smaller than this (elements) stay replicated on fsdp
    #: meshes: biases, norm scales, and scalars cost more to gather
    #: than they save, and their shard would be sub-tile anyway.
    FSDP_MIN_LEAF_SIZE = 4096

    def replicated(self) -> PartitionSpec:
        """Fully replicated: scalars, metrics, BN stats — and params/
        opt_state on meshes without an fsdp axis."""
        return PartitionSpec()

    def params(self, mesh: Optional[Mesh] = None) -> PartitionSpec:
        """Model parameters: the canonical GROUP spec. Replicated on
        meshes without an fsdp axis; ``fsdp_params()`` on meshes with
        one. Per-LEAF resolution (which dim, divisibility fallback) is
        :meth:`param_leaf_spec` — this group-level answer is what the
        audit's declared section and the docs tables pin."""
        if mesh is None or self.fsdp_axis not in mesh.axis_names:
            return PartitionSpec()
        return self.fsdp_params()

    def opt_state(self, mesh: Optional[Mesh] = None) -> PartitionSpec:
        """Optimizer state mirrors the param layout (Adam's mu/nu are
        param-shaped; the step counter falls back to replicated via the
        per-leaf policy like every other small leaf)."""
        return self.params(mesh)

    def fsdp_params(self) -> PartitionSpec:
        """The canonical fsdp GROUP marker spec: sharded over 'fsdp'.
        Real leaves resolve per-dim via :meth:`param_leaf_spec` (a conv
        kernel's dividing dim is rarely the leading one)."""
        return PartitionSpec(self.fsdp_axis)

    def param_leaf_spec(self, mesh: Mesh,
                        shape: Sequence[int]) -> PartitionSpec:
        """Per-leaf fsdp resolution — THE divisibility-fallback policy,
        decided centrally so no call site ever reimplements it.

        Shards the LARGEST dim that the mesh's fsdp axis divides
        (ties: the earliest). Conv kernels are HWIO — their leading
        dims are 1/3/7-sized taps, so a leading-dim-only rule would
        exempt the entire model; the largest dim is a channel dim.
        Falls back to replicated for leaves under FSDP_MIN_LEAF_SIZE
        (biases, norm params, scalars) and leaves no dim of which
        divides the axis — exactly the leaves whose gather would cost
        more than their shard saves."""
        n = self.fsdp_size(mesh)
        shape = tuple(int(s) for s in shape)
        if n <= 1 or int(np.prod(shape, dtype=np.int64)) < \
                self.FSDP_MIN_LEAF_SIZE:
            return PartitionSpec()
        best = None
        for i, d in enumerate(shape):
            if d and d % n == 0 and (best is None or d > shape[best]):
                best = i
        if best is None:
            return PartitionSpec()
        entry: "list" = [None] * len(shape)
        entry[best] = self.fsdp_axis
        return PartitionSpec(*entry)

    def batch(self) -> PartitionSpec:
        """Batch leaves on a 1-D mesh: leading (batch) dim over 'data'."""
        return PartitionSpec(self.data_axis)

    def batch_spatial(self) -> PartitionSpec:
        """Batch leaves on a 2-D (data, seq) mesh: batch over 'data' AND
        image rows over 'seq' — GSPMD partitions convolutions with halo
        exchange and the correlation volume by query rows."""
        return PartitionSpec(self.data_axis, self.seq_axis)

    def batch_spatial_compute(self) -> PartitionSpec:
        """shard_map in/out spec for HALO compute sharding
        (parallel/halo.py): batch leaves enter the body as per-device
        (B/data, H/seq, ...) slabs — batch over 'data', contiguous image
        rows over 'seq'. Same axes as :meth:`batch_spatial`, but pinned
        as its own canonical surface: batch_spatial is a GSPMD
        annotation (the partitioner decides the collectives), while this
        spec is a shard_map CONTRACT — the body sees local slabs and
        does its own ppermute halo exchange (:func:`seq_halo_perms`), so
        the audit tracks the two modes separately."""
        return PartitionSpec(self.data_axis, self.seq_axis)

    def carry(self) -> PartitionSpec:
        """Flow/carry state (flow_init, flow_low — (B, H/8, W/8, 2)):
        batch-sharded like the frames it warm-starts."""
        return PartitionSpec(self.data_axis)

    def corr_query_rows(self) -> PartitionSpec:
        """shard_map spec for explicit context parallelism
        (parallel/context.py): (B, H, W, D) feature maps / coords with
        H (the volume's query axis) over 'seq', everything else local."""
        return PartitionSpec(None, self.seq_axis, None, None)

    # ---- mesh-dependent specs -----------------------------------------

    def batch_for(self, mesh: Mesh) -> PartitionSpec:
        """THE batch spec for a given mesh: spatial (data, seq) when the
        mesh has a seq axis, else batch-only. Shared by the train step's
        in_shardings and the device prefetcher's put, so a prefetched
        batch lands already in the step's input layout. Contract: one
        spec for the whole batch dict, so every batch leaf must be
        >=3-D (B, H, ...) on a 2-D mesh — true for image1/2, flow,
        valid, edges; a future <3-D leaf needs per-leaf specs here AND
        in batch_putter (shard_batch_spatial already splits by ndim on
        the put side)."""
        return (self.batch_spatial() if self.seq_axis in mesh.axis_names
                else self.batch())

    def corr_volume(self, mesh: Mesh) -> PartitionSpec:
        """The ~200 MB all-pairs correlation volume (B, H, W, H*W):
        batch over 'data', query rows over 'seq' when the mesh has the
        axis. Since the flash-blocked kernel became the production
        eval/serve config (ISSUE 12) the volume only materializes behind
        --corr_impl allpairs; its canonical spec is kept for that path,
        but the audit's declared canary moved to corr_fmaps."""
        return self.batch_for(mesh)

    def corr_fmaps(self, mesh: Mesh) -> PartitionSpec:
        """The on-demand correlation paths' streamed tensor set — fmap1
        plus the pooled fmap2 pyramid, (B, H/8, W/8, C)-shaped — the
        audit's canary group now that eval/serve default to the
        volume-free flash kernel: batch over 'data', rows over 'seq'
        like every spatial activation. O(fmaps) is the whole point;
        replicating them at pod batch sizes would still be a layout
        bug the size tripwire must catch."""
        return self.batch_for(mesh)

    # ---- mesh shape queries -------------------------------------------

    def data_size(self, mesh: Mesh) -> int:
        """Number of ways the batch splits on this mesh's data axis."""
        return dict(mesh.shape).get(self.data_axis, 1)

    def has_seq(self, mesh: Mesh) -> bool:
        return self.seq_axis in mesh.axis_names

    def has_fsdp(self, mesh: Mesh) -> bool:
        """True when the mesh instantiates a >1-way fsdp axis (a 1-way
        axis is storage-identical to replicated, so callers skip the
        gather fences for it)."""
        return self.fsdp_size(mesh) > 1

    def fsdp_size(self, mesh: Mesh) -> int:
        """Number of ways params/opt_state shard on this mesh."""
        return dict(mesh.shape).get(self.fsdp_axis, 1)

    def seq_size(self, mesh: Mesh) -> int:
        """Number of ways image rows shard on this mesh's seq axis."""
        return dict(mesh.shape).get(self.seq_axis, 1)


#: The one layout instance application code threads around.
LAYOUT = SpecLayout()

#: Logical array groups the shard audit may see fully replicated without
#: flagging, with the reason pinned next to the exemption. params and
#: opt_state are deliberately NOT here anymore: since the fsdp axis went
#: live they resolve to the fsdp spec on fsdp meshes, and on data-only
#: meshes they sit under the size threshold — the over-threshold
#: replicated canary is ARMED on them (an opt_state that ever resolves
#: fully replicated above the tripwire fails the audit, no exemption).
REPLICATED_OK = {
    "batch_stats": "BatchNorm running stats are global (sync-BN)",
    "rng": "scalar-sized PRNG key",
    "step": "scalar step counter",
    "metrics": "scalar loss/metric outputs",
}

# legacy axis-name constants (parallel/mesh.py re-exports them); new
# code should take names from LAYOUT
DATA_AXIS = LAYOUT.data_axis
SEQ_AXIS = LAYOUT.seq_axis
FSDP_AXIS = LAYOUT.fsdp_axis


def named(mesh: Mesh, spec: PartitionSpec) -> NamedSharding:
    """The ONE NamedSharding constructor (JL010 bans inline ones)."""
    return NamedSharding(mesh, spec)


# --------------------------------------------------------------------------
# mesh constructors — the only Mesh() call sites in the tree (JL011)
# --------------------------------------------------------------------------


def make_mesh(devices: Optional[Sequence[jax.Device]] = None,
              axis: Optional[str] = None) -> Mesh:
    """1-D data mesh over the given (default: all) devices."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (axis or LAYOUT.data_axis,))


def make_mesh_2d(
    n_data: int,
    n_seq: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """(data, seq) mesh: batch DP x spatial/sequence CP.

    The seq axis shards image rows (and with them the quadratic
    correlation volume's query axis — see parallel.context). Keep seq
    groups on adjacent devices so the fmap2 all-gather rides ICI
    neighbors.
    """
    if devices is None:
        devices = jax.devices()
    if n_data * n_seq > len(devices):
        raise ValueError(
            f"mesh {n_data}x{n_seq} needs {n_data * n_seq} devices, "
            f"have {len(devices)}")
    grid = np.asarray(devices[: n_data * n_seq]).reshape(n_data, n_seq)
    return Mesh(grid, (LAYOUT.data_axis, LAYOUT.seq_axis))


def make_mesh_fsdp(
    n_data: int,
    n_fsdp: int,
    n_seq: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """(data, fsdp[, seq]) mesh: batch DP x parameter sharding [x CP].

    The fsdp axis holds params and optimizer state sharded between
    steps (param_leaf_spec); the batch still shards over 'data' (and
    rows over 'seq'), replicated across fsdp — fsdp is storage
    parallelism, gathered for compute by the train step's fences.

    Placement: the INNERMOST axis gets adjacent devices. On a 2-axis
    (data, fsdp) mesh that is fsdp, so the entry gather rides ICI
    neighbors; with ``n_seq`` it is seq — fsdp groups then stride by
    n_seq, deliberately: seq carries a halo exchange per sharded conv
    inside every step (make_mesh_2d's placement argument), while the
    fsdp gather happens once at step entry, so seq keeps the neighbor
    links when both want them.
    """
    if devices is None:
        devices = jax.devices()
    shape = (n_data, n_fsdp) + (() if n_seq is None else (n_seq,))
    total = int(np.prod(shape))
    if total > len(devices):
        raise ValueError(
            f"mesh {'x'.join(str(s) for s in shape)} needs {total} "
            f"devices, have {len(devices)}")
    axes = (LAYOUT.data_axis, LAYOUT.fsdp_axis) + (
        () if n_seq is None else (LAYOUT.seq_axis,))
    grid = np.asarray(devices[:total]).reshape(shape)
    return Mesh(grid, axes)


def make_train_mesh(batch_size: int,
                    devices: Optional[Sequence[jax.Device]] = None,
                    fsdp: "Optional[object]" = None) -> Mesh:
    """The training CLI's mesh policy (was inline glue in train_cli).

    data axis: the largest device count that divides the batch — pick
    batch sizes that are multiples of the slice size to use every chip
    for data parallelism.

    fsdp axis (``fsdp=``):
      * None / 1 — no fsdp axis: the historical 1-D data mesh.
      * 'auto'   — largest divisor after data: the axis grows over the
        devices data-parallelism left idle (a 2-batch on 8 chips:
        data=2, fsdp=4), host-count-aware — the size is walked down to
        one that keeps each fsdp shard group within whole host blocks
        (divides, or is a multiple of, the local device count) so the
        step-entry gather rides intra-host ICI.
      * int N    — exactly N-way fsdp: the axis is carved FIRST and
        data takes the largest batch divisor of the remaining budget
        (an 8-batch on 8 chips with fsdp=4 trains data=2 x fsdp=4) —
        the explicit form benches and A/B tests use.
    """
    if devices is None:
        devices = jax.devices()
    n_data = max(n for n in range(1, len(devices) + 1)
                 if batch_size % n == 0)
    if fsdp is None or fsdp == 1:
        return make_mesh(devices[:n_data])
    if fsdp == "auto":
        n_fsdp = len(devices) // n_data
        local = max(1, jax.local_device_count())
        while n_fsdp > 1 and not (local % n_fsdp == 0
                                  or n_fsdp % local == 0):
            n_fsdp -= 1
    else:
        n_fsdp = int(fsdp)
        if n_fsdp < 1 or n_fsdp > len(devices):
            raise ValueError(
                f"fsdp={n_fsdp}: need between 1 and {len(devices)} "
                f"device(s)")
        n_data = max(n for n in range(1, len(devices) // n_fsdp + 1)
                     if batch_size % n == 0)
    if n_fsdp <= 1:
        return make_mesh(devices[:n_data])
    return make_mesh_fsdp(n_data, n_fsdp, devices=devices)


def make_serve_mesh(n_chips: Optional[int] = None) -> Mesh:
    """1-D data mesh for the serving engine (dexiraft_tpu.serve): an
    inference batch shards over the 'data' axis across `n_chips` (default
    all). Serving never needs the 2-D (data, seq) train mesh — eval
    batches are the parallelism, not image rows."""
    devices = jax.devices()
    if n_chips is not None:
        if not 1 <= n_chips <= len(devices):
            raise ValueError(
                f"n_chips {n_chips} out of range 1..{len(devices)}")
        devices = devices[:n_chips]
    return make_mesh(devices)


# --------------------------------------------------------------------------
# shardings for a concrete mesh
# --------------------------------------------------------------------------


def batch_sharding(mesh: Mesh, axis: Optional[str] = None) -> NamedSharding:
    """Shard the leading (batch) dim over the data axis."""
    if axis is not None and axis != LAYOUT.data_axis:
        # explicit non-canonical axis: honored, but the layout owns the
        # PartitionSpec construction
        return named(mesh, PartitionSpec(axis))
    return named(mesh, LAYOUT.batch())


def spatial_sharding(mesh: Mesh) -> NamedSharding:
    """Batch over 'data' AND image rows over 'seq' (context parallelism):
    GSPMD partitions convolutions with halo exchange and the correlation
    volume by query rows under this annotation."""
    return named(mesh, LAYOUT.batch_spatial())


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated (parameters, optimizer state, scalars)."""
    return named(mesh, LAYOUT.replicated())


def batch_input_sharding(mesh: Mesh) -> NamedSharding:
    """The sharding the jitted train step pins its batch argument to —
    LAYOUT.batch_for(mesh) as a NamedSharding. Shared by train.step and
    the device prefetcher, so a prefetched batch lands ALREADY in the
    step's input layout and consuming it triggers no resharding copy."""
    return named(mesh, LAYOUT.batch_for(mesh))


def carry_sharding(mesh: Mesh) -> NamedSharding:
    """Warm-start carry (flow_init / flow_low) sharding."""
    return named(mesh, LAYOUT.carry())


#: TrainState fields whose leaves shard over fsdp; everything else in
#: the state (step, rng, batch_stats — see REPLICATED_OK) replicates.
_FSDP_STATE_FIELDS = ("params", "opt_state")


def state_sharding(mesh: Mesh, state: Any) -> Any:
    """Per-leaf NamedSharding tree for a TrainState-shaped pytree.

    On fsdp meshes, leaves under the ``params``/``opt_state`` fields
    resolve via LAYOUT.param_leaf_spec (largest dividing dim, small-leaf
    fallback); every other field — and every field on non-fsdp meshes —
    is replicated. ``state`` may be abstract (jax.eval_shape output):
    only shapes are read. This is THE tree the train step pins as its
    state in/out shardings and the one shard_state puts with, so
    storage layout and the jit boundary can never drift apart."""
    repl = replicated_sharding(mesh)
    if not LAYOUT.has_fsdp(mesh):
        return jax.tree.map(lambda _: repl, state)
    flat, treedef = jax.tree_util.tree_flatten_with_path(state)
    shardings = []
    for path, leaf in flat:
        field = getattr(path[0], "name", None)
        if field in _FSDP_STATE_FIELDS:
            shardings.append(
                named(mesh, LAYOUT.param_leaf_spec(mesh, np.shape(leaf))))
        else:
            shardings.append(repl)
    return jax.tree_util.tree_unflatten(treedef, shardings)


def variables_sharding(mesh: Mesh, variables: Any) -> Any:
    """Per-leaf NamedSharding tree for a flax variables dict
    ({"params": ..., "batch_stats": ...}): leaves under "params"
    resolve via LAYOUT.param_leaf_spec — the same storage layout the
    train state pins — and every other collection replicates. The halo
    eval step (train/step.py, ``compute_sharding="halo"``) pins its
    variables argument with this tree, so eval consumes fsdp-STORED
    params directly (the shard_map body gathers per block); on meshes
    without an fsdp axis every leaf resolves replicated. ``variables``
    may be abstract — only shapes are read."""
    repl = replicated_sharding(mesh)
    if not LAYOUT.has_fsdp(mesh):
        return jax.tree.map(lambda _: repl, variables)
    flat, treedef = jax.tree_util.tree_flatten_with_path(variables)
    shardings = []
    for path, leaf in flat:
        top = path[0]
        key = getattr(top, "key", getattr(top, "name", None))
        if key == "params":
            shardings.append(
                named(mesh, LAYOUT.param_leaf_spec(mesh, np.shape(leaf))))
        else:
            shardings.append(repl)
    return jax.tree_util.tree_unflatten(treedef, shardings)


def shard_state(state: Any, mesh: Mesh) -> Any:
    """Device-put a host/replicated TrainState into its storage layout
    (state_sharding). Multi-process safe: sharded leaves assemble via
    make_array_from_callback — every host holds the full host-side copy
    (create_state is deterministic per host) and contributes the slices
    its devices own."""
    shardings = state_sharding(mesh, state)

    def put(x: Any, sharding: NamedSharding) -> jax.Array:
        if sharding.spec == PartitionSpec():
            return _put(x, sharding)
        host = np.asarray(x)
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx: host[idx])

    return jax.tree.map(put, state, shardings)


def gather_state(tree: Any, mesh: Mesh) -> Any:
    """Explicit all-gather of a (possibly fsdp-sharded) pytree back to
    replicated — the host-side companion of the train step's entry
    fence, used where sharded leaves must not reach a consumer that
    compiles without the fences (validation's eval step, interop
    exports). No-op cost on already-replicated leaves."""
    repl = replicated_sharding(mesh)
    return jax.tree.map(
        lambda x: (jax.device_put(x, repl)
                   if isinstance(x, jax.Array)
                   and not x.is_fully_replicated else x), tree)


# --------------------------------------------------------------------------
# host -> device placement
# --------------------------------------------------------------------------


def _put(x: Any, sharding: NamedSharding) -> jax.Array:
    """Host array -> global sharded array.

    Single-process: plain device_put. Multi-process: the host holds only
    its jax.process_index() slice of the global batch (Loader slices at
    decode time), so assemble the global array from per-process locals —
    the multi-host analog of DataParallel's scatter."""
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    return jax.make_array_from_process_local_data(sharding, np.asarray(x))


def shard_batch(batch: Any, mesh: Mesh, axis: Optional[str] = None) -> Any:
    """Device-put every leaf of a host batch with its leading dim sharded.

    The per-host analog of DataParallel's scatter (but zero-copy once the
    arrays are on device; donation happens in the jitted step). In a
    multi-process run each host contributes its local Loader slice and
    the result is the global batch.
    """
    sharding = batch_sharding(mesh, axis)
    return jax.tree.map(lambda x: _put(x, sharding), batch)


def shard_batch_spatial(batch: Any, mesh: Mesh) -> Any:
    """device_put a host batch with (data, seq) sharding: 3D/4D image-like
    leaves shard over (batch, rows); everything else batch-only."""
    sp = spatial_sharding(mesh)
    bo = batch_sharding(mesh)
    return jax.tree.map(
        lambda x: _put(x, sp if np.ndim(x) >= 3 else bo), batch)


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Device-put every leaf of a pytree fully replicated over the mesh.

    Needed explicitly in multi-process runs: host-local state (e.g. from
    create_state, identical on every process by construction) must become
    global replicated arrays before a pjitted step can consume it."""
    repl = replicated_sharding(mesh)
    return jax.tree.map(lambda x: _put(x, repl), tree)


def batch_putter(mesh: Optional[Mesh]):
    """batch -> on-device batch, in the train step's input layout.

    The transfer-side helper for data.prefetch.DevicePrefetcher: returns
    a callable that device_puts a host batch dict with the SAME shardings
    make_train_step pins via in_shardings (batch_input_sharding above —
    same >=3-D-leaf contract on a 2-D mesh). jax.device_put is
    asynchronous, so the returned callable only ENQUEUES the
    host->device copy — the prefetcher keeps several in flight while
    the current step computes. mesh=None: plain device_put to the
    default device (single-chip)."""
    if mesh is None:
        return lambda batch: jax.tree.map(jax.device_put, batch)
    if LAYOUT.has_seq(mesh):
        return lambda batch: shard_batch_spatial(batch, mesh)
    return lambda batch: shard_batch(batch, mesh)


# --------------------------------------------------------------------------
# halo compute sharding — the seq-axis exchange topology and the
# per-block gather schedule (parallel/halo.py consumes both; they live
# HERE so every ppermute call site draws its permutation and axis name
# from the layout, per JL011)
# --------------------------------------------------------------------------


def seq_halo_perms(n_seq: int) -> Tuple[list, list]:
    """ppermute permutation pairs for NON-CIRCULAR neighbor halo
    exchange over the seq axis: ``fwd`` sends each device's boundary
    rows to its successor (filling the successor's TOP halo), ``bwd``
    to its predecessor (BOTTOM halo).

    Non-circular on purpose: ppermute zero-fills unaddressed outputs,
    which is byte-identical to the unsharded program's symmetric zero
    padding at the global image edges — device 0's top halo and device
    n-1's bottom halo get exactly the zeros the global conv would pad,
    so no edge-device special-casing exists anywhere downstream."""
    fwd = [(i, i + 1) for i in range(n_seq - 1)]
    bwd = [(i + 1, i) for i in range(n_seq - 1)]
    return fwd, bwd


def param_block_names(params: Any) -> Tuple[str, ...]:
    """The per-block all-gather schedule for halo compute sharding: the
    top-level module keys of the param tree (fnet / cnet /
    ScanRAFTStep_0), in tree order. Each block's leaves are gathered
    from their fsdp shards immediately before the block runs and
    dropped after (gather→use→drop), so peak gathered-params HBM is one
    block, not the tree. Pinned here so the step, the audit's declared
    groups, and the docs table agree on the grouping."""
    return tuple(params)


def spec_str(spec: PartitionSpec) -> str:
    """Stable, human-diffable serialization of a PartitionSpec — the
    representation layout_golden.json pins ("P()", "P('data', 'seq')",
    "P(None, 'seq', None, None)")."""
    parts = []
    for entry in tuple(spec):
        if entry is None:
            parts.append("None")
        elif isinstance(entry, tuple):
            parts.append("(" + ", ".join(repr(e) for e in entry) + ")")
        else:
            parts.append(repr(entry))
    return "P(" + ", ".join(parts) + ")"
