"""Sequence/context parallelism for the correlation volume.

The all-pairs volume is quadratic in pixels exactly like attention in
tokens — (H/8*W/8)^2 entries (SURVEY.md §5). For frames too large for one
chip's HBM, shard the QUERY axis (the volume's first HW dimension) across
a 'seq' mesh axis: each chip builds and looks up only its row-block of
the volume against the replicated target features — flash-attention-style
row parallelism with zero per-iteration communication (the only
collective is the all-gather of fmap2, inserted once by the partitioner).

Two complementary mechanisms:
  * context_parallel_corr — explicit shard_map over a (data, seq) mesh;
    used when you want manual control (and it documents the math).
  * spatial input shardings (parallel.mesh.spatial_sharding) — GSPMD
    auto-partitioning of the full train step: annotate batch images with
    P('data', 'seq') over H and XLA partitions the encoders (halo
    exchanges), the volume matmul, and the lookup automatically.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map
except ImportError:  # this container's jax (0.4.x) has it experimental
    from jax.experimental.shard_map import shard_map

from dexiraft_tpu.ops.corr import build_corr_pyramid, corr_lookup
from dexiraft_tpu.parallel.layout import LAYOUT, SEQ_AXIS


def context_parallel_corr(
    fmap1: jax.Array,
    fmap2: jax.Array,
    coords: jax.Array,
    mesh: Mesh,
    num_levels: int = 4,
    radius: int = 4,
) -> jax.Array:
    """Row-sharded all-pairs correlation lookup.

    fmap1, fmap2: (B, H, W, D); coords: (B, H, W, 2) in level-0 pixels.
    fmap1/coords shard over H on the 'seq' axis; fmap2 replicates (it is
    the target space every query row needs). Each shard materializes its
    (B * H_loc * W, H, W) volume slice and samples it — the full volume
    never exists on any single chip.

    Returns (B, H, W, num_levels * (2r+1)^2), sharded like the inputs.
    """
    if not LAYOUT.has_seq(mesh):
        raise ValueError(f"mesh has no '{SEQ_AXIS}' axis: {mesh.axis_names}")
    q_spec = LAYOUT.corr_query_rows()

    @partial(shard_map, mesh=mesh,
             in_specs=(q_spec, LAYOUT.replicated(), q_spec),
             out_specs=q_spec)
    def _lookup(f1_loc, f2_full, coords_loc):
        pyr = build_corr_pyramid(f1_loc, f2_full, num_levels, radius)
        return corr_lookup(pyr, coords_loc)

    return _lookup(fmap1, fmap2, coords)


def ring_corr_lookup(
    fmap1: jax.Array,
    fmap2: jax.Array,
    coords: jax.Array,
    mesh: Mesh,
    num_levels: int = 4,
    radius: int = 4,
) -> jax.Array:
    """Ring context-parallel correlation lookup — the ring-attention analog.

    Both QUERY and TARGET rows shard over the 'seq' axis. Per level, each
    chip's target-feature block rotates around the ring (lax.ppermute over
    ICI, exactly ring attention's rotating KV blocks); at each of the
    n_seq steps a chip correlates its queries against the visiting block
    (partial volume matmul) and accumulates that block's window
    contribution through a row-offset hat stencil. The hat supports
    partition across blocks, so the accumulated rows equal the unsharded
    lookup exactly.

    vs. context_parallel_corr (replicated fmap2, per-chip volume slice
    B·H_loc·W × H·W): peak transient here is B·H_loc·W × H_loc·W — the
    quadratic object shrinks with the SQUARE of the ring size, and no
    all-gather of fmap2 is needed. Comm per lookup = the fmap2 pyramid
    once around the ring (~1.33·H·W·C/n_seq per hop).

    Requires H % (n_seq · 2^(num_levels-1)) == 0 so the VALID 2x2 pooling
    of row blocks composes to the global pooling (no window straddles a
    block boundary).

    Returns (B, H, W, num_levels * (2r+1)^2), sharded like the inputs.
    """
    if not LAYOUT.has_seq(mesh):
        raise ValueError(f"mesh has no '{SEQ_AXIS}' axis: {mesh.axis_names}")
    n_seq = mesh.shape[SEQ_AXIS]
    h = fmap1.shape[1]
    if h % n_seq != 0 or (h // n_seq) % (2 ** (num_levels - 1)) != 0:
        raise ValueError(
            f"H={h} must be divisible by n_seq={n_seq} with blocks "
            f"divisible by 2^{num_levels - 1} for pooling alignment")
    q_spec = LAYOUT.corr_query_rows()
    fwd = [(i, (i + 1) % n_seq) for i in range(n_seq)]

    from dexiraft_tpu.ops.corr import (
        _axis_interp_matrix,
        all_pairs_correlation,
        avg_pool_2x2,
    )

    @partial(shard_map, mesh=mesh,
             in_specs=(q_spec, q_spec, q_spec), out_specs=q_spec)
    def _lookup(f1_loc, f2_loc, coords_loc):
        b, h_loc, w = f1_loc.shape[:3]
        n = b * h_loc * w
        idx = jax.lax.axis_index(SEQ_AXIS)
        flat = coords_loc.reshape(n, 2).astype(jnp.float32)
        win = 2 * radius + 1

        out = []
        f2_l = f2_loc.astype(jnp.float32)
        for lvl in range(num_levels):
            h_blk, wl = f2_l.shape[1], f2_l.shape[2]
            centers = flat / (2.0 ** lvl)
            ax = _axis_interp_matrix(centers[:, 0], radius, wl)

            # static unroll: n_seq - 1 ppermute hops (the last visiting
            # block needs no onward rotation)
            rows = jnp.zeros((n, win, wl), jnp.float32)
            blk = f2_l
            for s in range(n_seq):
                src = jax.lax.rem(idx - s + n_seq, n_seq)
                vol = all_pairs_correlation(f1_loc, blk)[..., 0]
                ay = _axis_interp_matrix(centers[:, 1], radius, h_blk,
                                         offset=(src * h_blk).astype(
                                             jnp.float32))
                rows = rows + jnp.einsum("nby,nyx->nbx", ay, vol,
                                         preferred_element_type=jnp.float32)
                if s < n_seq - 1:
                    blk = jax.lax.ppermute(blk, SEQ_AXIS, fwd)

            window = jnp.einsum("nax,nbx->nab", ax, rows,
                                preferred_element_type=jnp.float32)
            out.append(window.reshape(b, h_loc, w, win * win))
            f2_l = avg_pool_2x2(f2_l)
        return jnp.concatenate(out, axis=-1)

    return _lookup(fmap1, fmap2, coords)
