"""Sequence/context parallelism for the correlation volume.

The all-pairs volume is quadratic in pixels exactly like attention in
tokens — (H/8*W/8)^2 entries (SURVEY.md §5). For frames too large for one
chip's HBM, shard the QUERY axis (the volume's first HW dimension) across
a 'seq' mesh axis: each chip builds and looks up only its row-block of
the volume against the replicated target features — flash-attention-style
row parallelism with zero per-iteration communication (the only
collective is the all-gather of fmap2, inserted once by the partitioner).

Two complementary mechanisms:
  * context_parallel_corr — explicit shard_map over a (data, seq) mesh;
    used when you want manual control (and it documents the math).
  * spatial input shardings (parallel.mesh.spatial_sharding) — GSPMD
    auto-partitioning of the full train step: annotate batch images with
    P('data', 'seq') over H and XLA partitions the encoders (halo
    exchanges), the volume matmul, and the lookup automatically.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from dexiraft_tpu.ops.corr import build_corr_pyramid, corr_lookup
from dexiraft_tpu.parallel.mesh import SEQ_AXIS


def context_parallel_corr(
    fmap1: jax.Array,
    fmap2: jax.Array,
    coords: jax.Array,
    mesh: Mesh,
    num_levels: int = 4,
    radius: int = 4,
) -> jax.Array:
    """Row-sharded all-pairs correlation lookup.

    fmap1, fmap2: (B, H, W, D); coords: (B, H, W, 2) in level-0 pixels.
    fmap1/coords shard over H on the 'seq' axis; fmap2 replicates (it is
    the target space every query row needs). Each shard materializes its
    (B * H_loc * W, H, W) volume slice and samples it — the full volume
    never exists on any single chip.

    Returns (B, H, W, num_levels * (2r+1)^2), sharded like the inputs.
    """
    if SEQ_AXIS not in mesh.axis_names:
        raise ValueError(f"mesh has no '{SEQ_AXIS}' axis: {mesh.axis_names}")
    q_spec = P(None, SEQ_AXIS, None, None)

    @partial(shard_map, mesh=mesh,
             in_specs=(q_spec, P(), q_spec), out_specs=q_spec)
    def _lookup(f1_loc, f2_full, coords_loc):
        pyr = build_corr_pyramid(f1_loc, f2_full, num_levels, radius)
        return corr_lookup(pyr, coords_loc)

    return _lookup(fmap1, fmap2, coords)
