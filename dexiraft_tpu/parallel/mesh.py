"""1-D data-parallel mesh over all chips.

Replaces torch.nn.DataParallel's replicate/scatter/gather (train.py:139)
with a jax.sharding.Mesh: batch arrays are sharded over the 'data' axis,
parameters are replicated, and XLA's SPMD partitioner inserts the
gradient all-reduce (psum over ICI) during autodiff of the sharded
computation — no imperative communication code at all.

Multi-host: jax.devices() already enumerates every chip in the slice, so
the same mesh spans hosts; DCN axes would only be needed for multi-slice
(not required for parity, SURVEY.md §2.7).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None, axis: str = DATA_AXIS) -> Mesh:
    """1-D mesh over the given (default: all) devices."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (axis,))


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (batch) dim over the data axis."""
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated (parameters, optimizer state, scalars)."""
    return NamedSharding(mesh, P())


def shard_batch(batch: Any, mesh: Mesh, axis: str = DATA_AXIS) -> Any:
    """Device-put every leaf of a host batch with its leading dim sharded.

    The per-host analog of DataParallel's scatter (but zero-copy once the
    arrays are on device; donation happens in the jitted step).
    """
    sharding = batch_sharding(mesh, axis)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)
