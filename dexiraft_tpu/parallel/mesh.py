"""1-D data-parallel mesh over all chips.

Replaces torch.nn.DataParallel's replicate/scatter/gather (train.py:139)
with a jax.sharding.Mesh: batch arrays are sharded over the 'data' axis,
parameters are replicated, and XLA's SPMD partitioner inserts the
gradient all-reduce (psum over ICI) during autodiff of the sharded
computation — no imperative communication code at all.

Multi-host: jax.devices() already enumerates every chip in the slice, so
the same mesh spans hosts; DCN axes would only be needed for multi-slice
(not required for parity, SURVEY.md §2.7).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SEQ_AXIS = "seq"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None, axis: str = DATA_AXIS) -> Mesh:
    """1-D mesh over the given (default: all) devices."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (axis,))


def make_mesh_2d(
    n_data: int,
    n_seq: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """(data, seq) mesh: batch DP x spatial/sequence CP.

    The seq axis shards image rows (and with them the quadratic
    correlation volume's query axis — see parallel.context). Keep seq
    groups on adjacent devices so the fmap2 all-gather rides ICI
    neighbors.
    """
    if devices is None:
        devices = jax.devices()
    if n_data * n_seq > len(devices):
        raise ValueError(
            f"mesh {n_data}x{n_seq} needs {n_data * n_seq} devices, "
            f"have {len(devices)}")
    grid = np.asarray(devices[: n_data * n_seq]).reshape(n_data, n_seq)
    return Mesh(grid, (DATA_AXIS, SEQ_AXIS))


def make_serve_mesh(n_chips: Optional[int] = None) -> Mesh:
    """1-D data mesh for the serving engine (dexiraft_tpu.serve): an
    inference batch shards over the 'data' axis across `n_chips` (default
    all). Serving never needs the 2-D (data, seq) train mesh — eval
    batches are the parallelism, not image rows."""
    devices = jax.devices()
    if n_chips is not None:
        if not 1 <= n_chips <= len(devices):
            raise ValueError(
                f"n_chips {n_chips} out of range 1..{len(devices)}")
        devices = devices[:n_chips]
    return make_mesh(devices)


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (batch) dim over the data axis."""
    return NamedSharding(mesh, P(axis))


def spatial_sharding(mesh: Mesh) -> NamedSharding:
    """Batch over 'data' AND image rows over 'seq' (context parallelism):
    GSPMD partitions convolutions with halo exchange and the correlation
    volume by query rows under this annotation."""
    return NamedSharding(mesh, P(DATA_AXIS, SEQ_AXIS))


def _put(x: Any, sharding: NamedSharding) -> jax.Array:
    """Host array -> global sharded array.

    Single-process: plain device_put. Multi-process: the host holds only
    its jax.process_index() slice of the global batch (Loader slices at
    decode time), so assemble the global array from per-process locals —
    the multi-host analog of DataParallel's scatter."""
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    return jax.make_array_from_process_local_data(sharding, np.asarray(x))


def shard_batch_spatial(batch: Any, mesh: Mesh) -> Any:
    """device_put a host batch with (data, seq) sharding: 3D/4D image-like
    leaves shard over (batch, rows); everything else batch-only."""
    sp = spatial_sharding(mesh)
    bo = batch_sharding(mesh)
    return jax.tree.map(
        lambda x: _put(x, sp if np.ndim(x) >= 3 else bo), batch)


def batch_input_sharding(mesh: Mesh) -> NamedSharding:
    """The sharding the jitted train step pins its batch argument to:
    (data, seq) spatial when the mesh has a seq axis, else batch-only.
    Shared by train.step and the device prefetcher — a prefetched batch
    lands ALREADY in the step's input layout, so consuming it triggers
    no resharding copy. Contract: one spec for the whole batch dict, so
    every batch leaf must be >=3-D (B, H, ...) on a 2-D mesh — true for
    image1/2, flow, valid, edges; a future <3-D leaf needs per-leaf
    specs here AND in batch_putter (shard_batch_spatial already splits
    by ndim on the put side)."""
    return (spatial_sharding(mesh) if SEQ_AXIS in mesh.axis_names
            else batch_sharding(mesh))


def batch_putter(mesh: Optional[Mesh]):
    """batch -> on-device batch, in the train step's input layout.

    The transfer-side helper for data.prefetch.DevicePrefetcher: returns
    a callable that device_puts a host batch dict with the SAME shardings
    make_train_step pins via in_shardings (batch_input_sharding above —
    same >=3-D-leaf contract on a 2-D mesh). jax.device_put is
    asynchronous, so the returned callable only ENQUEUES the
    host->device copy — the prefetcher keeps several in flight while
    the current step computes. mesh=None: plain device_put to the
    default device (single-chip)."""
    if mesh is None:
        return lambda batch: jax.tree.map(jax.device_put, batch)
    if SEQ_AXIS in mesh.axis_names:
        return lambda batch: shard_batch_spatial(batch, mesh)
    return lambda batch: shard_batch(batch, mesh)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated (parameters, optimizer state, scalars)."""
    return NamedSharding(mesh, P())


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Device-put every leaf of a pytree fully replicated over the mesh.

    Needed explicitly in multi-process runs: host-local state (e.g. from
    create_state, identical on every process by construction) must become
    global replicated arrays before a pjitted step can consume it."""
    repl = replicated_sharding(mesh)
    return jax.tree.map(lambda x: _put(x, repl), tree)


def shard_batch(batch: Any, mesh: Mesh, axis: str = DATA_AXIS) -> Any:
    """Device-put every leaf of a host batch with its leading dim sharded.

    The per-host analog of DataParallel's scatter (but zero-copy once the
    arrays are on device; donation happens in the jitted step). In a
    multi-process run each host contributes its local Loader slice and
    the result is the global batch.
    """
    sharding = batch_sharding(mesh, axis)
    return jax.tree.map(lambda x: _put(x, sharding), batch)
