"""Compat surface over the canonical sharding layout.

Everything here now lives in — and is re-exported from —
``parallel/layout.py``: the frozen :class:`~dexiraft_tpu.parallel.layout.
SpecLayout` is the single source of truth for mesh axis names and
PartitionSpecs, and the jaxlint sharding rules (JL010+) ban constructing
``Mesh``/``NamedSharding``/``PartitionSpec`` anywhere else. This module
keeps the historical import path working for tests and older call
sites; new code imports from ``dexiraft_tpu.parallel.layout``.
"""

from __future__ import annotations

from dexiraft_tpu.parallel.layout import (  # noqa: F401
    DATA_AXIS,
    FSDP_AXIS,
    LAYOUT,
    SEQ_AXIS,
    SpecLayout,
    _put,
    batch_input_sharding,
    batch_putter,
    batch_sharding,
    carry_sharding,
    gather_state,
    make_mesh,
    make_mesh_2d,
    make_mesh_fsdp,
    make_serve_mesh,
    make_train_mesh,
    named,
    replicate,
    replicated_sharding,
    shard_batch,
    shard_batch_spatial,
    shard_state,
    spatial_sharding,
    state_sharding,
)

__all__ = [
    "DATA_AXIS",
    "FSDP_AXIS",
    "LAYOUT",
    "SEQ_AXIS",
    "SpecLayout",
    "batch_input_sharding",
    "batch_putter",
    "batch_sharding",
    "carry_sharding",
    "gather_state",
    "make_mesh",
    "make_mesh_2d",
    "make_mesh_fsdp",
    "make_serve_mesh",
    "make_train_mesh",
    "named",
    "replicate",
    "replicated_sharding",
    "shard_batch",
    "shard_batch_spatial",
    "shard_state",
    "spatial_sharding",
    "state_sharding",
]
