"""Compute-sharded RAFT step: shard_map spatial partitioning with
explicit halo exchange + per-block fsdp all-gather.

The fence-mode train step (train/step.py) keeps fsdp a STORAGE axis:
state gathers to replicated at entry, compute is the replicated
program, and every device holds the full activation set. This module is
the COMPUTE-sharded alternative: the heavy spatial work runs inside one
``shard_map`` over the (data, fsdp, seq) mesh where

  * each device owns a contiguous slab of image rows (the 'seq' axis;
    in/out spec :meth:`SpecLayout.batch_spatial_compute`). Convolutions
    exchange exactly their receptive-field boundary rows with ppermute
    neighbors (:func:`halo_exchange`; permutations from
    :func:`seq_halo_perms`) and compute on own+halo rows — byte-parity
    with the unsharded program, because the non-circular exchange's
    zero-fill at the mesh edges IS the global conv's zero padding;
  * params stay fsdp-sharded BETWEEN and DURING compute: each top-level
    module block (``param_block_names`` — fnet / cnet / ScanRAFTStep_0)
    is all-gathered immediately before it runs, inside
    ``jax.checkpoint``, so the gathered copies are dropped after use
    and re-gathered in backward — peak gathered-params HBM is ONE
    block, not the tree (:func:`_run_block`). GSPMD never sees an
    fsdp-sharded tensor inside a conv (the miscompile the fence
    guards against, tests/test_zzzfsdp.py), because inside shard_map
    there is no GSPMD — every collective here is explicit.

Halo widths are not folklore: each module's H-axis conv chain is
declared NEXT to its convs (models/extractor.block_conv_chain /
encoder_conv_chain, models/update.*_CHAIN) and composed into
receptive-field margins by :func:`chain_halo`; the resulting per-module
table (:func:`halo_rows`) is pinned by tests/test_zzzhalo.py. The
implementation itself exchanges PER CONV (k, s, p) -> (lo=p,
hi=max(0, k-s-p)) rows, so a single conv never moves more than its own
kernel's support.

The forward here is a manual re-implementation of the flax modules
(exact auto-names, exact op order) rather than flax.apply under
shard_map — flax normalization layers reduce over the LOCAL slab,
which is silently wrong under row sharding; the manual forward psums
the instance-norm moments over 'seq' and runs frozen BatchNorm as a
pure affine. The price is a strict support matrix
(:func:`check_halo_support`): v1 ('raft') variant, allpairs fp32
correlation, no dropout/noise/accumulation, and BatchNorm only frozen.
Loss parity vs the fence step is pinned by tests/test_zzzhalo.py.

Correlation under row sharding: fmap2 (the target space every query
row needs) all-gathers over 'seq' once per step; the pyramid builds
from (local queries x global targets), so each device materializes
only its ROW-BLOCK of the quadratic volume — the context-parallel
formulation of parallel/context.py, now inside the train step. The
lookup is bit-exact vs unsharded (per-query-pixel local math).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map
except ImportError:  # this container's jax (0.4.x) has it experimental
    from jax.experimental.shard_map import shard_map

from dexiraft_tpu.config import RAFTConfig, TrainConfig
from dexiraft_tpu.models.extractor import encoder_conv_chain
from dexiraft_tpu.models.raft import _normalize
from dexiraft_tpu.models.update import (
    CONV_GRU_CHAIN,
    FLOW_HEAD_CHAIN,
    MASK_HEAD_CHAIN,
    MOTION_ENCODER_CHAIN,
    SEP_CONV_GRU_CHAIN,
)
from dexiraft_tpu.ops.corr import build_corr_pyramid, corr_lookup
from dexiraft_tpu.ops.grid import _resize_matrix, coords_grid
from dexiraft_tpu.ops.losses import MAX_FLOW
from dexiraft_tpu.parallel.layout import (
    DATA_AXIS,
    FSDP_AXIS,
    LAYOUT,
    SEQ_AXIS,
    param_block_names,
    seq_halo_perms,
)

Chain = Tuple[Tuple[int, int, int], ...]  # ((kernel, stride, pad), ...)


# --------------------------------------------------------------------------
# halo arithmetic — compose a conv chain into receptive-field margins
# --------------------------------------------------------------------------


def chain_halo(chain: Chain) -> Tuple[int, int]:
    """(top, bottom) input-row margins one output row of the chain needs
    beyond the rows it owns.

    Walking the chain LAST conv to FIRST: a single conv (k, s, p) reads
    p rows above its first input row (lo = p) and max(0, k - s - p)
    below its last (hi); a downstream margin of m rows becomes s*m
    input rows through a stride-s conv. Hence the recursion
    lo = p + s*lo_next, hi = max(0, k - s - p) + s*hi_next — the
    standard receptive-field-radius composition, derived from the same
    (k, s, p) triples the convs themselves are built from.
    """
    lo = hi = 0
    for k, s, p in reversed(chain):
        lo = p + s * lo
        hi = max(0, k - s - p) + s * hi
    return lo, hi


def halo_rows() -> Dict[str, int]:
    """Per-module halo width (rows of neighbor context one device needs,
    max of the top/bottom margins) at the module's INPUT resolution.

    Derived live from the declarative conv chains pinned next to the
    modules (models/extractor.py, models/update.py); the expected
    values are pinned by tests/test_zzzhalo.py so a kernel-size change
    that forgets its exchange width fails a test, not a pod run.
    upsample_convex / upflow8 read one coarse row past each slab edge
    (3x3 taps / the bilinear hat's support) — pinned directly, they
    have no conv chain.
    """
    table = {
        "encoder_basic": chain_halo(encoder_conv_chain("residual")),
        "encoder_small": chain_halo(encoder_conv_chain("bottleneck")),
        "motion_encoder": chain_halo(MOTION_ENCODER_CHAIN),
        "gru_conv": chain_halo(CONV_GRU_CHAIN),
        "gru_sep": chain_halo(SEP_CONV_GRU_CHAIN),
        "flow_head": chain_halo(FLOW_HEAD_CHAIN),
        "mask_head": chain_halo(MASK_HEAD_CHAIN),
    }
    rows = {name: max(lo, hi) for name, (lo, hi) in table.items()}
    rows["upsample_convex"] = 1
    rows["upflow8"] = 1
    return rows


# --------------------------------------------------------------------------
# exchange + conv primitives (shard_map-body code: collectives explicit)
# --------------------------------------------------------------------------


def halo_exchange(x: jax.Array, lo: int, hi: int, n_seq: int) -> jax.Array:
    """Extend a (B, L, ...) row slab with ``lo`` rows from the seq
    predecessor and ``hi`` from the successor via neighbor ppermute.

    Non-circular (seq_halo_perms): the first device's top halo and the
    last device's bottom halo arrive ZERO-filled — byte-identical to
    the unsharded conv's symmetric zero padding at the image edges, so
    callers never special-case edge devices. Guards lo/hi == 0 before
    slicing (``x[:, -0:]`` is the whole array, not an empty slab).
    """
    if n_seq <= 1 or (lo == 0 and hi == 0):
        return x
    fwd, bwd = seq_halo_perms(n_seq)
    parts = []
    if lo > 0:
        parts.append(jax.lax.ppermute(x[:, -lo:], SEQ_AXIS, fwd))
    parts.append(x)
    if hi > 0:
        parts.append(jax.lax.ppermute(x[:, :hi], SEQ_AXIS, bwd))
    return jnp.concatenate(parts, axis=1)


def halo_conv(
    x: jax.Array,
    kernel: jax.Array,
    bias: Optional[jax.Array],
    *,
    stride: int = 1,
    n_seq: int = 1,
) -> jax.Array:
    """One NHWC conv on a row slab: exchange the kernel's own H support
    (lo = p, hi = max(0, k - s - p)), then convolve VALID in H and SAME
    in W. Output rows = L/stride, aligned with the device's global row
    block — the composition over a whole chain therefore equals the
    unsharded conv chain row-for-row (parity pinned at bit level by
    tests/test_zzzhalo.py). n_seq == 1 pads zeros locally instead, which
    is the identical global program.
    """
    kh, kw = int(kernel.shape[0]), int(kernel.shape[1])
    p_h, p_w = kh // 2, kw // 2
    lo, hi = p_h, max(0, kh - stride - p_h)
    if lo or hi:
        if n_seq > 1:
            x = halo_exchange(x, lo, hi, n_seq)
        else:
            x = jnp.pad(x, ((0, 0), (lo, hi), (0, 0), (0, 0)))
    y = jax.lax.conv_general_dilated(
        x, kernel,
        window_strides=(stride, stride),
        padding=((0, 0), (p_w, p_w)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if bias is not None:
        y = y + bias
    return y


def _instance_norm(x: jax.Array, n_seq: int, eps: float = 1e-5) -> jax.Array:
    """Instance norm (per sample, per channel over the FULL H x W) on a
    row slab: local first/second moments psum over 'seq'. Matches flax
    GroupNorm(group_size=1, no scale/bias): var = max(0, E[x^2] - E[x]^2)
    with the same clamp. Association of the cross-device sum differs
    from the single-pass reduction, so this is float-tolerance (not
    bit) parity — covered by the fence-vs-halo loss-parity pin."""
    s = jnp.sum(x, axis=(1, 2))
    ss = jnp.sum(x * x, axis=(1, 2))
    cnt = x.shape[1] * x.shape[2]
    if n_seq > 1:
        s = jax.lax.psum(s, SEQ_AXIS)
        ss = jax.lax.psum(ss, SEQ_AXIS)
        cnt = cnt * n_seq
    mu = s / cnt
    var = jnp.maximum(ss / cnt - mu * mu, 0.0)
    return (x - mu[:, None, None]) * jax.lax.rsqrt(var[:, None, None] + eps)


def _frozen_bn(x, scale, bias, mean, var, eps: float = 1e-5):
    """BatchNorm on running stats — a pure per-channel affine, in flax's
    exact op order ((x - mean) * (rsqrt(var+eps) * scale) + bias), so it
    is bit-identical to the unsharded frozen-BN path row-for-row."""
    mul = jax.lax.rsqrt(var + eps) * scale
    return (x - mean) * mul + bias


def _norm(norm_fn: str, p: Any, st: Any, idx: int, x, n_seq: int):
    if norm_fn == "instance":
        return _instance_norm(x, n_seq)
    if norm_fn == "batch":
        bn_p, bn_s = p[f"BatchNorm_{idx}"], st[f"BatchNorm_{idx}"]
        return _frozen_bn(x, bn_p["scale"], bn_p["bias"],
                          bn_s["mean"], bn_s["var"])
    return x  # "none"


def _conv(p: Any, name: str, x, *, stride: int = 1, n_seq: int = 1):
    leaf = p[name]
    return halo_conv(x, leaf["kernel"], leaf["bias"],
                     stride=stride, n_seq=n_seq)


# --------------------------------------------------------------------------
# manual module forwards (flax auto-names, flax op order)
# --------------------------------------------------------------------------


def _residual_block(p, st, x, stride, norm_fn, n_seq):
    y = jax.nn.relu(_norm(norm_fn, p, st, 0,
                          _conv(p, "Conv_0", x, stride=stride, n_seq=n_seq),
                          n_seq))
    y = jax.nn.relu(_norm(norm_fn, p, st, 1,
                          _conv(p, "Conv_1", y, n_seq=n_seq), n_seq))
    if stride != 1:
        x = _conv(p, "Conv_2", x, stride=stride, n_seq=n_seq)
        x = _norm(norm_fn, p, st, 2, x, n_seq)
    return jax.nn.relu(x + y)


def _bottleneck_block(p, st, x, stride, norm_fn, n_seq):
    y = jax.nn.relu(_norm(norm_fn, p, st, 0,
                          _conv(p, "Conv_0", x, n_seq=n_seq), n_seq))
    y = jax.nn.relu(_norm(norm_fn, p, st, 1,
                          _conv(p, "Conv_1", y, stride=stride, n_seq=n_seq),
                          n_seq))
    y = jax.nn.relu(_norm(norm_fn, p, st, 2,
                          _conv(p, "Conv_2", y, n_seq=n_seq), n_seq))
    if stride != 1:
        x = _conv(p, "Conv_3", x, stride=stride, n_seq=n_seq)
        x = _norm(norm_fn, p, st, 3, x, n_seq)
    return jax.nn.relu(x + y)


def _encoder_fwd(p, st, x, *, small: bool, norm_fn: str, n_seq: int):
    """models/extractor.Encoder, manually: 7x7/2 stem -> 2 blocks per
    stage -> 1x1 projection, with sharded-aware norms. Stage schedule
    and block auto-names mirror the flax module exactly (param trees
    are shared with the fence path — checkpoints interchange)."""
    from dexiraft_tpu.models.extractor import BASIC_STAGES, SMALL_STAGES
    stages = SMALL_STAGES if small else BASIC_STAGES
    block_fwd = _bottleneck_block if small else _residual_block
    cls = "BottleneckBlock" if small else "ResidualBlock"

    x = _conv(p, "Conv_0", x, stride=2, n_seq=n_seq)
    x = jax.nn.relu(_norm(norm_fn, p, st, 0, x, n_seq))
    i = 0
    for _, stride in stages:
        for s in (stride, 1):
            name = f"{cls}_{i}"
            x = block_fwd(p[name], st.get(name, {}) if st else {},
                          x, s, norm_fn, n_seq)
            i += 1
    return _conv(p, "Conv_1", x, n_seq=n_seq)


def _small_update(p, net, inp, corr, flow, n_seq):
    """models/update.SmallUpdateBlock, manually. ``p`` is the
    ScanRAFTStep_0 subtree (the update block is its one child)."""
    p = p["SmallUpdateBlock_0"]
    me = p["SmallMotionEncoder_0"]
    cor = jax.nn.relu(_conv(me, "Conv_0", corr, n_seq=n_seq))
    flo = jax.nn.relu(_conv(me, "Conv_1", flow, n_seq=n_seq))
    flo = jax.nn.relu(_conv(me, "Conv_2", flo, n_seq=n_seq))
    out = jax.nn.relu(_conv(me, "Conv_3",
                            jnp.concatenate([cor, flo], -1), n_seq=n_seq))
    motion = jnp.concatenate([out, flow], -1)

    x = jnp.concatenate([inp, motion], -1)
    g = p["ConvGRU_0"]
    hx = jnp.concatenate([net, x], -1)
    z = jax.nn.sigmoid(_conv(g, "Conv_0", hx, n_seq=n_seq))
    r = jax.nn.sigmoid(_conv(g, "Conv_1", hx, n_seq=n_seq))
    q = jnp.tanh(_conv(g, "Conv_2",
                       jnp.concatenate([r * net, x], -1), n_seq=n_seq))
    net = (1 - z) * net + z * q

    fh = p["FlowHead_0"]
    delta = _conv(fh, "Conv_1", jax.nn.relu(_conv(fh, "Conv_0", net,
                                                  n_seq=n_seq)), n_seq=n_seq)
    return net, None, delta


def _sep_gru_pass(g, base: int, h, x, n_seq):
    hx = jnp.concatenate([h, x], -1)
    z = jax.nn.sigmoid(_conv(g, f"Conv_{base}", hx, n_seq=n_seq))
    r = jax.nn.sigmoid(_conv(g, f"Conv_{base + 1}", hx, n_seq=n_seq))
    q = jnp.tanh(_conv(g, f"Conv_{base + 2}",
                       jnp.concatenate([r * h, x], -1), n_seq=n_seq))
    return (1 - z) * h + z * q


def _basic_update(p, net, inp, corr, flow, n_seq):
    """models/update.BasicUpdateBlock, manually (incl. the mask head,
    whose Conv_0/Conv_1 live at the update block's own scope). ``p`` is
    the ScanRAFTStep_0 subtree."""
    p = p["BasicUpdateBlock_0"]
    me = p["BasicMotionEncoder_0"]
    cor = jax.nn.relu(_conv(me, "Conv_0", corr, n_seq=n_seq))
    cor = jax.nn.relu(_conv(me, "Conv_1", cor, n_seq=n_seq))
    flo = jax.nn.relu(_conv(me, "Conv_2", flow, n_seq=n_seq))
    flo = jax.nn.relu(_conv(me, "Conv_3", flo, n_seq=n_seq))
    out = jax.nn.relu(_conv(me, "Conv_4",
                            jnp.concatenate([cor, flo], -1), n_seq=n_seq))
    motion = jnp.concatenate([out, flow], -1)

    x = jnp.concatenate([inp, motion], -1)
    g = p["SepConvGRU_0"]
    net = _sep_gru_pass(g, 0, net, x, n_seq)  # (1,5) horizontal
    net = _sep_gru_pass(g, 3, net, x, n_seq)  # (5,1) vertical

    fh = p["FlowHead_0"]
    delta = _conv(fh, "Conv_1", jax.nn.relu(_conv(fh, "Conv_0", net,
                                                  n_seq=n_seq)), n_seq=n_seq)

    mask = jax.nn.relu(_conv(p, "Conv_0", net, n_seq=n_seq))
    mask = 0.25 * _conv(p, "Conv_1", mask, n_seq=n_seq)
    return net, mask, delta


# --------------------------------------------------------------------------
# upsampling on row slabs
# --------------------------------------------------------------------------


def _upflow8_halo(flow: jax.Array, n_seq: int) -> jax.Array:
    """ops/grid.upflow8 on a (B, L, W, 2) row slab, bit-exact.

    Output rows [8*c0, 8*(c0+L)) of the global align_corners resize read
    input rows [c0-1, c0+L] only (the hat's support is two adjacent
    taps and the stretch factor is < 1/8 per output row), i.e. the
    local slab + a 1-row halo each side. The hat matrix is the GLOBAL
    one (_resize_matrix — same linspace arithmetic as the unsharded
    path), dynamic-sliced to the device's row block; a zero column
    padded each side makes the c0-1 / c0+L taps in-bounds WITHOUT
    dynamic_slice's start clamping shifting the window at the mesh
    edges. Zero-weight taps against zero-filled halo rows contribute
    exact +-0, so the two-tap sums match the unsharded einsum bitwise.
    """
    b, lc, wc = flow.shape[:3]
    if n_seq <= 1:
        from dexiraft_tpu.ops.grid import upflow8
        return upflow8(flow)
    h_tot = lc * n_seq
    ry = _resize_matrix(h_tot, 8 * h_tot, flow.dtype)
    ry = jnp.pad(ry, ((0, 0), (1, 1)))
    c0 = jax.lax.axis_index(SEQ_AXIS) * lc
    m_h = jax.lax.dynamic_slice(ry, (8 * c0, c0), (8 * lc, lc + 2))
    rx = _resize_matrix(wc, 8 * wc, flow.dtype)

    xh = halo_exchange(flow, 1, 1, n_seq)  # (B, L+2, W, 2)
    out = jnp.einsum("oy,nyxc->noxc", m_h, xh,
                     precision=jax.lax.Precision.HIGHEST,
                     preferred_element_type=jnp.float32).astype(flow.dtype)
    out = jnp.einsum("px,noxc->nopc", rx, out,
                     precision=jax.lax.Precision.HIGHEST,
                     preferred_element_type=jnp.float32).astype(flow.dtype)
    return 8.0 * out


def _upsample_convex_halo(flow: jax.Array, mask: jax.Array,
                          n_seq: int) -> jax.Array:
    """ops/upsample.upsample_flow_convex on a row slab, bit-exact: the
    3x3 patch extraction needs one coarse row past each slab edge —
    halo-exchanged where the unsharded path zero-pads (same zeros at
    the global edges, by the non-circular exchange contract)."""
    b, h, w, _ = flow.shape
    m = mask.reshape(b, h, w, 9, 8, 8)
    m = jax.nn.softmax(m, axis=3)

    fp = halo_exchange(8.0 * flow, 1, 1, n_seq)  # rows: L + 2
    fp = jnp.pad(fp, ((0, 0), (0, 0), (1, 1), (0, 0)))
    patches = jnp.stack(
        [fp[:, dy:dy + h, dx:dx + w, :] for dy in range(3) for dx in range(3)],
        axis=3,
    )
    up = jnp.einsum("bhwkij,bhwkc->bhwijc", m, patches)
    return up.transpose(0, 1, 3, 2, 4, 5).reshape(b, 8 * h, 8 * w, 2)


def _upsample_halo(flow, mask, n_seq):
    if mask is None:
        return _upflow8_halo(flow, n_seq)
    return _upsample_convex_halo(flow.astype(jnp.float32),
                                 mask.astype(jnp.float32), n_seq)


def _coords_grid_sharded(b: int, l8: int, w8: int, n_seq: int) -> jax.Array:
    """coords_grid in GLOBAL pixel coordinates on a row slab: the local
    grid plus this device's global row offset on the y channel. Global
    coords are what makes the correlation lookup bit-exact — the level
    arrays span the full (gathered) target height."""
    c = coords_grid(b, l8, w8)
    if n_seq > 1:
        off = (jax.lax.axis_index(SEQ_AXIS) * l8).astype(jnp.float32)
        c = c + jnp.stack([jnp.zeros_like(off), off])
    return c


# --------------------------------------------------------------------------
# sharded loss / metrics (global sums via psum; static global count)
# --------------------------------------------------------------------------


def _flow_metrics_sharded(pred, gt, valid_mask):
    epe = jnp.sqrt(jnp.sum((pred - gt) ** 2, axis=-1))
    v = valid_mask.astype(jnp.float32)
    sums = jnp.stack([
        jnp.sum(epe * v),
        jnp.sum((epe < 1.0).astype(jnp.float32) * v),
        jnp.sum((epe < 3.0).astype(jnp.float32) * v),
        jnp.sum((epe < 5.0).astype(jnp.float32) * v),
        jnp.sum(v),
    ])
    sums = jax.lax.psum(sums, (DATA_AXIS, SEQ_AXIS))
    denom = jnp.maximum(sums[4], 1.0)
    return {"epe": sums[0] / denom, "1px": sums[1] / denom,
            "3px": sums[2] / denom, "5px": sums[3] / denom}


def _sequence_loss_sharded(flow_preds, flow_gt, valid, gamma,
                           n_data, n_seq):
    """ops/losses.sequence_loss on (data, seq)-sharded predictions,
    returned as this device's LOCAL CONTRIBUTION to the global loss:
    local |err| sums divided by the STATIC GLOBAL element count — the
    psum over (data, seq) happens OUTSIDE value_and_grad (body), so the
    gradient seed is the plain per-device cotangent and the grads'
    cross-device psum counts each contribution exactly once (psum's
    transpose is itself a psum: seeding the replicated psum'd scalar
    would scale every grad by n_data*n_seq). Masking semantics match
    the unsharded loss exactly (invalid pixels zeroed but counted)."""
    n = flow_preds.shape[0]
    mag = jnp.sqrt(jnp.sum(flow_gt ** 2, axis=-1))
    valid_mask = (valid >= 0.5) & (mag < MAX_FLOW)
    vf = valid_mask.astype(jnp.float32)[None, ..., None]

    weights = gamma ** jnp.arange(n - 1, -1, -1, dtype=jnp.float32)
    i_loss = jnp.abs(flow_preds - flow_gt[None])
    local = jnp.sum(vf * i_loss, axis=(1, 2, 3, 4))  # (n,)
    count = ((flow_preds.shape[1] * n_data)
             * (flow_preds.shape[2] * n_seq)
             * flow_preds.shape[3] * 2)
    local_loss = jnp.sum(weights * (local / count))

    metrics = _flow_metrics_sharded(flow_preds[-1], flow_gt, valid_mask)
    return local_loss, metrics


# --------------------------------------------------------------------------
# per-block fsdp gather (gather -> use -> drop)
# --------------------------------------------------------------------------


def _spec_dim(spec) -> int:
    """Index of the fsdp-sharded dim in a param leaf spec, -1 if the
    leaf is replicated. Spec trees are NOT tree-mapped over
    (PartitionSpec is a tuple subclass — jax.tree would descend into
    it); the int trees this produces are what the body logic walks."""
    for i, entry in enumerate(tuple(spec)):
        if entry == FSDP_AXIS:
            return i
    return -1


def _run_block(fn: Callable, block_params: Any, block_dims: Any,
               n_fsdp: int, *args):
    """Run ``fn(full_params, *args)`` with the block's fsdp-sharded
    leaves all-gathered just-in-time. The gather AND the block compute
    sit inside one jax.checkpoint: the gathered leaves are not residuals
    (backward re-gathers and recomputes), so peak gathered-params HBM is
    one block — gather -> use -> drop. Replicated leaves (dim -1: small
    biases/norm params per LAYOUT.param_leaf_spec) pass through."""
    dims = jax.tree.leaves(block_dims)
    if n_fsdp <= 1 or not any(d >= 0 for d in dims):
        return fn(block_params, *args)

    def gathered_call(bp, *a):
        full = jax.tree.map(
            lambda leaf, d: (jax.lax.all_gather(leaf, FSDP_AXIS,
                                                axis=d, tiled=True)
                             if d >= 0 else leaf),
            bp, block_dims)
        return fn(full, *a)

    return jax.checkpoint(gathered_call)(block_params, *args)


# --------------------------------------------------------------------------
# support matrix
# --------------------------------------------------------------------------


def check_halo_support(cfg: RAFTConfig, tc: TrainConfig,
                       mesh: Optional[Mesh]) -> None:
    """Refuse configurations the halo forward does not reproduce, each
    with a one-line actionable error — the v1 support matrix
    (docs/parallel.md "Compute sharding")."""
    if mesh is None or not LAYOUT.has_seq(mesh):
        raise ValueError(
            "compute_sharding='halo' needs a mesh with a 'seq' axis — "
            "build one with make_mesh_fsdp(n_data, n_fsdp, n_seq) or "
            "make_mesh_2d(n_data, n_seq)")
    if cfg.variant != "raft":
        raise ValueError(
            f"compute_sharding='halo' supports variant='raft' (v1) only, "
            f"got {cfg.variant!r} — edge streams / DexiNed are not halo-"
            "sharded yet; use compute_sharding='fence'")
    if cfg.corr_impl != "allpairs" or cfg.corr_dtype != "fp32":
        raise ValueError(
            f"compute_sharding='halo' needs corr_impl='allpairs' with "
            f"corr_dtype='fp32' (got {cfg.corr_impl!r}/{cfg.corr_dtype!r}) "
            "— the sharded lookup builds the row-block pyramid explicitly")
    if cfg.fused_update:
        raise ValueError(
            "compute_sharding='halo' does not support fused_update — the "
            "Pallas fused step is not shard_map-partitioned; use "
            "compute_sharding='fence'")
    if cfg.mixed_precision or tc.precision != "fp32":
        raise ValueError(
            "compute_sharding='halo' is fp32-only for now (precision="
            f"{tc.precision!r}, mixed_precision={cfg.mixed_precision}) — "
            "bit-parity with the fence step is pinned in fp32")
    if cfg.dropout > 0.0:
        raise ValueError(
            "compute_sharding='halo' does not support dropout>0 — the "
            "manual forward draws no per-device RNG; set dropout=0.0")
    if tc.add_noise:
        raise ValueError(
            "compute_sharding='halo' does not support add_noise — noise "
            "RNG is not split per row slab; disable it or use 'fence'")
    if tc.accum_steps != 1:
        raise ValueError(
            f"compute_sharding='halo' needs accum_steps=1 (got "
            f"{tc.accum_steps}) — accumulate by growing the data axis")
    if tc.edge_sum_fusion:
        raise ValueError(
            "compute_sharding='halo' does not support edge_sum_fusion "
            "(v1-lineage double forward); use compute_sharding='fence'")
    if (not cfg.small) and not tc.freeze_bn:
        raise ValueError(
            "compute_sharding='halo' runs BatchNorm frozen only: set "
            "freeze_bn=True (post-chairs stages already do) or use the "
            "small model — train-mode sync-BN stats are not exchanged")
    n_data = LAYOUT.data_size(mesh)
    n_seq = LAYOUT.seq_size(mesh)
    if tc.batch_size % n_data != 0:
        raise ValueError(
            f"batch_size {tc.batch_size} not divisible by the mesh's "
            f"{n_data}-way data axis")
    h = tc.image_size[0]
    if h % (8 * n_seq) != 0:
        raise ValueError(
            f"image height {h} must be divisible by 8*n_seq={8 * n_seq} "
            f"so every device owns whole 1/8-resolution rows — pad with "
            f"data.padder.InputPadder(shape, seq={n_seq})")
    if h // (8 * n_seq) < 3:
        raise ValueError(
            f"image height {h} over {n_seq} seq shards leaves "
            f"{h // (8 * n_seq)} rows per device at 1/8 resolution; "
            "need >= 3 (the update block's 7x7 support) — use fewer seq "
            "shards or taller crops")


# --------------------------------------------------------------------------
# the sharded forward + train/eval fn factories
# --------------------------------------------------------------------------


def _halo_forward(cfg: RAFTConfig, params, batch_stats, im1, im2, *,
                  n_seq: int, n_fsdp: int, param_dims, iters: int,
                  remat_mode: str, unroll: int, emit: bool,
                  flow_init=None):
    """The v1 RAFT forward on (B_loc, H_loc, W, C) slabs — mirrors
    models/raft.RAFT.__call__ (mode='pair') op-for-op, with per-block
    fsdp gathers and explicit halo exchange. emit=True returns the
    per-iteration upsampled flows (training); emit=False returns
    (flow_low, flow_up) (test mode)."""
    small = cfg.small
    ctx_norm = "none" if small else "batch"
    hdim = cfg.hidden_dim
    update_fwd = _small_update if small else _basic_update

    x1 = _normalize(im1.astype(jnp.float32))
    x2 = _normalize(im2.astype(jnp.float32))

    # fnet on both frames, one batched call like the flax path (instance
    # norm is per-sample, so batch concat changes nothing numerically)
    both = jnp.concatenate([x1, x2], axis=0)
    fmaps = _run_block(
        lambda p, x: _encoder_fwd(p, {}, x, small=small,
                                  norm_fn="instance", n_seq=n_seq),
        params["fnet"], param_dims["fnet"], n_fsdp, both)
    fmap1, fmap2 = jnp.split(fmaps, 2, axis=0)

    cnet_stats = batch_stats.get("cnet", {}) if batch_stats else {}
    ctx = _run_block(
        lambda p, x: _encoder_fwd(p, cnet_stats, x, small=small,
                                  norm_fn=ctx_norm, n_seq=n_seq),
        params["cnet"], param_dims["cnet"], n_fsdp, x1)
    net = jnp.tanh(ctx[..., :hdim])
    inp = jax.nn.relu(ctx[..., hdim:])

    # row-block correlation pyramid: local queries x gathered targets —
    # each device holds only its (B*H_loc*W, H, W) volume slice
    f2_full = (jax.lax.all_gather(fmap2, SEQ_AXIS, axis=1, tiled=True)
               if n_seq > 1 else fmap2)
    pyr = build_corr_pyramid(fmap1, f2_full, cfg.corr_levels, cfg.radius)

    b_loc, l8, w8 = fmap1.shape[:3]
    coords0 = _coords_grid_sharded(b_loc, l8, w8, n_seq)
    coords1 = coords0 if flow_init is None else coords0 + flow_init

    def scan_block(up_params, net, coords1, inp, pyr, coords0):
        def step(carry, _):
            net, coords1 = carry
            coords1 = jax.lax.stop_gradient(coords1)
            flow = coords1 - coords0
            corr = corr_lookup(pyr, coords1)
            net, up_mask, delta = update_fwd(up_params, net, inp, corr,
                                             flow, n_seq)
            coords1 = coords1 + delta.astype(jnp.float32)
            if not emit:
                return (net, coords1), up_mask
            flow_up = _upsample_halo(coords1 - coords0, up_mask, n_seq)
            return (net, coords1), flow_up

        if remat_mode == "per_iter":
            step = jax.checkpoint(step, prevent_cse=False)
        elif remat_mode == "dots_saveable":
            step = jax.checkpoint(
                step, prevent_cse=False,
                policy=jax.checkpoint_policies.dots_saveable)
        (net, coords1), ys = jax.lax.scan(
            step, (net, coords1), None, length=iters,
            unroll=max(1, min(unroll, iters)))
        return coords1, ys

    coords1, ys = _run_block(scan_block, params["ScanRAFTStep_0"],
                             param_dims["ScanRAFTStep_0"], n_fsdp,
                             net, coords1, inp, pyr, coords0)
    if emit:
        return ys  # (iters, B_loc, 8*L, 8*W, 2)
    flow_low = coords1 - coords0
    up_mask = None if small else ys[-1]
    return flow_low, _upsample_halo(flow_low, up_mask, n_seq)


def _param_geometry(mesh: Mesh, abstract_params):
    """(spec tree, int dims tree) for a param tree on this mesh. The
    spec tree goes ONLY to shard_map in_specs/out_specs; all body logic
    walks the int tree (-1 = replicated) — PartitionSpec is a tuple
    subclass, so tree-mapping over spec trees would descend into them."""
    specs = jax.tree.map(
        lambda leaf: LAYOUT.param_leaf_spec(mesh, leaf.shape),
        abstract_params)
    dims = jax.tree.map(
        lambda leaf: _spec_dim(LAYOUT.param_leaf_spec(mesh, leaf.shape)),
        abstract_params)
    return specs, dims


def make_halo_train_fn(cfg: RAFTConfig, tc: TrainConfig, mesh: Mesh,
                       abstract_params, remat_mode: str = "none"):
    """Build the shard_map'd sharded-compute gradient fn:

        (params, batch_stats, image1, image2, flow, valid)
            -> (loss, metrics, grads)

    params enter/leave in their fsdp STORAGE layout (param_leaf_spec) —
    no fences; batch leaves enter as (data, seq) slabs
    (batch_spatial_compute); loss/metrics replicate; grads leave in the
    params' layout, ready for a sharded optimizer update OUTSIDE the
    shard_map (train/step.py wires that). batch_stats pass through
    read-only (halo trains with instance norm / frozen BN only, per
    check_halo_support). The gradient rule: value_and_grad runs on the
    LOCAL loss contribution (the global loss is its (data, seq) psum,
    taken outside the grad — seeding the psum'd replicated scalar would
    scale every grad by n_data*n_seq, since psum's transpose is again a
    psum), per-device grads then psum over (data, seq) to assemble the
    global gradient; gathered leaves additionally divide by n_fsdp (the
    all-gather transpose — a psum_scatter over fsdp — sums n_fsdp
    identical replicas)."""
    check_halo_support(cfg, tc, mesh)
    n_data = LAYOUT.data_size(mesh)
    n_seq = LAYOUT.seq_size(mesh)
    n_fsdp = LAYOUT.fsdp_size(mesh)
    param_specs, param_dims = _param_geometry(mesh, abstract_params)
    blocks = param_block_names(abstract_params)
    for required in ("fnet", "cnet", "ScanRAFTStep_0"):
        if required not in blocks:
            raise ValueError(
                f"param tree is missing block {required!r} (have "
                f"{blocks}) — not a v1 RAFT tree")

    def body(params, batch_stats, im1, im2, flow_gt, valid):
        def loss_fn(p):
            preds = _halo_forward(
                cfg, p, batch_stats, im1, im2, n_seq=n_seq,
                n_fsdp=n_fsdp, param_dims=param_dims, iters=tc.iters,
                remat_mode=remat_mode, unroll=cfg.scan_unroll, emit=True)
            return _sequence_loss_sharded(preds, flow_gt, valid,
                                          tc.gamma, n_data, n_seq)

        (local_loss, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        # loss_fn returns the LOCAL loss contribution; the global loss
        # is its (data, seq) psum — taken HERE, outside value_and_grad,
        # so each device's grads are its own contribution exactly once
        # and the psum below assembles the true global gradient
        loss = jax.lax.psum(local_loss, (DATA_AXIS, SEQ_AXIS))
        grads = jax.tree.map(
            lambda g: jax.lax.psum(g, (DATA_AXIS, SEQ_AXIS)), grads)
        if n_fsdp > 1:
            grads = jax.tree.map(
                lambda g, d: g / n_fsdp if d >= 0 else g,
                grads, param_dims)
        return loss, metrics, grads

    bsc = LAYOUT.batch_spatial_compute()
    repl = LAYOUT.replicated()
    return shard_map(
        body, mesh=mesh,
        in_specs=(param_specs, repl, bsc, bsc, bsc, bsc),
        out_specs=(repl, repl, param_specs),
        check_rep=False)


def make_halo_eval_fn(cfg: RAFTConfig, mesh: Mesh, abstract_params,
                      iters: int = 24):
    """shard_map'd test-mode forward on (data, seq) slabs:

        (params, batch_stats, image1, image2, flow_init)
            -> (flow_low, flow_up)   # both row-sharded like the inputs

    flow_init is always materialized ((B, H/8, W/8, 2); zeros = cold
    start), mirroring the refine step's one-executable contract. The
    support matrix is the train one minus the train-only knobs — reuse
    check_halo_support with a neutral TrainConfig shell for the shared
    checks (variant/corr/precision/shape)."""
    from dexiraft_tpu.config import TrainConfig as _TC
    n_seq = LAYOUT.seq_size(mesh)
    shell = _TC(batch_size=LAYOUT.data_size(mesh),
                image_size=(8 * n_seq * 3, 64), freeze_bn=True)
    check_halo_support(cfg, shell, mesh)
    n_fsdp = LAYOUT.fsdp_size(mesh)
    param_specs, param_dims = _param_geometry(mesh, abstract_params)

    def body(params, batch_stats, im1, im2, flow_init):
        return _halo_forward(
            cfg, params, batch_stats, im1, im2, n_seq=n_seq,
            n_fsdp=n_fsdp, param_dims=param_dims, iters=iters,
            remat_mode="none", unroll=cfg.scan_unroll, emit=False,
            flow_init=flow_init)

    bsc = LAYOUT.batch_spatial_compute()
    repl = LAYOUT.replicated()
    return shard_map(
        body, mesh=mesh,
        in_specs=(param_specs, repl, bsc, bsc, bsc),
        out_specs=(bsc, bsc),
        check_rep=False)
