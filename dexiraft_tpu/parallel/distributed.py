"""Multi-host initialization.

The reference's parallelism is single-process DataParallel (SURVEY.md
§2.7) — it has no multi-node story at all. Here multi-host is the same
code path as single-host: call initialize() once per process before any
jax usage, build a mesh over jax.devices() (which enumerates EVERY chip
in the slice, all hosts), and the sharded train step's collectives ride
ICI; DCN only enters for multi-slice meshes.

Per-host data loading is already process-aware (Loader's
process_index/process_count slices the global batch), so no further
changes are needed for multi-host training.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """jax.distributed.initialize with env-var defaults; called by the
    train CLI before any jax usage.

    Modes:
      * explicit: coordinator_address given (arg or
        JAX_COORDINATOR_ADDRESS) + num_processes/process_id (args or
        JAX_NUM_PROCESSES / JAX_PROCESS_ID);
      * auto-bootstrap: JAX_AUTO_DISTRIBUTED=1 -> no-arg
        jax.distributed.initialize() (TPU pods self-discover);
      * otherwise: no-op (single process — one host owning all chips).
    """
    coordinator_address = (coordinator_address
                           or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if coordinator_address is None:
        if os.environ.get("JAX_AUTO_DISTRIBUTED") == "1":
            jax.distributed.initialize()
        return
    num_processes = num_processes or _env_int("JAX_NUM_PROCESSES")
    process_id = process_id if process_id is not None \
        else _env_int("JAX_PROCESS_ID")
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def _env_int(name: str) -> int:
    value = os.environ.get(name)
    if value is None:
        raise ValueError(
            f"multi-host init: coordinator address was given but {name} "
            "is not set (and no explicit argument was passed)")
    return int(value)
