"""Multi-host initialization.

The reference's parallelism is single-process DataParallel (SURVEY.md
§2.7) — it has no multi-node story at all. Here multi-host is the same
code path as single-host: call initialize() once per process before any
jax usage, build a mesh over jax.devices() (which enumerates EVERY chip
in the slice, all hosts), and the sharded train step's collectives ride
ICI; DCN only enters for multi-slice meshes.

Per-host data loading is already process-aware (Loader's
process_index/process_count slices the global batch), so no further
changes are needed for multi-host training.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """jax.distributed.initialize with env-var defaults; called by the
    train CLI before any jax usage.

    Modes:
      * explicit: coordinator_address given (arg or
        JAX_COORDINATOR_ADDRESS) + num_processes/process_id (args or
        JAX_NUM_PROCESSES / JAX_PROCESS_ID);
      * auto-bootstrap: JAX_AUTO_DISTRIBUTED=1 -> no-arg
        jax.distributed.initialize() (TPU pods self-discover);
      * otherwise: no-op (single process — one host owning all chips).
    """
    coordinator_address = (coordinator_address
                           or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if coordinator_address is None:
        if os.environ.get("JAX_AUTO_DISTRIBUTED") == "1":
            jax.distributed.initialize()
        return
    num_processes = num_processes or _env_int("JAX_NUM_PROCESSES")
    process_id = process_id if process_id is not None \
        else _env_int("JAX_PROCESS_ID")
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def _env_int(name: str) -> int:
    value = os.environ.get(name)
    if value is None:
        raise ValueError(
            f"multi-host init: coordinator address was given but {name} "
            "is not set (and no explicit argument was passed)")
    return int(value)


# --- elastic runtime lifecycle (resilience.membership) ---------------------
#
# jax.distributed.initialize is once-per-process by design: its client is
# constructed with the DEFAULT missed-heartbeat behavior (terminate the
# process when the coordination service reports ANY peer in error — see
# xla pjrt distributed client.h), and State.initialize refuses a second
# call. Elastic membership needs the opposite on both counts: a survivor
# must OUTLIVE a dead peer, then tear the whole runtime down and
# re-initialize at the new world size. The helpers below mirror
# jax._src.distributed.State.initialize/shutdown with three deliberate
# differences, each validated against this container's jax 0.4.37:
#
#   * service AND client heartbeats are relaxed to effectively-never
#     (max_missing_heartbeats ~ 1e5): the coordination service never
#     declares a silent peer dead, so it never propagates the fatal
#     error that the default client answers with process termination
#     (the custom missed_heartbeat_callback escape hatch is unusable
#     here — this jaxlib's binding cannot convert the absl::Status
#     argument and aborts with std::bad_cast). Liveness detection moves
#     wholesale to the KV-store leases the membership runtime owns,
#     where a missed lease is a catchable verdict, not a SIGABRT.
#   * the client is built with shutdown_on_destruction=False and a small
#     shutdown_timeout, so teardown against DEAD peers is bounded: the
#     explicit client.shutdown() below stops the client's error-polling
#     thread FIRST (shutting the service down under a live poller is the
#     other path to the fatal callback), fails its shutdown barrier
#     after shutdown_timeout at worst, and never hangs or aborts.
#   * teardown clears jax's backend caches (xla_bridge process_count /
#     local_devices lru_caches included — stale entries otherwise leak
#     the OLD world size into orbax's barrier participation decisions)
#     so the next elastic_initialize presents the new world to
#     jax.process_count()/jax.devices() consistently on every member.

_ELASTIC_HEARTBEAT_INTERVAL_S = 10
_ELASTIC_MAX_MISSING_HEARTBEATS = 100_000


def elastic_initialize(coordinator_address: str, num_processes: int,
                       process_id: int, *, start_service: bool,
                       init_timeout_s: int = 60,
                       shutdown_timeout_s: int = 5) -> None:
    """Install a survivable distributed runtime (see block comment).

    Safe to call repeatedly with elastic_teardown between calls — that
    pair is exactly one membership epoch transition. ``start_service``
    is True on the epoch's rank 0 (the coordinator host).
    """
    from jax._src import distributed
    from jaxlib import xla_extension

    st = distributed.global_state
    if st.client is not None:
        raise RuntimeError(
            "elastic_initialize: a distributed runtime is already "
            "installed — elastic_teardown() first (one epoch at a time)")
    if start_service:
        st.service = xla_extension.get_distributed_runtime_service(
            "[::]:" + coordinator_address.rsplit(":", 1)[1],
            int(num_processes),
            heartbeat_interval=_ELASTIC_HEARTBEAT_INTERVAL_S,
            max_missing_heartbeats=_ELASTIC_MAX_MISSING_HEARTBEATS)
    st.coordinator_address = coordinator_address
    st.num_processes = int(num_processes)
    st.process_id = int(process_id)
    client = xla_extension.get_distributed_runtime_client(
        coordinator_address, int(process_id),
        init_timeout=int(init_timeout_s),
        shutdown_timeout=int(shutdown_timeout_s),
        heartbeat_interval=_ELASTIC_HEARTBEAT_INTERVAL_S,
        max_missing_heartbeats=_ELASTIC_MAX_MISSING_HEARTBEATS,
        shutdown_on_destruction=False, use_compression=True)
    client.connect()
    st.client = client
    st.preemption_sync_manager = (
        xla_extension.create_preemption_sync_manager())
    st.preemption_sync_manager.initialize(client)
    # flight-recorder stamp: the connect above is itself a collective
    # rendezvous (every member of the new world must dial in), so the
    # (addr, size) digest is identical across the world
    from dexiraft_tpu.analysis import collective_trace

    collective_trace.record(
        "dexiraft/elastic", "elastic_initialize",
        digest=collective_trace.args_digest(coordinator_address,
                                            num_processes))


def elastic_teardown(graceful: bool = True) -> None:
    """Dismantle the current distributed runtime so a new epoch can
    initialize at a different size.

    graceful=False is the shrink path (peers are DEAD): the client
    shutdown still runs first — its barrier fails after the small
    shutdown_timeout, but the attempt stops the error-polling thread
    before the service goes away, which is what keeps a survivor
    alive — and every error is swallowed. Backend caches are refreshed
    either way; live arrays become invalid (the elastic contract:
    state is re-restored from the checkpoint after re-initialization).
    """
    import gc

    from jax._src import distributed

    from dexiraft_tpu.analysis import collective_trace

    collective_trace.record(
        "dexiraft/elastic", "elastic_teardown",
        digest=collective_trace.args_digest(bool(graceful)))
    st = distributed.global_state
    client, service = st.client, st.service
    st.client = None
    st.service = None
    st.preemption_sync_manager = None
    if client is not None:
        try:
            client.shutdown()
        except Exception as e:
            if graceful:
                print(f"[elastic] client shutdown: {type(e).__name__}: "
                      f"{str(e)[:120]}", flush=True)
    del client
    gc.collect()  # any backend-held client refs die before the service
    if service is not None:
        try:
            service.shutdown()
        except Exception as e:
            if graceful:
                print(f"[elastic] service shutdown: {type(e).__name__}: "
                      f"{str(e)[:120]}", flush=True)
    refresh_backend_world()


def refresh_backend_world() -> None:
    """Drop every cached view of the device world. jax rebuilds the
    backend from jax._src.distributed.global_state on next use, so after
    this the NEW world's process_count/process_index/devices are what
    every consumer (orbax's barrier participation above all) observes."""
    import jax as _jax
    from jax._src import xla_bridge

    xla_bridge._clear_backends()
    # process_count/local_devices carry their own lru_caches on top of
    # the backend cache — stale entries here are how an incumbent kept
    # reporting the OLD world size after re-initialization
    xla_bridge.process_count.cache_clear()
    xla_bridge.local_devices.cache_clear()
    _jax.clear_caches()
