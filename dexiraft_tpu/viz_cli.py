"""Flow visualization CLI (reference flowviz.py — batch .flo -> PNG).

  python -m dexiraft_tpu viz --input flows/ --output viz/
  python -m dexiraft_tpu viz --input a.flo b.flo --rad_max 40
"""

from __future__ import annotations

import argparse
import os
import os.path as osp
import sys
from glob import glob

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("dexiraft-viz")
    p.add_argument("--input", nargs="+", required=True,
                   help=".flo files or directories to scan recursively")
    p.add_argument("--output", default=None,
                   help="output dir (default: next to each input)")
    p.add_argument("--rad_max", type=float, default=None,
                   help="fixed magnitude normalization (consistent colors "
                        "across a sequence); default: per-frame max")
    return p


def main(argv=None) -> None:
    import imageio.v2 as imageio

    from dexiraft_tpu.data.flow_io import read_flo
    from dexiraft_tpu.eval.flow_viz import flow_to_image

    args = build_parser().parse_args(argv)
    files = []  # (path, output-relative name)
    for item in args.input:
        if osp.isdir(item):
            # keep subdirectory structure under --output: Sintel scenes
            # all name their frames frame_0001.flo etc., so flattening to
            # basenames would silently overwrite
            for f in sorted(glob(osp.join(item, "**", "*.flo"),
                                 recursive=True)):
                files.append((f, osp.relpath(f, item)))
        else:
            files.append((item, osp.basename(item)))
    if not files:
        raise SystemExit("no .flo files found")

    for f, rel in files:
        flow = read_flo(f)
        img = flow_to_image(np.asarray(flow), rad_max=args.rad_max)
        if args.output:
            out = osp.join(args.output, osp.splitext(rel)[0] + ".png")
            os.makedirs(osp.dirname(out) or ".", exist_ok=True)
        else:
            out = osp.splitext(f)[0] + ".png"
        imageio.imwrite(out, img)
        print(f"{f} -> {out}")


if __name__ == "__main__":
    main(sys.argv[1:])
