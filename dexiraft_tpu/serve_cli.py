"""Persistent flow-service CLI: a restored checkpoint behind HTTP.

  python -m dexiraft_tpu serve --model checkpoints/raft-things \
      --variant v5 --port 8000 --batch_size 4 --bucket_multiple 64

One process = one worker: restore (verified, PR 4 fallback path) ->
jitted eval step -> InferenceEngine -> SLO Scheduler -> ThreadingHTTP
endpoint (serve/server.py). ``--workers N`` scales out: N stateless
worker processes bind ONE port via SO_REUSEPORT (the kernel balances
accepts) and share the persistent XLA compile cache, so workers 2..N
skip the multi-minute compile the first worker paid — relaunch-speed
scale-out, the PR 2 cache's serving payoff. Session warm-start is a
single-worker (or sticky-LB) feature: kernel accept-balancing has no
affinity, so ``--workers > 1`` forces ``--session_ttl_s 0`` (stateless
mode) unless an external sticky router fronts the pool
(docs/serving.md).

SIGTERM drains: admitted requests finish and flush before exit
(PR 4's preemption discipline, service-shaped); a second signal aborts.

Session carry is DEVICE-RESIDENT by default (the splat result never
leaves the chip; --host_carry restores the PR 6 host round-trip), and
single-worker session-enabled replicas also serve chained video through
``POST /v1/flow/stream`` — the split-encoder streaming engine
(serve/video.py) with a byte-budgeted device carry
(--stream_sessions_mb; docs/serving.md "Streaming").
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys

from dexiraft_tpu.config import VARIANTS
from dexiraft_tpu.serve.engine import ServeConfig, add_engine_args


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("dexiraft-serve")
    p.add_argument("--model", default=None, help="orbax checkpoint dir "
                   "(restored via the verified-restore fallback path)")
    p.add_argument("--synthetic_init", action="store_true",
                   help="serve RANDOM-init weights instead of a "
                        "checkpoint — load/capacity benches and fleet "
                        "chaos tests exercise the full serving stack "
                        "without shipping a model around")
    p.add_argument("--variant", default="v1", choices=sorted(VARIANTS))
    p.add_argument("--small", action="store_true")
    p.add_argument("--mixed_precision", action="store_true")
    p.add_argument("--corr_impl", default="auto",
                   choices=["auto", "allpairs", "local", "pallas", "flash"],
                   help="'auto' (default) = the production config: "
                        "flash-blocked fused step on TPU (O(fmaps) "
                        "correlation memory at any geometry), allpairs "
                        "off-chip")
    p.add_argument("--corr_dtype", default="fp32",
                   choices=["fp32", "bf16", "int8"],
                   help="correlation-pyramid storage precision (bf16 "
                        "halves / int8 quarters per-request HBM traffic)")
    p.add_argument("--fused_update", action="store_true",
                   help="one fused Pallas lookup+update kernel per "
                        "refinement iteration (requires --corr_impl "
                        "flash or pallas)")
    p.add_argument("--scan_unroll", type=int, default=1)
    p.add_argument("--dexined_upconv", default="subpixel",
                   choices=["transpose", "subpixel"])
    p.add_argument("--iters", type=int, default=24,
                   help="refinement iterations per request (the budget "
                        "CAP with --adaptive)")
    p.add_argument("--adaptive", action="store_true",
                   help="adaptive-iteration inference: the refinement "
                        "while_loop freezes each item at convergence "
                        "(converge_tol) and the scheduler turns each "
                        "batch head's remaining SLO + queue pressure "
                        "into a per-dispatch iteration budget — "
                        "overload degrades refinement depth smoothly "
                        "before admission control sheds "
                        "(docs/serving.md \"Adaptive iterations\")")
    p.add_argument("--converge_tol", type=float, default=None,
                   help="override RAFTConfig.converge_tol (mean 1/8-res "
                        "flow-delta norm below which an item stops "
                        "refining; 0 disables the gate)")
    p.add_argument("--min_iters", type=int, default=4,
                   help="adaptive budget floor: no SLO/overload "
                        "pressure pushes a dispatch below this many "
                        "refinement iterations")
    p.add_argument("--mode", default="sintel", choices=["sintel", "kitti"],
                   help="pad placement for bucket padding")
    # engine knobs — the ONE shared surface with eval_cli/serve_bench
    # (ServeConfig.from_args); serving defaults raise batch + bucket
    # granule because bounded executables are the point of a service
    add_engine_args(p, batch_size=4, bucket_multiple=64)
    p.add_argument("--data_parallel", type=int, default=0,
                   help="shard each inference batch over this many chips "
                        "(0 = single chip); batch_size must divide by it")
    # service knobs
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--slo_ms", type=float, default=200.0,
                   help="per-request latency budget: a partial batch "
                        "dispatches when the oldest queued request's "
                        "budget (minus the bucket's learned service "
                        "time) runs out")
    p.add_argument("--max_queue", type=int, default=64,
                   help="queued-request admission bound; past it the "
                        "service sheds load with 503 instead of "
                        "stretching everyone's latency")
    p.add_argument("--session_ttl_s", type=float, default=60.0,
                   help="session warm-start TTL; 0 disables sessions "
                        "(stateless mode, forced when --workers > 1)")
    p.add_argument("--host_carry", action="store_true",
                   help="keep the PR 6 host-numpy session carry "
                        "(device_get per response + H2D per warm "
                        "request) instead of the device-resident "
                        "handoff; for pools/externally-restarted "
                        "workers that cannot share device state. "
                        "Implied by --workers > 1 and --data_parallel")
    p.add_argument("--stream_sessions_mb", type=float, default=256.0,
                   help="HBM byte budget for the streaming tier's "
                        "device-resident feature carries (POST "
                        "/v1/flow/stream; LRU-evicted past it, counted "
                        "in /stats). 0 disables the streaming endpoint")
    p.add_argument("--stream_chunk_frames", type=int, default=64,
                   help="max frames per /v1/flow/stream chunk (400 past "
                        "it): one chunk holds the streaming engine for "
                        "its whole frame loop, so the cap bounds how "
                        "long one request can starve other streams")
    p.add_argument("--request_timeout_s", type=float, default=60.0,
                   help="per-request server-side wait bound (504 past it)")
    p.add_argument("--workers", type=int, default=1,
                   help="worker processes sharing one SO_REUSEPORT port "
                        "and one persistent compile cache")
    p.add_argument("--warmup", default=None,
                   help="comma-separated HxW geometries to pre-compile "
                        "before accepting traffic (e.g. 440x1024,368x768)")
    p.add_argument("--compile_cache_dir", default=None,
                   help="persistent XLA cache dir (default: the repo "
                        "cache; workers share it for fast scale-out)")
    p.add_argument("--no_compile_cache", action="store_true")
    p.add_argument("--strict", action="store_true",
                   help="PR 5 drift watch with teeth: a recompile on an "
                        "already-compiled bucket signature raises "
                        "instead of the one-line warning")
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend (local shakeout)")
    p.add_argument("--reuse_port", action="store_true",
                   help=argparse.SUPPRESS)  # set by the --workers parent
    return p


# ---- multi-worker pool --------------------------------------------------


def _run_pool(args, argv) -> None:
    """Spawn N single-worker children on one SO_REUSEPORT port; forward
    SIGTERM/SIGINT so every child drains; exit with the worst child rc.
    Children are STATELESS (sessions off): kernel accept-balancing has
    no affinity, so carry state would be wrong half the time."""
    if args.port == 0:
        raise SystemExit("serve: --workers > 1 needs an explicit --port "
                         "(ephemeral port 0 would scatter the workers)")
    # appended flags override the parent's own --workers/--session_ttl_s
    # (argparse: the last occurrence of a store option wins)
    child_argv = list(argv) + ["--workers", "1", "--reuse_port",
                               "--session_ttl_s", "0"]
    children = []
    for i in range(args.workers):
        env = dict(os.environ, DEXIRAFT_SERVE_WORKER=str(i))
        # own session: a foreground ^C delivers SIGINT to the whole
        # terminal process group, and _forward would deliver it AGAIN —
        # two signals is the children's abort gesture, not a drain.
        # Detached, every signal a child sees comes through _forward,
        # exactly once.
        children.append(subprocess.Popen(
            [sys.executable, "-m", "dexiraft_tpu", "serve"] + child_argv,
            env=env, start_new_session=True))
    print(f"[serve] pool: {args.workers} workers on "
          f"{args.host}:{args.port} (SO_REUSEPORT), shared compile cache, "
          f"stateless sessions", flush=True)

    def _forward(signum, frame):
        for c in children:
            try:
                c.send_signal(signum)
            except OSError:
                pass

    for s in (signal.SIGTERM, signal.SIGINT):
        signal.signal(s, _forward)
    rc = 0
    for c in children:
        try:
            rc = max(rc, abs(c.wait()))
        except KeyboardInterrupt:
            _forward(signal.SIGINT, None)
            rc = max(rc, abs(c.wait()))
    raise SystemExit(rc)


# ---- single worker ------------------------------------------------------


def _load(args):
    """Verified restore (PR 4): the newest checkpoint step that passes
    integrity checks serves; truncated/poisoned steps are skipped (and
    deleted) loudly instead of crashing the worker at boot.
    --synthetic_init skips the restore entirely (random weights): the
    fleet bench/chaos replicas measure the serving stack, not EPE."""
    import jax

    from dexiraft_tpu.config import TrainConfig
    from dexiraft_tpu.resilience import restore_verified
    from dexiraft_tpu.train import checkpoint as ckpt
    from dexiraft_tpu.train.state import create_state

    from dexiraft_tpu.config import resolve_corr_impl_args

    impl, fused = resolve_corr_impl_args(args, jax.devices()[0].platform,
                                         "serve")
    cfg = VARIANTS[args.variant](small=args.small,
                                 mixed_precision=args.mixed_precision,
                                 corr_impl=impl,
                                 corr_dtype=args.corr_dtype,
                                 fused_update=fused,
                                 dexined_upconv=args.dexined_upconv,
                                 scan_unroll=args.scan_unroll)
    if getattr(args, "converge_tol", None) is not None:
        import dataclasses

        # checkpoint-compatible: the gate threshold shapes no params
        cfg = dataclasses.replace(cfg, converge_tol=args.converge_tol)
    if args.synthetic_init:
        state = create_state(jax.random.PRNGKey(0), cfg, TrainConfig())
        print("[serve] synthetic init: serving RANDOM weights "
              "(bench/chaos mode — flow quality is meaningless)",
              flush=True)
        return cfg, state.variables
    try:
        ckpt.require_checkpoints(args.model)
    except FileNotFoundError as e:
        raise SystemExit(f"serve: {e}")
    template = create_state(jax.random.PRNGKey(0), cfg, TrainConfig())
    state, step = restore_verified(args.model, template)
    # the server never saves: release orbax's per-manager machinery now
    # instead of carrying it for the life of the process
    ckpt.close_managers()
    print(f"[serve] restored verified checkpoint step {step} from "
          f"{args.model}", flush=True)
    return cfg, state.variables


def _make_carry_fn(device: bool = True):
    """Session carry = the submission loop's splat: the previous frame's
    low-res flow forward-interpolated to the next frame's grid.

    device=True (the default): the splat result STAYS a device array —
    the store holds it, the engine stacks it into the next warm batch on
    device, and the carry path moves zero host<->device bytes per frame
    (engine.stats carry_h2d/d2h_bytes pin it). device=False keeps the
    PR 6 host round-trip (explicit device_get here, H2D on the next
    request) for deployments whose workers cannot share device state
    (--host_carry, pools, the data-parallel mesh path)."""
    import jax

    from dexiraft_tpu.eval.interpolate import forward_interpolate

    if device:
        return forward_interpolate
    return lambda flow_low: jax.device_get(forward_interpolate(flow_low))


def _warmup(engine, geometries, carry_fn=None, video=None) -> None:
    """Pre-compile the named buckets before the listener opens: the
    first real request on a cold bucket would otherwise eat the compile
    inside its latency budget. With sessions on, the engine always
    materializes flow_init (warm_start=True), so one signature per
    bucket covers cold AND warm traffic — and the carry splat
    (forward_interpolate, jitted per bucket shape) compiles here too,
    so --strict serving is compile-flat from the first request. With
    streaming enabled the video engine warms the same geometries (its
    encode/refine/splat signatures), extending the compile-flat
    guarantee to /v1/flow/stream."""
    import numpy as np

    for geom in geometries:
        h, w = (int(v) for v in geom.split("x"))
        item = {"image1": np.zeros((h, w, 3), np.float32),
                "image2": np.zeros((h, w, 3), np.float32)}
        (res,) = engine.run_batch([item])
        if carry_fn is not None:
            carry_fn(res.flow_low)
            engine.watch.mark_warm()  # expected compile, not drift
        if engine.config.adaptive:
            # the budget is a TRACED int32 scalar: a second dispatch at
            # a different explicit budget (plus the iters_used/delta
            # fetch it exercises) must ride the executable the first
            # dispatch compiled. check() turns any accidental budget
            # re-specialization into a boot-time error instead of a
            # first-request 500 under --strict.
            (res2,) = engine.run_batch([item], iter_budget=1)
            engine.watch.check()
            if res2.iters_used is None:
                raise RuntimeError(
                    "adaptive engine returned no iters_used during "
                    "warmup — eval_fn is not the adaptive 4-tuple "
                    "contract (make_eval_step(adaptive=True))")
    if video is not None:
        video.warmup(geometries)
    engine.reset_stats()  # warmup is not traffic


def _make_video_engine(args, cfg, variables, mesh, sessions_on,
                       watch=None):
    """The streaming tier (serve/video.py), or None with a printed why.

    Streaming needs sessions (the carry IS the feature), a budget, a
    single-chip step (the chunk loop is batch-1 serially dependent —
    sharding one frame over a data mesh is the wrong axis), and a
    variant whose edges don't come from the dataset (v2/v3 without
    embed_dexined would need per-frame edge images on the wire)."""
    why = None
    if args.stream_sessions_mb <= 0:
        why = "--stream_sessions_mb 0"
    elif not sessions_on:
        why = "sessions off (the carry needs a session store)"
    elif mesh is not None:
        why = "--data_parallel (batch-1 chunks do not shard)"
    elif cfg.variant in ("early", "separate") and not cfg.embed_dexined:
        why = (f"variant {cfg.variant!r} needs data-supplied edge "
               "frames the stream wire format does not carry")
    if why is not None:
        print(f"[serve] streaming endpoint disabled: {why}", flush=True)
        return None

    import jax

    from dexiraft_tpu.eval.interpolate import forward_interpolate
    from dexiraft_tpu.serve.sessions import DeviceSessionStore
    from dexiraft_tpu.serve.video import VideoEngine
    from dexiraft_tpu.train.step import make_encode_step, make_refine_step

    import numpy as np

    encode_step = make_encode_step(cfg)
    adaptive = getattr(args, "adaptive", False)
    refine_step = make_refine_step(cfg, iters=args.iters,
                                   adaptive=adaptive)
    if adaptive:
        # streaming rides the FULL budget (chunks bypass the
        # scheduler's SLO policy); the convergence gate still exits
        # early per pair. One np.int32 aval = one executable per bucket.
        full = np.int32(args.iters)
        refine_fn = (lambda f1, f2, fi:
                     refine_step(variables, f1, f2, fi, full))
    else:
        refine_fn = lambda f1, f2, fi: refine_step(variables, f1, f2, fi)
    # the splat stays on device: flow_low (1, h/8, w/8, 2) -> the next
    # pair's seed, one jitted executable per bucket shape (warmup
    # absorbs the compile)
    splat = jax.jit(lambda low: forward_interpolate(low[0])[None])
    store = DeviceSessionStore(
        budget_bytes=int(args.stream_sessions_mb * 2**20),
        ttl_s=args.session_ttl_s,
        max_sessions=1024)
    return VideoEngine(
        lambda frame: encode_step(variables, frame),
        refine_fn,
        splat,
        sessions=store,
        put=jax.device_put,
        mode=args.mode,
        bucket_multiple=args.bucket_multiple,
        max_chunk_frames=args.stream_chunk_frames,
        adaptive=adaptive,
        strict=args.strict,
        # ONE RecompileWatch with the pair engine: the backend compile
        # counter is process-global, so a separate watch would let a
        # cold streaming bucket's expected compile read as drift to the
        # pair dispatcher's --strict check (and vice versa)
        watch=watch)


def _serve_one(args) -> None:
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    if args.strict:
        # --strict arms BOTH runtime sentinels: the recompile watch
        # (engine/video strict checks) and the lock-order runtime —
        # a rank inversion or ABBA cycle in the serve thread fabric
        # raises at the offending acquisition instead of warning
        from dexiraft_tpu.analysis import locks

        locks.set_strict(True)
    if not args.no_compile_cache:
        from dexiraft_tpu.profiling import enable_persistent_cache

        cache = enable_persistent_cache(args.compile_cache_dir)
        print(f"[serve] compile cache: {cache}", flush=True)

    cfg, variables = _load(args)

    # one resident copy of the weights: the pair eval step and the
    # streaming encode/refine steps all close over THIS device tree
    # (device_put inside _make_eval_fn is a no-op on it)
    variables = jax.device_put(variables)

    from dexiraft_tpu.eval_cli import _make_eval_fn
    from dexiraft_tpu.serve import InferenceEngine
    from dexiraft_tpu.serve.server import FlowService

    eval_fn, mesh = _make_eval_fn(args, cfg, variables, args.iters)
    sessions_on = args.session_ttl_s > 0
    # device-resident carry is the default; the host round-trip stays
    # behind --host_carry (and is forced on the data-parallel mesh path,
    # whose pinned in_shardings re-lay the batch out host-side anyway)
    device_carry = sessions_on and not args.host_carry and mesh is None
    engine = InferenceEngine(
        eval_fn,
        ServeConfig.from_args(args, mode=args.mode, warm_start=sessions_on,
                              device_carry=device_carry),
        mesh=mesh)
    carry_fn = (_make_carry_fn(device=device_carry)
                if sessions_on else None)
    video = _make_video_engine(args, cfg, variables, mesh, sessions_on,
                               watch=engine.watch)
    if args.warmup:
        _warmup(engine, args.warmup.split(","), carry_fn, video)
        print(f"[serve] warmup: compiled "
              f"{engine.registry.compiles} signature(s)"
              f"{' (+streaming)' if video is not None else ''}",
              flush=True)

    service = FlowService(
        engine,
        host=args.host, port=args.port,
        slo_ms=args.slo_ms, max_queue=args.max_queue,
        # adaptive defaults from engine.config; the scheduler clamps
        # every SLO/overload budget to [min_iters, iters]
        max_iters=args.iters, min_iters=args.min_iters,
        session_ttl_s=args.session_ttl_s,
        carry_fn=carry_fn,
        request_timeout_s=args.request_timeout_s,
        reuse_port=args.reuse_port,
        video=video)
    service.install_signal_handlers()
    service.start()
    worker = os.environ.get("DEXIRAFT_SERVE_WORKER")
    tag = f" (worker {worker})" if worker is not None else ""
    print(f"[serve] listening on {service.url}{tag} — "
          f"batch_size={args.batch_size} slo_ms={args.slo_ms:g} "
          f"sessions={'on' if sessions_on else 'off'} "
          f"strict={'on' if args.strict else 'off'}"
          + (f" adaptive=on (tol={cfg.converge_tol:g}, "
             f"iters {args.min_iters}..{args.iters})"
             if args.adaptive else ""), flush=True)

    try:
        while not service.stopped.wait(1.0):
            pass
    except KeyboardInterrupt:
        # second signal (or bare ^C before the handler ran): best-effort
        # fast drain, then leave
        service.drain_and_stop(timeout=5.0)
    sched = service.scheduler.stats
    print(f"[serve] stopped after {service.uptime_s():.1f}s — "
          f"{sched.completed} served, {sched.rejected} shed, "
          f"{sched.failed} failed; {engine.stats.summary()}", flush=True)


def main(argv=None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    args = build_parser().parse_args(argv)
    if bool(args.model) == bool(args.synthetic_init):
        raise SystemExit("serve: exactly one of --model or "
                         "--synthetic_init is required")
    if args.workers < 1:
        raise SystemExit(f"serve: --workers must be >= 1, got "
                         f"{args.workers}")
    if args.workers > 1:
        if args.session_ttl_s > 0:
            # the PR 6 affinity gap, made loud: SO_REUSEPORT pools give
            # sessions no home — the kernel balances accepts blindly,
            # so a stream's warm carry lands on the wrong worker half
            # the time. The router (python -m dexiraft_tpu router) is
            # the sanctioned multi-replica path for session traffic.
            print("[serve] WARNING: --workers > 1 has NO session "
                  "affinity (SO_REUSEPORT accept-balancing is blind); "
                  "sessions are forced OFF in the pool. For warm-start "
                  "at scale, front single-worker replicas with "
                  "`python -m dexiraft_tpu router` (docs/serving.md "
                  "\"Fleet\").", flush=True)
        _run_pool(args, argv)
    else:
        _serve_one(args)


if __name__ == "__main__":
    main(sys.argv[1:])
