"""Configuration tree for the framework.

One resolved, immutable config replaces the reference's three independent
argparse blocks plus the args-namespace mutation inside RAFT.__init__
(core/raft.py:37-53) — configs here are frozen dataclasses, resolved once.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

# storage precisions for the correlation volume / fmap2 pyramid
# (ops/quant.py implements them; lives here jax-free so CLI parser
# construction — including `serve --help` — doesn't pay the jax import).
# int8 is an inference format: its round() kills fmap gradients, so the
# model refuses to train with it (models/raft.py).
CORR_DTYPES = ("fp32", "bf16", "int8")

# correlation implementations: the materialized MXU volume, the XLA
# on-demand path, the per-pixel Pallas kernel, and the flash-blocked
# Pallas kernel (fmap2 streamed from HBM in row blocks — O(fmaps)
# memory at any geometry; ops/pallas_corr.py). Jax-free for the same
# CLI-parser reason as CORR_DTYPES.
CORR_IMPLS = ("allpairs", "local", "pallas", "flash")


def resolve_corr_impl(impl: str, platform: str) -> Tuple[str, bool]:
    """Resolve an eval/serve CLI ``--corr_impl`` value to a concrete
    (corr_impl, fused_update) pair.

    "auto" is the production default: on TPU it resolves to the
    flash-blocked fused step (corr_impl="flash", fused_update=True) —
    the O(fmaps)-memory configuration that unlocks 1080p+ and
    constant-memory video (docs/perf.md "Correlation memory &
    precision"). Off-TPU it falls back to the materialized volume:
    Pallas kernels only run off-chip in interpreter mode, which is
    debug-speed, not serving-speed. Explicit values pass through with
    fused_update=False (the CLI's --fused_update flag overrides).
    """
    if impl == "auto":
        return ("flash", True) if platform == "tpu" else ("allpairs", False)
    return impl, False


def resolve_corr_impl_args(args, platform: str, label: str) -> Tuple[str, bool]:
    """The eval/serve CLI glue around :func:`resolve_corr_impl`: merge
    the --fused_update flag into the resolution, refuse fused on a
    non-kernel impl with a one-line actionable error, and announce what
    "auto" resolved to. ONE copy so the two CLIs cannot drift."""
    impl, fused_auto = resolve_corr_impl(args.corr_impl, platform)
    fused = args.fused_update or fused_auto
    if fused and impl not in ("pallas", "flash"):
        raise SystemExit(f"{label}: --fused_update requires --corr_impl "
                         "flash or pallas (pass one explicitly — 'auto' "
                         "resolves to allpairs off-TPU)")
    if args.corr_impl == "auto":
        print(f"[{label}] corr_impl auto -> {impl}"
              f"{' + fused_update' if fused else ''}", flush=True)
    return impl, fused


@dataclasses.dataclass(frozen=True)
class RAFTConfig:
    """Architecture config covering the reference's five experiment variants
    (SURVEY.md §2.5):

      v1  variant='raft'                       vanilla RAFT, image stream only
      v2  variant='early'                      6-ch early fusion (image ⊕ edge image from data)
      v3  variant='separate'                   dual stream, edges from data, decoupled
                                               updates + RefineFlow fusion
      v4  variant='early',  embed_dexined=True 10-ch early fusion (image ⊕ 7 DexiNed logit maps)
      v5  variant='dual',   embed_dexined=True dual stream w/ embedded frozen DexiNed,
                                               shared update block, coupled Δf+Δef update
    """

    variant: str = "raft"  # raft | early | separate | dual
    small: bool = False
    embed_dexined: bool = False
    corr_levels: int = 4
    corr_radius: Optional[int] = None  # None -> 4 full / 3 small (core/raft.py:37-47)
    dropout: float = 0.0
    mixed_precision: bool = False  # bf16 compute in encoders/update; corr stays fp32
    # allpairs = materialized MXU volume; local/pallas/flash = on-demand
    # paths (flash is the blocked HBM-streaming kernel — the production
    # eval/serve default on TPU via resolve_corr_impl("auto", ...))
    corr_impl: str = "allpairs"
    # STORAGE precision of the correlation pyramid (allpairs: the
    # materialized volume levels; local/pallas: the fmap2 pyramid the
    # lookup streams) — "fp32" | "bf16" | "int8" (per-level scale,
    # dequantized inside the consuming matmul/kernel, ops/quant.py).
    # Correlation math stays fp32-accumulated on every path; this knob
    # only changes the HBM bytes each refinement iteration moves. int8
    # is inference-only (gradients to the quantized operand are dead)
    corr_dtype: str = "fp32"
    # fuse each refinement iteration's 4-level window lookup WITH the
    # motion encoder's 1x1 corr conv into ONE Pallas kernel
    # (ops/pallas_corr.pallas_fused_step / flash_fused_step): the
    # (2r+1)^2-per-level corr features never round-trip HBM — only the
    # conv's F-channel output does. Requires corr_impl="pallas" or
    # "flash" (the VMEM-kernel formulations); parameter tree is
    # IDENTICAL to the unfused path, so checkpoints interchange
    # (models/update.py FusedCorrEncoder)
    fused_update: bool = False
    # rows per chunk for the local path's gather (bounds the transient
    # patch buffer to rows*W*(2r+2)^2*C floats; None = whole frame at once)
    corr_row_chunk: Optional[int] = 8
    # rematerialize each refinement iteration in the backward pass:
    # activations of the scanned step are recomputed instead of stored,
    # trading FLOPs for HBM (jax.checkpoint over the scan body)
    remat: bool = False
    # what the per-iteration checkpoint SAVES when remat=True:
    #   "full"          — save nothing, recompute everything (the
    #                     historical behavior; max HBM savings)
    #   "dots_saveable" — save matmul/conv outputs, recompute the cheap
    #                     elementwise chains (jax.checkpoint_policies.
    #                     dots_saveable): most of the memory win at a
    #                     fraction of the recompute FLOPs — the middle
    #                     point the train_bench HBM columns quantify
    remat_policy: str = "full"
    # rematerialize ONLY the correlation lookup: drops the per-iteration
    # one-hot hat matrices — the dominant training-memory term (measured
    # 5x1.57 GB with up to 15x lane padding at batch 6, 368x496; see
    # docs/perf.md) — at a fraction of full remat's recompute cost.
    # Numerically identical; composes with (and is implied by) remat
    remat_lookup: bool = False
    # transposed-conv implementation inside the embedded DexiNed's
    # upsamplers: "transpose" (lax.conv_transpose) or "subpixel" (the
    # numerically identical phase-decomposed form — dense half-res convs
    # instead of an input-dilated full-res conv; see models/dexined.py).
    # Default flipped to "subpixel" after the on-chip A/B: end-to-end v5
    # forward at 440x1024 dropped 175.9 -> 100.0 ms (allpairs path),
    # prelude ~104 -> ~26 ms (logs/tpu_queue_r4/bench_record.log).
    dexined_upconv: str = "subpixel"
    # unroll factor for the refinement-loop scan (lax.scan unroll): >1
    # lets XLA software-pipeline consecutive iterations (fuse the next
    # lookup's hat-matrix build with the current GRU) at the cost of
    # code-size/compile time. Numerically identical; eval-latency knob
    scan_unroll: int = 1
    # convergence gate for the ADAPTIVE inference path (models/raft.py
    # adaptive=True): an item freezes once the mean per-pixel L2 norm of
    # its 1/8-res flow delta drops below this. 0.0 disables the gate
    # (the norm is >= 0, so `norm < 0` never fires) — the while_loop
    # then runs exactly `iter_budget` iterations and is bit-exact with
    # the fixed scan at the same count (pinned in tests). The default
    # is the EPE-vs-latency frontier point measured in docs/perf.md:
    # within 0.05 px of fixed-32 at >= 25% fewer mean iterations
    converge_tol: float = 0.02

    def __post_init__(self):
        # config-time refusals (ISSUE 12 satellite): an unknown
        # corr_impl / corr_dtype / fused_update combination fails HERE,
        # at construction, not as a store_corr ValueError deep inside
        # build_local_corr mid-trace. Runtime-dependent checks (int8
        # under train=True) stay in models/raft.py.
        if self.corr_impl not in CORR_IMPLS:
            raise ValueError(
                f"unknown corr_impl {self.corr_impl!r}; expected one of "
                f"{CORR_IMPLS}")
        if self.corr_dtype not in CORR_DTYPES:
            raise ValueError(
                f"unknown corr_dtype {self.corr_dtype!r}; expected one "
                f"of {CORR_DTYPES}")
        if self.fused_update and self.corr_impl not in ("pallas", "flash"):
            raise ValueError(
                "fused_update=True requires corr_impl='flash' (the "
                "blocked HBM-streaming kernel — the production default) "
                "or 'pallas' (the per-pixel VMEM formulation); the "
                "allpairs volume cannot be tiled per pixel block")
        if self.remat_policy not in ("full", "dots_saveable"):
            raise ValueError(
                f"unknown remat_policy {self.remat_policy!r}; expected "
                "'full' or 'dots_saveable'")
        if self.converge_tol < 0:
            raise ValueError(
                f"converge_tol must be >= 0 (a flow-delta NORM threshold; "
                f"0 disables the gate), got {self.converge_tol}")

    @property
    def radius(self) -> int:
        return self.corr_radius if self.corr_radius is not None else (3 if self.small else 4)

    @property
    def hidden_dim(self) -> int:
        return 96 if self.small else 128

    @property
    def context_dim(self) -> int:
        return 64 if self.small else 128

    @property
    def fnet_dim(self) -> int:
        return 128 if self.small else 256

    @property
    def corr_planes(self) -> int:
        return self.corr_levels * (2 * self.radius + 1) ** 2

    @property
    def image_channels(self) -> int:
        if self.variant == "early":
            return 10 if self.embed_dexined else 6
        return 3

    @property
    def has_edge_stream(self) -> bool:
        return self.variant in ("separate", "dual")


def raft_v1(**kw) -> RAFTConfig:
    return RAFTConfig(variant="raft", **kw)


def raft_v2(**kw) -> RAFTConfig:
    return RAFTConfig(variant="early", embed_dexined=False, **kw)


def raft_v3(**kw) -> RAFTConfig:
    return RAFTConfig(variant="separate", **kw)


def raft_v4(**kw) -> RAFTConfig:
    return RAFTConfig(variant="early", embed_dexined=True, **kw)


def raft_v5(**kw) -> RAFTConfig:
    return RAFTConfig(variant="dual", embed_dexined=True, **kw)


# experiment-variant name -> constructor: the --variant surface shared by
# the train/eval/serve CLIs. Lives here (jax-free) so parser construction
# — including `serve --help` and the --workers pool parent, which never
# run the model — doesn't pay the jax import.
VARIANTS = {
    "v1": raft_v1, "raft": raft_v1,
    "v2": raft_v2, "early": raft_v2,
    "v3": raft_v3, "separate": raft_v3,
    "v4": raft_v4,
    "v5": raft_v5, "dual": raft_v5,
}


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """One training stage. Presets mirror train_standard.sh / train_mixed.sh."""

    name: str = "raft"
    stage: str = "chairs"  # chairs | things | sintel | kitti
    lr: float = 4e-4
    num_steps: int = 100_000
    batch_size: int = 10
    image_size: Tuple[int, int] = (368, 496)
    wdecay: float = 1e-4
    epsilon: float = 1e-8
    clip: float = 1.0
    gamma: float = 0.8
    iters: int = 12
    add_noise: bool = False
    # training precision policy: "fp32", or "bf16" — the step forces the
    # model's mixed-precision path (bf16 module compute; flax casts each
    # op's params from the fp32 MASTER weights, so gradients land fp32)
    # while loss, metrics, BN running stats, and optimizer math stay
    # fp32. The model's own mixed-precision contract keeps the corr
    # volume fp32. No loss scaling needed: bf16 keeps fp32's exponent
    # range
    precision: str = "fp32"
    # gradient accumulation: the step's batch leading dim is
    # (accum_steps * microbatch) and a lax.scan inside the ONE jitted
    # step runs the microbatches sequentially, averaging gradients —
    # large effective batches on one chip, compiled once. 1 = off
    accum_steps: int = 1
    # device-side prefetch depth: batches device_put ahead of the step
    # consuming them (data.prefetch.DevicePrefetcher); 2 = classic
    # double buffering. 0 disables the prefetcher entirely
    prefetch_depth: int = 2
    # v1-lineage fusion (alt/train_1.py:173-176): run the SAME model on
    # (image1, image2) and on the edge-image pair, and sum the per-iter
    # flow predictions before the sequence loss; requires edge-pair data
    edge_sum_fusion: bool = False
    # rematerialization policy axis for the TRAIN step (the bench's
    # --remat knob): "none" stores every refinement iteration's
    # activations; "per_iter" checkpoints each scanned iteration and
    # recomputes everything in the backward (cfg.remat with
    # remat_policy="full"); "dots_saveable" checkpoints each iteration
    # but SAVES matmul/conv outputs (jax.checkpoint_policies
    # .dots_saveable) — most of per_iter's HBM win at a fraction of its
    # recompute FLOPs. Numerically identical on all three settings
    remat: str = "none"
    freeze_bn: bool = False  # true for all post-chairs stages (train.py:149-150)
    val_freq: int = 5000
    sum_freq: int = 100
    seed: int = 1234
    validation: Tuple[str, ...] = ()


# The 4-stage curriculum, standard recipe (train_standard.sh:3-6).
STANDARD_STAGES = (
    TrainConfig(name="raft-chairs", stage="chairs", validation=("chairs",), num_steps=100_000,
                batch_size=10, lr=4e-4, image_size=(368, 496), wdecay=1e-4),
    TrainConfig(name="raft-things", stage="things", validation=("sintel",), num_steps=100_000,
                batch_size=6, lr=1.25e-4, image_size=(400, 720), wdecay=1e-4, freeze_bn=True),
    TrainConfig(name="raft-sintel", stage="sintel", validation=("sintel",), num_steps=100_000,
                batch_size=6, lr=1.25e-4, image_size=(368, 768), wdecay=1e-5, gamma=0.85,
                freeze_bn=True),
    TrainConfig(name="raft-kitti", stage="kitti", validation=("kitti",), num_steps=50_000,
                batch_size=6, lr=1e-4, image_size=(288, 960), wdecay=1e-5, gamma=0.85,
                freeze_bn=True),
)

# Mixed-precision single-chip recipe (train_mixed.sh:3-6).
MIXED_STAGES = (
    TrainConfig(name="raft-chairs", stage="chairs", validation=("chairs",), num_steps=120_000,
                batch_size=8, lr=2.5e-4, image_size=(368, 496), wdecay=1e-4),
    TrainConfig(name="raft-things", stage="things", validation=("sintel",), num_steps=120_000,
                batch_size=5, lr=1e-4, image_size=(400, 720), wdecay=1e-4, freeze_bn=True),
    TrainConfig(name="raft-sintel", stage="sintel", validation=("sintel",), num_steps=120_000,
                batch_size=5, lr=1e-4, image_size=(368, 768), wdecay=1e-5, gamma=0.85,
                freeze_bn=True),
    TrainConfig(name="raft-kitti", stage="kitti", validation=("kitti",), num_steps=50_000,
                batch_size=5, lr=1e-4, image_size=(288, 960), wdecay=1e-5, gamma=0.85,
                freeze_bn=True),
)
