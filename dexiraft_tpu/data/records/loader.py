"""RecordLoader: the packed-record implementation of the Loader contract.

Identical batch semantics to ``data.loader.Loader`` — the exact
``batches(start_epoch=, start_offset=)`` stream, the epoch-seeded
``epoch_permutation`` global shuffle, the static ``(host_id, num_hosts)``
partition of every global batch, augmentation in the worker pool, and
PR 4's bounded retry/skip/count fault discipline — but ``_load_raw`` is
an O(1) indexed shard read instead of a raw-file decode. The two
loaders produce the identical batch stream for the same stage and seed
(pinned by test), so FRESH runs can pick either path freely — but a
mid-trajectory --resume never swaps planes: the stream sidecar's
``loader_kind`` + pack-fingerprint fields refuse the swap loudly, by
design (resilience.stream.LoaderKindMismatch).

What records adds on top of the base loader is visibility:
``RecordPipelineStats`` extends PipelineStats with ``records/*``
counters — reads that succeeded and CRC/framing failures — so a pack
quietly rotting on disk shows up in the training log's pipeline line,
not just as mysterious retries.
"""

from __future__ import annotations

from typing import Dict, Union

from dexiraft_tpu.data.loader import Loader, PipelineStats
from dexiraft_tpu.data.records.dataset import open_records
from dexiraft_tpu.data.records.format import RecordCorruptError


class RecordPipelineStats(PipelineStats):
    """PipelineStats + the record plane's own fault/health counters."""

    def reset(self) -> None:
        super().reset()
        self.record_reads = 0         # samples served from shards
        self.record_crc_failures = 0  # CRC/framing violations observed
                                      # (each also charges one retry)

    def as_dict(self) -> Dict[str, int]:
        d = super().as_dict()
        d["records/reads"] = self.record_reads
        d["records/crc_failures"] = self.record_crc_failures
        return d

    def summary(self) -> str:
        base = super().summary()
        if not self.record_crc_failures:
            return base
        return (f"{base}; {self.record_crc_failures} record CRC "
                f"failure(s) over {self.record_reads} record reads")


class RecordLoader(Loader):
    """Loader over a packed-records directory (or an already-open
    record dataset from ``open_records``)."""

    def __init__(self, records: Union[str, object], batch_size: int,
                 **loader_kwargs):
        if isinstance(records, str):
            records = open_records(records)
        if not hasattr(records, "manifest"):
            raise TypeError(
                "RecordLoader needs a records directory path or a dataset "
                "from open_records(); for raw-file datasets use Loader")
        super().__init__(records, batch_size, **loader_kwargs)
        self.manifest = records.manifest
        self.stats = RecordPipelineStats()

    def _note_decode_ok(self) -> None:
        self.stats.record_reads += 1

    def _note_decode_error(self, exc: BaseException) -> None:
        if isinstance(exc, RecordCorruptError):
            self.stats.record_crc_failures += 1
