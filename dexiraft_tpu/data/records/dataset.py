"""Record-backed datasets: the packed mirror of datasets.fetch_dataset.

``open_records(records_dir)`` rebuilds the packed stage's mixture
structure from the manifest — one ``RecordMember`` per original member,
with the same length (repeat expanded), the same sparse flag, and an
augmentor rebuilt from the same four recipe knobs — composed through the
ordinary ``ConcatFlowDataset``. Because ``FlowDataset.sample`` is
`_load_raw -> augment(rng, ...)` and the records hold byte-identical
``_load_raw`` output, a RecordDataset sample is bit-exact against the
raw stage's for any (index, rng): the raw loader and the record loader
feed the same training run.

Record ids map to shards by contiguous ranges (manifest order); the
record set resolves id -> (shard, local) with one searchsorted over the
cumulative counts and each shard read is O(1) via the shard's trailing
index. Readers are thread-safe (positioned pread) and pickle down to
paths for process-pool workers.
"""

from __future__ import annotations

import os.path as osp
from typing import List, Optional

import numpy as np

from dexiraft_tpu.data.datasets import ConcatFlowDataset, FlowDataset, Sample
from dexiraft_tpu.data.records.format import RecordShardReader
from dexiraft_tpu.data.records.manifest import Manifest, load_manifest


class ShardedRecordSet:
    """Flat record-id address space over a directory of shards."""

    def __init__(self, records_dir: str, manifest: Optional[Manifest] = None):
        self.records_dir = records_dir
        self.manifest = manifest or load_manifest(records_dir)
        self._readers = [RecordShardReader(osp.join(records_dir, s.file))
                         for s in self.manifest.shards]
        # cumulative record counts: record id r lives in the shard whose
        # range [starts[s], starts[s+1]) contains it
        self._starts = np.cumsum(
            [0] + [s.records for s in self.manifest.shards])

    def __len__(self) -> int:
        return self.manifest.num_records

    def read(self, record_id: int) -> Sample:
        if not 0 <= record_id < len(self):
            raise IndexError(
                f"record {record_id} out of range [0, {len(self)})")
        s = int(np.searchsorted(self._starts, record_id, side="right")) - 1
        return self._readers[s].read(record_id - int(self._starts[s]))

    def close(self) -> None:
        for r in self._readers:
            r.close()


class RecordMember(FlowDataset):
    """One packed mixture member: FlowDataset semantics (augment,
    repeat, sparse) with ``_load_raw`` served from the record set."""

    def __init__(self, recordset: ShardedRecordSet, lo: int, n_raw: int,
                 repeat: int, sparse: bool, aug_params: Optional[dict]):
        super().__init__(aug_params, sparse=sparse)
        self.recordset = recordset
        self.lo = lo
        self.n_raw = n_raw
        self.repeat = repeat

    def __len__(self) -> int:
        return self.n_raw * self.repeat

    def _load_raw(self, index: int) -> Sample:
        return self.recordset.read(self.lo + index % self.n_raw)


def open_records(records_dir: str, *, augment: bool = True):
    """Open a packed dataset for training.

    Returns a FlowDataset-shaped object (RecordMember, or a
    ConcatFlowDataset of them for mixtures) with ``.manifest`` and
    ``.recordset`` attached. ``augment=False`` drops every member's
    augmentor — raw decoded arrays out, for verification and benches.
    """
    recordset = ShardedRecordSet(records_dir)
    manifest = recordset.manifest
    members: List[RecordMember] = []
    for m in manifest.members:
        aug = dict(m.aug) if (augment and m.aug is not None) else None
        members.append(RecordMember(recordset, m.records[0], m.n_raw,
                                    m.repeat, m.sparse, aug))
    ds = members[0] if len(members) == 1 else ConcatFlowDataset(members)
    ds.manifest = manifest
    ds.recordset = recordset
    return ds


__all__ = ["ShardedRecordSet", "RecordMember", "open_records"]
