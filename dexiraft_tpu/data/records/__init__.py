"""Packed-record data plane: sharded record files, deterministic global
shuffle, per-host input sharding (docs/data_plane.md).

The streaming-scale answer to the raw-file loader: an offline packer
(scripts/pack_records.py) decodes a fetch_dataset stage ONCE into
self-describing CRC-framed shard files + a JSON manifest, and
RecordLoader serves the exact Loader.batches() contract from them —
O(1) seek for exact-resume, disjoint per-host slices for multi-host
meshes, augmentation still fresh per (seed, epoch, index) in the worker
pool. Everything here is numpy + stdlib: no jax import, safe for
process-pool workers and offline tooling.
"""

from dexiraft_tpu.data.records.dataset import (
    RecordMember,
    ShardedRecordSet,
    open_records,
)
from dexiraft_tpu.data.records.format import (
    RecordCorruptError,
    RecordShardReader,
    RecordShardWriter,
)
from dexiraft_tpu.data.records.loader import RecordLoader, RecordPipelineStats
from dexiraft_tpu.data.records.manifest import (
    Manifest,
    MemberInfo,
    ShardInfo,
    load_manifest,
    save_manifest,
)
from dexiraft_tpu.data.records.packer import pack_dataset, verify_records

__all__ = [
    "Manifest",
    "MemberInfo",
    "RecordCorruptError",
    "RecordLoader",
    "RecordMember",
    "RecordPipelineStats",
    "RecordShardReader",
    "RecordShardWriter",
    "ShardInfo",
    "ShardedRecordSet",
    "load_manifest",
    "open_records",
    "pack_dataset",
    "save_manifest",
    "verify_records",
]
