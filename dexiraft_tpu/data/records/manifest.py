"""Packed-dataset manifest: the JSON contract between packer and loader.

``manifest.json`` sits next to the shard files and is the only thing a
consumer needs to open a packed dataset:

  format / version     "dexiraft-records" / 1
  stage, image_size,   provenance: which fetch_dataset stage was packed,
  train_ds             at which crop recipe (train_cli cross-checks them
                       against the run's config before trusting the pack)
  num_records          distinct decoded samples across all shards
  num_samples          LOGICAL epoch length — repeats expanded, i.e.
                       len(fetch_dataset(...)) of the packed stage
  shards               [{file, records, bytes}] in record-id order;
                       record ids are contiguous across the list
  members              the mixture structure, in sample-index order:
                       [{name, records: [lo, hi), repeat, sparse,
                         aug: {crop_size, min_scale, max_scale, do_flip}
                         | null}] — enough to rebuild per-member
                       augmentors bit-identical to the raw stage's
  keys                 {name: {dtype, shape|null}} from the first record
                       (shape null when it varies across records)
  fingerprint          sha1 over the member structure + source file
                       basenames, so two packs of the same dataset tree
                       agree and a pack of a DIFFERENT tree is loudly
                       distinguishable in logs and bench records

The manifest is written atomically (tmp + rename) after every shard has
been closed, so a directory with a manifest is complete by construction.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import os.path as osp
from typing import Dict, List, Optional

MANIFEST_NAME = "manifest.json"
MANIFEST_FORMAT = "dexiraft-records"
MANIFEST_VERSION = 1


@dataclasses.dataclass(frozen=True)
class ShardInfo:
    file: str
    records: int
    bytes: int


@dataclasses.dataclass(frozen=True)
class MemberInfo:
    name: str
    records: "tuple[int, int]"  # [lo, hi) record-id range
    repeat: int
    sparse: bool
    aug: Optional[dict]  # FlowAugmentor kwargs, None = no augmentation

    @property
    def n_raw(self) -> int:
        return self.records[1] - self.records[0]

    def __len__(self) -> int:
        return self.n_raw * self.repeat


@dataclasses.dataclass(frozen=True)
class Manifest:
    num_records: int
    num_samples: int
    shards: "tuple[ShardInfo, ...]"
    members: "tuple[MemberInfo, ...]"
    keys: Dict[str, dict]
    fingerprint: str
    stage: Optional[str] = None
    image_size: Optional["tuple[int, int]"] = None
    train_ds: Optional[str] = None

    def as_dict(self) -> dict:
        return {
            "format": MANIFEST_FORMAT,
            "version": MANIFEST_VERSION,
            "stage": self.stage,
            "image_size": (list(self.image_size)
                           if self.image_size is not None else None),
            "train_ds": self.train_ds,
            "num_records": self.num_records,
            "num_samples": self.num_samples,
            "shards": [dataclasses.asdict(s) for s in self.shards],
            "members": [{
                "name": m.name, "records": list(m.records),
                "repeat": m.repeat, "sparse": m.sparse, "aug": m.aug,
            } for m in self.members],
            "keys": self.keys,
            "fingerprint": self.fingerprint,
        }


def dataset_fingerprint(entries: List[dict]) -> str:
    """sha1 over the flattened member structure. ``entries`` carries one
    dict per member: name, counts, repeat, sparse, and source-file
    basenames (not absolute paths — the same tree mounted elsewhere must
    fingerprint identically)."""
    blob = json.dumps(entries, sort_keys=True).encode()
    return hashlib.sha1(blob).hexdigest()


def save_manifest(records_dir: str, manifest: Manifest) -> str:
    path = osp.join(records_dir, MANIFEST_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest.as_dict(), f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_manifest(records_dir: str) -> Manifest:
    path = osp.join(records_dir, MANIFEST_NAME)
    try:
        with open(path) as f:
            raw = json.load(f)
    except OSError as e:
        raise FileNotFoundError(
            f"no record manifest at {path} — is {records_dir!r} a "
            f"directory produced by scripts/pack_records.py?") from e
    if raw.get("format") != MANIFEST_FORMAT:
        raise ValueError(f"{path}: not a {MANIFEST_FORMAT} manifest "
                         f"(format={raw.get('format')!r})")
    if raw.get("version") != MANIFEST_VERSION:
        raise ValueError(f"{path}: unsupported manifest version "
                         f"{raw.get('version')!r}")
    return Manifest(
        num_records=int(raw["num_records"]),
        num_samples=int(raw["num_samples"]),
        shards=tuple(ShardInfo(s["file"], int(s["records"]), int(s["bytes"]))
                     for s in raw["shards"]),
        members=tuple(MemberInfo(m["name"], tuple(m["records"]),
                                 int(m["repeat"]), bool(m["sparse"]),
                                 m.get("aug"))
                      for m in raw["members"]),
        keys=raw["keys"],
        fingerprint=raw["fingerprint"],
        stage=raw.get("stage"),
        image_size=(tuple(raw["image_size"])
                    if raw.get("image_size") else None),
        train_ds=raw.get("train_ds"),
    )
