"""Sharded record-file format: the on-disk unit of the packed data plane.

One shard is a self-describing append-only file of decoded samples:

  header   32 B   magic "DXRREC1\\n", format version, flags, record count
  records  per record: payload length (u64 LE), crc32 (u32 LE), payload
                 — the payload is a standard uncompressed .npz archive of
                 the sample's arrays (np.savez), so a shard is readable
                 with nothing but numpy and this 40-line framing
  index    u64 byte-offset per record, then a 24 B trailer
                 (index offset, record count, magic "DXRIDX1\\n")

The trailing index is what makes ``seek(i)`` O(1): a reader maps record
id -> byte offset with one array lookup, so exact-resume positions a
shard without touching any earlier record (the raw-file loader pays a
full decode per sample instead). Reads go through ``os.pread`` on one
shared fd — positioned, syscall-level reads with no shared file cursor,
so a thread-pool of decode workers needs no locking; process-pool
workers re-open the fd lazily after pickling (``__getstate__`` drops it).

Corruption discipline (PR 4): every framing violation — bad magic,
truncated record, CRC mismatch, malformed npz — raises
``RecordCorruptError``, which the loader's bounded retry/skip/count
machinery treats like any other decode fault. A flipped bit degrades
one sample, never the run.
"""

from __future__ import annotations

import io
import os
import struct
import threading
import zlib
from typing import Dict, List, Optional

import numpy as np

MAGIC = b"DXRREC1\n"
INDEX_MAGIC = b"DXRIDX1\n"
FORMAT_VERSION = 1

_HEADER = struct.Struct("<8sIIQQ")      # magic, version, flags, count, reserved
_REC_HEAD = struct.Struct("<QI")        # payload length, crc32
_TRAILER = struct.Struct("<QQ8s")       # index offset, count, magic

Sample = Dict[str, np.ndarray]


class RecordCorruptError(RuntimeError):
    """A record (or its shard framing) failed an integrity check."""


def encode_sample(sample: Sample) -> bytes:
    """Sample dict -> uncompressed npz bytes (bit-exact round-trip)."""
    buf = io.BytesIO()
    np.savez(buf, **sample)
    return buf.getvalue()


_EOCD_SIG = b"PK\x05\x06"
_CDIR_SIG = b"PK\x01\x02"


def _fast_npz_entries(payload: bytes):
    """Parse a ZIP_STORED npz's central directory by hand: (name, data
    slice) per entry, or None when the layout is anything but the plain
    stored zip np.savez writes (the caller then falls back to np.load).

    Why: zipfile re-CRCs every entry on read, but the record framing
    already CRC'd the WHOLE payload — going through ZipFile costs a
    second integrity pass plus its object machinery per record, which
    benchmarked as the majority of the packed plane's decode time.
    """
    eocd = payload.rfind(_EOCD_SIG, max(0, len(payload) - 65557))
    if eocd < 0 or len(payload) < eocd + 22:
        return None
    n_entries = int.from_bytes(payload[eocd + 10:eocd + 12], "little")
    cdir_off = int.from_bytes(payload[eocd + 16:eocd + 20], "little")
    entries = []
    pos = cdir_off
    for _ in range(n_entries):
        if payload[pos:pos + 4] != _CDIR_SIG:
            return None
        method = int.from_bytes(payload[pos + 10:pos + 12], "little")
        csize = int.from_bytes(payload[pos + 20:pos + 24], "little")
        name_len = int.from_bytes(payload[pos + 28:pos + 30], "little")
        extra_len = int.from_bytes(payload[pos + 30:pos + 32], "little")
        comment_len = int.from_bytes(payload[pos + 32:pos + 34], "little")
        local_off = int.from_bytes(payload[pos + 42:pos + 46], "little")
        if method != 0 or csize == 0xFFFFFFFF or local_off == 0xFFFFFFFF:
            return None  # compressed or zip64-indirected: not our writer
        name = payload[pos + 46:pos + 46 + name_len].decode("ascii",
                                                            "replace")
        # local header: 30 fixed bytes + its OWN name/extra lengths
        ln = int.from_bytes(payload[local_off + 26:local_off + 28],
                            "little")
        le = int.from_bytes(payload[local_off + 28:local_off + 30],
                            "little")
        data_off = local_off + 30 + ln + le
        if data_off + csize > len(payload):
            return None
        entries.append((name, payload[data_off:data_off + csize]))
        pos += 46 + name_len + extra_len + comment_len
    return entries


def decode_sample(payload: bytes) -> Sample:
    try:
        entries = _fast_npz_entries(payload)
        if entries is not None:
            out = {}
            for name, blob in entries:
                key = name[:-4] if name.endswith(".npy") else name
                out[key] = np.lib.format.read_array(io.BytesIO(blob),
                                                    allow_pickle=False)
            return out
        with np.load(io.BytesIO(payload), allow_pickle=False) as z:
            return {k: z[k] for k in z.files}
    except Exception as e:  # zipfile/numpy raise a zoo of types here
        raise RecordCorruptError(f"undecodable record payload: {e}") from e


class RecordShardWriter:
    """Sequential writer; ``close()`` appends the index and patches the
    header count, so a crash mid-pack leaves an obviously-invalid shard
    (count 0, no trailer) rather than a silently short one."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "wb")
        self._offsets: List[int] = []
        self._f.write(_HEADER.pack(MAGIC, FORMAT_VERSION, 0, 0, 0))
        self._closed = False

    def append(self, sample: Sample) -> int:
        payload = encode_sample(sample)
        self._offsets.append(self._f.tell())
        self._f.write(_REC_HEAD.pack(len(payload), zlib.crc32(payload)))
        self._f.write(payload)
        return len(self._offsets) - 1

    @property
    def num_records(self) -> int:
        return len(self._offsets)

    @property
    def num_bytes(self) -> int:
        return self._f.tell()

    def close(self) -> None:
        if self._closed:
            return
        index_offset = self._f.tell()
        if self._offsets:
            self._f.write(np.asarray(self._offsets, "<u8").tobytes())
        self._f.write(_TRAILER.pack(index_offset, len(self._offsets),
                                    INDEX_MAGIC))
        self._f.seek(0)
        self._f.write(_HEADER.pack(MAGIC, FORMAT_VERSION, 0,
                                   len(self._offsets), 0))
        self._f.close()
        self._closed = True

    def __enter__(self) -> "RecordShardWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RecordShardReader:
    """Random-access reader over one shard.

    ``read(i)`` is an O(1) index lookup + one positioned read;
    ``seek(i)`` just sets the sequential cursor for ``next()``/iteration.
    Thread-safe by construction (os.pread, no shared cursor state beyond
    the explicit sequential position) and pickle-safe for process-pool
    workers (the fd and index reload lazily on first use).
    """

    def __init__(self, path: str):
        self.path = path
        self._fd: Optional[int] = None
        self._offsets: Optional[np.ndarray] = None
        self._num_records: Optional[int] = None
        self._pos = 0
        self._lock = threading.Lock()

    # -- lazy state (survives pickling to process workers) --

    def __getstate__(self):
        return {"path": self.path, "_pos": self._pos}

    def __setstate__(self, state):
        self.__init__(state["path"])
        self._pos = state["_pos"]

    def _file(self) -> int:
        if self._fd is None:
            with self._lock:
                if self._fd is None:
                    self._fd = os.open(self.path, os.O_RDONLY)
        return self._fd

    def _pread(self, n: int, offset: int) -> bytes:
        data = os.pread(self._file(), n, offset)
        if len(data) != n:
            raise RecordCorruptError(
                f"{self.path}: truncated read at offset {offset} "
                f"(wanted {n} bytes, got {len(data)})")
        return data

    def _load_index(self) -> np.ndarray:
        if self._offsets is not None:
            return self._offsets
        size = os.fstat(self._file()).st_size
        if size < _HEADER.size + _TRAILER.size:
            raise RecordCorruptError(f"{self.path}: file too short ({size} B)")
        magic, version, _flags, count, _ = _HEADER.unpack(
            self._pread(_HEADER.size, 0))
        if magic != MAGIC:
            raise RecordCorruptError(f"{self.path}: bad shard magic {magic!r}")
        if version != FORMAT_VERSION:
            raise RecordCorruptError(
                f"{self.path}: unsupported format version {version}")
        index_offset, trailer_count, index_magic = _TRAILER.unpack(
            self._pread(_TRAILER.size, size - _TRAILER.size))
        if index_magic != INDEX_MAGIC or trailer_count != count:
            raise RecordCorruptError(
                f"{self.path}: bad index trailer (magic {index_magic!r}, "
                f"header count {count}, trailer count {trailer_count}) — "
                f"the shard was not closed cleanly")
        raw = self._pread(8 * count, index_offset)
        self._offsets = np.frombuffer(raw, "<u8")
        self._num_records = int(count)
        return self._offsets

    def __len__(self) -> int:
        if self._num_records is None:
            self._load_index()
        return self._num_records

    def read(self, i: int) -> Sample:
        """Record ``i``, CRC-verified. O(1) w.r.t. the shard size."""
        offsets = self._load_index()
        if not 0 <= i < len(offsets):
            raise IndexError(f"record {i} out of range [0, {len(offsets)})")
        off = int(offsets[i])
        length, crc = _REC_HEAD.unpack(self._pread(_REC_HEAD.size, off))
        payload = self._pread(int(length), off + _REC_HEAD.size)
        if zlib.crc32(payload) != crc:
            raise RecordCorruptError(
                f"{self.path}: CRC mismatch on record {i} "
                f"(offset {off}, {length} B)")
        return decode_sample(payload)

    def seek(self, i: int) -> None:
        """Position the sequential cursor at record ``i`` (O(1))."""
        if not 0 <= i <= len(self):
            raise IndexError(f"seek({i}) out of range [0, {len(self)}]")
        self._pos = i

    def __iter__(self):
        while self._pos < len(self):
            out = self.read(self._pos)
            self._pos += 1
            yield out

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None

    def __enter__(self) -> "RecordShardReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
