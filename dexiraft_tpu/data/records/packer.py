"""Offline packer: any fetch_dataset stage -> shard files + manifest.

Packing walks the stage's mixture structure member by member and writes
each DISTINCT raw sample exactly once — curriculum replication factors
(``100 * clean`` etc.) stay in the manifest as per-member ``repeat``
entries, so a stage whose logical epoch is 2.6 M samples packs only the
~20 k distinct decodes behind it. What goes into a record is the output
of ``FlowDataset._load_raw``: the DECODED arrays (uint8 images, float32
flow, sparse valid) with augmentation still unapplied — augmentation is
per-(seed, epoch, index) and must keep drawing fresh per epoch, so it
stays in the loader's worker pool where the raw path runs it too. That
split is what makes pack->read bit-exact: RecordDataset rebuilds the
same augmentors from the manifest and replays the same RNG stream over
byte-identical raw arrays.

``verify_records`` is the packer's trust-but-verify pass: re-read every
record of every shard (CRC-checked), cross-check per-shard counts,
totals, member ranges, and key dtypes against the manifest. The CLI
(scripts/pack_records.py --verify) exits nonzero on any mismatch, so a
pack that survives it is safe to hand to a pod.
"""

from __future__ import annotations

import os
import os.path as osp
from typing import Callable, List, Optional

from dexiraft_tpu.data.datasets import ConcatFlowDataset, FlowDataset
from dexiraft_tpu.data.records.format import (
    RecordCorruptError,
    RecordShardReader,
    RecordShardWriter,
)
from dexiraft_tpu.data.records.manifest import (
    Manifest,
    MemberInfo,
    ShardInfo,
    dataset_fingerprint,
    load_manifest,
    save_manifest,
)

_AUG_FIELDS = ("crop_size", "min_scale", "max_scale", "do_flip")


def _flatten(ds) -> List[FlowDataset]:
    if isinstance(ds, ConcatFlowDataset):
        return [m for sub in ds.members for m in _flatten(sub)]
    return [ds]


def _member_aug(member: FlowDataset) -> Optional[dict]:
    if member.augmentor is None:
        return None
    a = member.augmentor
    return {"crop_size": list(a.crop_size), "min_scale": a.min_scale,
            "max_scale": a.max_scale, "do_flip": a.do_flip}


def _member_entries(members: List[FlowDataset]) -> List[dict]:
    entries = []
    for m in members:
        paths = [osp.basename(p) for pair in m.image_list for p in pair]
        paths += [osp.basename(p) for p in m.flow_list]
        # aug participates in the fingerprint: two packs of the same
        # tree at different crop recipes produce different sample
        # sequences, and the resume-time fingerprint check (stream
        # sidecar) must tell them apart
        entries.append({"name": type(m).__name__,
                        "n_raw": len(m.image_list), "repeat": m.repeat,
                        "sparse": m.sparse, "aug": _member_aug(m),
                        "files": paths})
    return entries


def shard_name(index: int, num_shards: int) -> str:
    return f"shard-{index:05d}-of-{num_shards:05d}.rec"


def pack_dataset(dataset, records_dir: str, num_shards: int = 1, *,
                 stage: Optional[str] = None,
                 image_size: Optional["tuple[int, int]"] = None,
                 train_ds: Optional[str] = None,
                 progress: Optional[Callable[[int, int], None]] = None,
                 ) -> Manifest:
    """Walk ``dataset`` (a FlowDataset or mixture) and write ``num_shards``
    shard files + manifest.json into ``records_dir``."""
    members = _flatten(dataset)
    for m in members:
        if not isinstance(m, FlowDataset):
            raise TypeError(f"cannot pack {type(m).__name__}: not a "
                            f"FlowDataset")
        if type(m).__name__ == "EdgePairDataset":
            raise NotImplementedError(
                "edge-paired stages carry a second image tree that "
                "_load_raw does not cover; pack the base stage and keep "
                "--edge_root on the raw loader")
        if m.is_test:
            raise ValueError("test-split datasets (extra_info, no flow) "
                             "are not packable — pack training stages")

    total = sum(len(m.image_list) for m in members)
    if total == 0:
        raise ValueError("dataset has no samples to pack")
    num_shards = max(1, min(int(num_shards), total))
    os.makedirs(records_dir, exist_ok=True)
    # drop any previous pack FIRST — the manifest before the shards:
    # the manifest is written last, so "manifest present => pack
    # complete" stays true even when a repack over an old directory
    # crashes halfway (the half-written shards are then unopenable as a
    # set, instead of being served under the stale manifest's counts
    # and fingerprint); stale shard files go too, so a repack at a
    # different --shards count can't leave old -of-NNNNN files that a
    # human globbing *.rec would mistake for part of this pack
    from glob import glob as _glob

    old_manifest = osp.join(records_dir, "manifest.json")
    if osp.exists(old_manifest):
        os.remove(old_manifest)
    for stale in _glob(osp.join(records_dir, "shard-*-of-*.rec")):
        os.remove(stale)

    per_shard = -(-total // num_shards)  # ceil
    # re-derive the count that per_shard actually produces, so the
    # -of-NNNNN in every file name is the true shard count (9 records
    # at --shards 4 packs 3 files of 3, never 3 files "of 4")
    num_shards = -(-total // per_shard)
    writers = []
    shard_infos: List[ShardInfo] = []
    member_infos: List[MemberInfo] = []
    keys: dict = {}
    try:
        record_id = 0
        shard_ix = -1
        writer = None
        for m in members:
            lo = record_id
            for i in range(len(m.image_list)):
                if record_id // per_shard != shard_ix:
                    shard_ix = record_id // per_shard
                    writer = RecordShardWriter(
                        osp.join(records_dir,
                                 shard_name(shard_ix, num_shards)))
                    writers.append(writer)
                raw = m._load_raw(i)
                if not keys:
                    first_shapes = {k: list(v.shape) for k, v in raw.items()}
                    keys = {k: {"dtype": str(v.dtype),
                                "shape": first_shapes[k]}
                            for k, v in raw.items()}
                else:
                    for k, v in raw.items():
                        spec = keys.setdefault(
                            k, {"dtype": str(v.dtype), "shape": None})
                        if spec["shape"] != list(v.shape):
                            spec["shape"] = None  # variable geometry
                writer.append(raw)
                record_id += 1
                if progress is not None:
                    progress(record_id, total)
            member_infos.append(MemberInfo(
                name=type(m).__name__, records=(lo, record_id),
                repeat=m.repeat, sparse=m.sparse, aug=_member_aug(m)))
    finally:
        for w in writers:
            w.close()

    shard_infos = [ShardInfo(osp.basename(w.path), w.num_records,
                             osp.getsize(w.path)) for w in writers]
    manifest = Manifest(
        num_records=total,
        num_samples=sum(len(m) for m in members),
        shards=tuple(shard_infos),
        members=tuple(member_infos),
        keys=keys,
        fingerprint=dataset_fingerprint(_member_entries(members)),
        stage=stage,
        image_size=tuple(image_size) if image_size is not None else None,
        train_ds=train_ds,
    )
    save_manifest(records_dir, manifest)
    return manifest


def verify_records(records_dir: str) -> List[str]:
    """Re-read every shard against the manifest; returns a list of
    human-readable problems (empty = the pack is sound)."""
    problems: List[str] = []
    try:
        manifest = load_manifest(records_dir)
    except (OSError, ValueError, KeyError) as e:
        return [f"manifest unreadable: {e}"]

    total = 0
    for info in manifest.shards:
        path = osp.join(records_dir, info.file)
        try:
            with RecordShardReader(path) as reader:
                n = len(reader)
                if n != info.records:
                    problems.append(
                        f"{info.file}: {n} records on disk, manifest "
                        f"says {info.records}")
                for i in range(n):
                    try:
                        sample = reader.read(i)
                    except RecordCorruptError as e:
                        problems.append(str(e))
                        continue
                    for k, v in sample.items():
                        spec = manifest.keys.get(k)
                        if spec is None:
                            problems.append(
                                f"{info.file} record {i}: key {k!r} "
                                f"absent from manifest keys")
                        elif spec["dtype"] != str(v.dtype):
                            problems.append(
                                f"{info.file} record {i}: key {k!r} is "
                                f"{v.dtype}, manifest says {spec['dtype']}")
                total += n
        except (OSError, RecordCorruptError) as e:
            problems.append(f"{info.file}: {e}")
    if total != manifest.num_records:
        problems.append(f"{total} records across shards, manifest says "
                        f"{manifest.num_records}")
    if manifest.members:
        hi = max(m.records[1] for m in manifest.members)
        covered = sum(m.n_raw for m in manifest.members)
        if hi != manifest.num_records or covered != manifest.num_records:
            problems.append(
                f"member ranges cover {covered} records ending at {hi}, "
                f"manifest says {manifest.num_records}")
    return problems
