"""Device-side double-buffered batch prefetch.

`data.loader.Loader` already decodes AHEAD of the training loop into
host RAM (threaded/process pool). This layer removes the remaining
synchronous hop: the host→device transfer. `jax.device_put` is
asynchronous — it enqueues a DMA and returns immediately — so keeping
`depth` puts in flight means batch N+1 (and N+2, ...) is streaming onto
the chips with the step's OWN input shardings while step N computes.
The train step then starts without waiting on PCIe/DCN: its arguments
are already resident (the classic double-buffering pattern; depth=2 is
one buffer computing + one filling).

Stall accounting: after warm-fill, any time spent inside `next()` of
the HOST iterator is chip-starvation time (the host failed to keep
ahead) — the number `scripts/train_bench.py` reports as
`prefetch_stall`. The device_put enqueue itself is non-blocking, so it
is deliberately not counted as stall.

Donation interplay: the jitted step donates only its STATE argument
(donate_argnums=0), never the batch, so a prefetched batch that is
still queued for a future step is never invalidated by the current one.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Callable, Iterable, Iterator, Optional

# parallel.mesh (and with it jax) is imported lazily: data/__init__ must
# stay importable without jax so the Loader's SPAWNED process workers
# don't pay a jax init just to decode numpy batches


class PrefetchStats:
    """Host-side starvation accounting for a DevicePrefetcher."""

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        """Zero the counters (warm_fill_s included) — e.g. to exclude a
        bench's warmup steps from the steady-state record."""
        self.batches = 0  # batches yielded (after warm-fill)
        self.stall_s = 0.0  # time blocked on the HOST iterator
        self.stalls = 0  # yields on which the host made us wait
        self.warm_fill_s = 0.0  # initial fill (excluded from stall_s)

    @property
    def stall_per_batch_s(self) -> float:
        return self.stall_s / self.batches if self.batches else 0.0

    def summary(self, pipeline_stats=None) -> str:
        base = (f"{self.batches} batches, prefetch stall "
                f"{self.stall_s * 1e3:.1f} ms total "
                f"({self.stall_per_batch_s * 1e3:.3f} ms/batch, "
                f"{self.stalls} stalled yields; warm fill "
                f"{self.warm_fill_s * 1e3:.1f} ms)")
        if pipeline_stats is not None and pipeline_stats.faults:
            base += f"; {pipeline_stats.summary()}"
        return base


class DevicePrefetcher:
    """Iterate device-resident batches, keeping `depth` transfers in flight.

    put: host batch -> on-device batch (e.g. parallel.mesh.batch_putter
    result — device_put with the train step's input shardings). depth=2
    is double buffering; depth=0 degrades to a synchronous put-per-yield
    (useful as the parity baseline in tests).
    """

    def __init__(
        self,
        iterable: Iterable[Any],
        put: Optional[Callable[[Any], Any]] = None,
        *,
        depth: int = 2,
        pipeline_stats=None,
    ):
        if depth < 0:
            raise ValueError(f"depth must be >= 0, got {depth}")
        if put is None:
            from dexiraft_tpu.parallel.layout import batch_putter

            put = batch_putter(None)
        self.put = put
        self.depth = depth
        self.stats = PrefetchStats()
        # fault counters of the HOST pipeline feeding this prefetcher (a
        # Loader's PipelineStats) — passed explicitly when the iterable
        # is a bare generator (loader.batches(...)), else picked off a
        # Loader's .stats; surfaced by summary() so the end-of-run
        # prefetch line also reports pipeline degradation
        if pipeline_stats is None:
            pipeline_stats = getattr(iterable, "stats", None)
        self.pipeline_stats = (pipeline_stats
                               if hasattr(pipeline_stats, "faults") else None)
        self._it = iter(iterable)
        self._buf: "collections.deque" = collections.deque()
        self._warm = False
        self._exhausted = False

    # a host next() faster than this is "the batch was already decoded
    # and waiting" — only waits above it count as a stalled yield (the
    # call itself always costs some microseconds)
    STALL_EPS_S = 1e-3

    def _pull(self) -> bool:
        """Enqueue one more host batch's transfer; False when exhausted.
        The put only ENQUEUES (async dispatch) — the host-iterator next()
        is the only blocking part, and that is what gets timed."""
        if self._exhausted:
            return False
        t0 = time.perf_counter()
        try:
            batch = next(self._it)
        except StopIteration:
            self._exhausted = True
            return False
        dt = time.perf_counter() - t0
        if self._warm:
            self.stats.stall_s += dt
            if dt > self.STALL_EPS_S:
                self.stats.stalls += 1
        else:
            self.stats.warm_fill_s += dt
        self._buf.append(self.put(batch))
        return True

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        if not self._warm:
            # warm fill: depth+1 so the first yield already leaves
            # `depth` batches in flight behind it
            for _ in range(self.depth + 1):
                self._pull()
            self._warm = True
        else:
            self._pull()
        if not self._buf:
            raise StopIteration
        self.stats.batches += 1
        return self._buf.popleft()

    def summary(self) -> str:
        """One line: prefetch stall accounting + any pipeline faults."""
        return self.stats.summary(self.pipeline_stats)

    def close(self) -> None:
        """Close the underlying host iterator (e.g. a Loader generator,
        whose feeder thread and worker pool stop on close) and drop the
        buffered device batches so their device memory can be freed."""
        close = getattr(self._it, "close", None)
        if close is not None:
            close()
        self._buf.clear()
        self._exhausted = True


def prefetch_to_device(
    iterable: Iterable[Any],
    mesh=None,
    *,
    depth: int = 2,
    pipeline_stats=None,
) -> DevicePrefetcher:
    """Convenience wrapper: prefetch with the train step's input layout
    for `mesh` (parallel.layout.batch_putter; plain device_put when None)."""
    from dexiraft_tpu.parallel.layout import batch_putter

    return DevicePrefetcher(iterable, batch_putter(mesh), depth=depth,
                            pipeline_stats=pipeline_stats)
