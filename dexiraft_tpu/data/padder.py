"""Eval-time padding to stride-8 shapes (core/utils/utils.py:7-24).

'sintel' mode centers the pad; other modes (kitti/HD1K) pad top+right only
— replicate-edge padding in both, like F.pad(mode='replicate').
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np


class InputPadder:
    def __init__(self, shape: Sequence[int], mode: str = "sintel", stride: int = 8):
        self.ht, self.wd = int(shape[-3]), int(shape[-2])  # NHWC
        pad_ht = (((self.ht // stride) + 1) * stride - self.ht) % stride
        pad_wd = (((self.wd // stride) + 1) * stride - self.wd) % stride
        if mode == "sintel":
            # [left, right, top, bottom]
            self._pad = [pad_wd // 2, pad_wd - pad_wd // 2,
                         pad_ht // 2, pad_ht - pad_ht // 2]
        else:
            self._pad = [pad_wd // 2, pad_wd - pad_wd // 2, 0, pad_ht]

    def pad(self, *inputs: np.ndarray) -> Tuple[np.ndarray, ...]:
        l, r, t, b = self._pad
        width = [(0, 0)] * (inputs[0].ndim - 3) + [(t, b), (l, r), (0, 0)]
        return tuple(np.pad(x, width, mode="edge") for x in inputs)

    def unpad(self, x: np.ndarray) -> np.ndarray:
        l, r, t, b = self._pad
        ht, wd = x.shape[-3], x.shape[-2]
        return x[..., t:ht - b or None, l:wd - r or None, :]
