"""Eval-time padding to stride-8 shapes (core/utils/utils.py:7-24).

'sintel' mode centers the pad; other modes (kitti/HD1K) pad width
centered + all height at the bottom — replicate-edge padding in both,
like F.pad(mode='replicate').

`target=` generalizes the reference contract for the serving engine's
shape buckets (dexiraft_tpu.serve): instead of the next stride multiple,
pad out to an arbitrary (stride-aligned, >= input) bucket shape with the
same replicate-edge placement rules, and unpad per item on the way out.
target=None is bit-for-bit the reference behavior.

`seq=` aligns HEIGHT for halo compute sharding (parallel/halo.py):
each of the mesh's n_seq devices owns a contiguous block of feature
rows, so the padded height must divide by stride*seq — the effective
height alignment becomes stride*seq while width keeps plain stride.
seq=1 (default) is the unchanged single-slab contract.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


class InputPadder:
    def __init__(self, shape: Sequence[int], mode: str = "sintel", stride: int = 8,
                 target: Optional[Tuple[int, int]] = None, seq: int = 1):
        self.ht, self.wd = int(shape[-3]), int(shape[-2])  # NHWC
        if seq < 1:
            raise ValueError(f"seq must be >= 1, got {seq}")
        h_align = stride * seq  # rows split into seq slabs of whole
        # stride-blocks each; width never shards, so it keeps stride
        if target is None:
            pad_ht = (((self.ht // h_align) + 1) * h_align - self.ht) \
                % h_align
            pad_wd = (((self.wd // stride) + 1) * stride - self.wd) % stride
        else:
            tht, twd = int(target[0]), int(target[1])
            if tht < self.ht or twd < self.wd:
                raise ValueError(
                    f"pad target {tht}x{twd} smaller than input "
                    f"{self.ht}x{self.wd}")
            if tht % stride or twd % stride:
                raise ValueError(
                    f"pad target {tht}x{twd} not stride-{stride} aligned")
            if tht % h_align:
                raise ValueError(
                    f"pad target height {tht} not divisible by "
                    f"stride*seq = {stride}*{seq} = {h_align} — pick a "
                    f"bucket height that splits into {seq} whole-stride "
                    "row slabs")
            pad_ht, pad_wd = tht - self.ht, twd - self.wd
        if mode == "sintel":
            # [left, right, top, bottom]
            self._pad = [pad_wd // 2, pad_wd - pad_wd // 2,
                         pad_ht // 2, pad_ht - pad_ht // 2]
        else:
            self._pad = [pad_wd // 2, pad_wd - pad_wd // 2, 0, pad_ht]

    @property
    def padded_shape(self) -> Tuple[int, int]:
        l, r, t, b = self._pad
        return (self.ht + t + b, self.wd + l + r)

    def pad(self, *inputs: np.ndarray) -> Tuple[np.ndarray, ...]:
        l, r, t, b = self._pad
        width = [(0, 0)] * (inputs[0].ndim - 3) + [(t, b), (l, r), (0, 0)]
        return tuple(np.pad(x, width, mode="edge") for x in inputs)

    def unpad(self, x: np.ndarray) -> np.ndarray:
        l, r, t, b = self._pad
        ht, wd = x.shape[-3], x.shape[-2]
        return x[..., t:ht - b or None, l:wd - r or None, :]
