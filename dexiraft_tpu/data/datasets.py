"""Flow datasets: sample indexing, decoding, curriculum mixtures.

Re-design of core/datasets.py (+ datasets_seperate.py, datasets_sub.py):
datasets are plain indexable objects returning numpy dicts — no torch.
Randomness is explicit: `sample(index, rng)` takes the generator, so an
epoch is replayable from (seed, epoch) and each host of a multi-host
mesh can derive disjoint streams (the reference relies on global
per-worker seeding, core/datasets.py:45-51).

Directory layouts match the reference adapters so the same dataset roots
work; roots come from DEXIRAFT_DATA_DIR (default /mnt/dst_datasets/optical_flow,
the reference's hard-coded prefix, core/datasets.py:104-183).
"""

from __future__ import annotations

import os
import os.path as osp
from glob import glob
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from dexiraft_tpu.data.augment import FlowAugmentor, SparseFlowAugmentor
from dexiraft_tpu.data.flow_io import read_flow_kitti, read_gen, read_image

Sample = Dict[str, np.ndarray]


def data_root(name: str) -> str:
    base = os.environ.get("DEXIRAFT_DATA_DIR", "/mnt/dst_datasets/optical_flow")
    return osp.join(base, name)


# the sintel-stage mixture selector train_cli implicitly trains with;
# a records pack made with a different one is a different sample
# sequence, which the --records_dir provenance check refuses
DEFAULT_TRAIN_DS = "C+T+K+S+H"


class FlowDataset:
    """Base dataset: (image pair, flow[, valid]) with optional augmentation."""

    def __init__(self, aug_params: Optional[dict] = None, sparse: bool = False):
        self.sparse = sparse
        self.augmentor = None
        if aug_params is not None:
            cls = SparseFlowAugmentor if sparse else FlowAugmentor
            self.augmentor = cls(**aug_params)
        self.is_test = False
        self.flow_list: List[str] = []
        self.image_list: List[Tuple[str, str]] = []
        self.extra_info: List = []
        self.repeat = 1  # curriculum replication factor (cheap __rmul__)

    # -- composition (mirrors torch's ConcatDataset / reference __rmul__) --

    def __mul__(self, v: int) -> "FlowDataset":
        # value semantics: a shallow copy so `100 * ds` never mutates ds
        # (the reference's in-place __rmul__, core/datasets.py:94-97,
        # silently compounds factors when a dataset object is reused)
        import copy

        out = copy.copy(self)
        out.repeat = self.repeat * int(v)
        return out

    __rmul__ = __mul__

    def __add__(self, other: "FlowDataset") -> "ConcatFlowDataset":
        return ConcatFlowDataset([self, other])

    def __len__(self) -> int:
        return len(self.image_list) * self.repeat

    # -- decoding --

    def _load_raw(self, index: int) -> Sample:
        index = index % len(self.image_list)
        img1 = read_image(self.image_list[index][0])
        img2 = read_image(self.image_list[index][1])
        if self.is_test:
            return {"image1": img1.astype(np.float32),
                    "image2": img2.astype(np.float32),
                    "extra_info": self.extra_info[index]}
        if self.sparse:
            flow, valid = read_flow_kitti(self.flow_list[index])
        else:
            flow = np.asarray(read_gen(self.flow_list[index]), np.float32)
            valid = None
        out: Sample = {"image1": img1, "image2": img2,
                       "flow": flow.astype(np.float32)}
        if valid is not None:
            out["valid"] = valid.astype(np.float32)
        return out

    def sample(self, index: int, rng: Optional[np.random.Generator] = None) -> Sample:
        """One training sample: float32 HWC images, (H,W,2) flow, (H,W) valid."""
        raw = self._load_raw(index)
        if self.is_test:
            return raw
        img1, img2, flow = raw["image1"], raw["image2"], raw["flow"]
        valid = raw.get("valid")

        if self.augmentor is not None:
            if rng is None:
                raise ValueError("augmenting dataset needs an rng")
            if self.sparse:
                img1, img2, flow, valid = self.augmentor(rng, img1, img2, flow, valid)
            else:
                img1, img2, flow = self.augmentor(rng, img1, img2, flow)

        if valid is None:
            # dense data: mask absurd flow (core/datasets.py:88)
            valid = ((np.abs(flow[..., 0]) < 1000)
                     & (np.abs(flow[..., 1]) < 1000)).astype(np.float32)
        return {"image1": img1.astype(np.float32),
                "image2": img2.astype(np.float32),
                "flow": flow.astype(np.float32),
                "valid": np.asarray(valid, np.float32)}

    __getitem__ = sample


class ConcatFlowDataset:
    """Concatenation preserving per-member replication factors."""

    def __init__(self, members: Sequence):
        self.members: List = []
        for m in members:
            if isinstance(m, ConcatFlowDataset):
                self.members.extend(m.members)
            else:
                self.members.append(m)

    def __add__(self, other) -> "ConcatFlowDataset":
        return ConcatFlowDataset([self, other])

    def __len__(self) -> int:
        return sum(len(m) for m in self.members)

    def sample(self, index: int, rng: Optional[np.random.Generator] = None) -> Sample:
        for m in self.members:
            n = len(m)
            if index < n:
                return m.sample(index, rng)
            index -= n
        raise IndexError(index)

    __getitem__ = sample


class MpiSintel(FlowDataset):
    """Sintel scene walk, clean/final passes (core/datasets.py:103-120)."""

    def __init__(self, aug_params=None, split="training", root=None,
                 dstype="clean", scene: Optional[str] = None,
                 qualitative: bool = False):
        """scene restricts to one scene; qualitative=True additionally
        returns test-style samples (image pair + extra_info, no flow) for
        visualization runs on training scenes — the reference's
        core/datasets_sub.py market_2 workflow."""
        super().__init__(aug_params)
        root = root or data_root("Sintel")
        flow_root = osp.join(root, split, "flow")
        image_root = osp.join(root, split, dstype)
        if split == "test" or qualitative:
            self.is_test = True
        scenes = [scene] if scene else sorted(os.listdir(image_root))
        for sc in scenes:
            images = sorted(glob(osp.join(image_root, sc, "*.png")))
            for i in range(len(images) - 1):
                self.image_list.append((images[i], images[i + 1]))
                self.extra_info.append((sc, i))
            if split != "test":
                self.flow_list += sorted(glob(osp.join(flow_root, sc, "*.flo")))


class FlyingChairs(FlowDataset):
    """FlyingChairs with the published 1/2 train/val split file
    (core/datasets.py:123-136; chairs_split.txt consumed at :131)."""

    def __init__(self, aug_params=None, split="training", root=None,
                 split_file: Optional[str] = None):
        super().__init__(aug_params)
        root = root or data_root("FlyingChairs_release/data")
        images = sorted(glob(osp.join(root, "*.ppm")))
        flows = sorted(glob(osp.join(root, "*.flo")))
        assert len(images) // 2 == len(flows), (len(images), len(flows))

        if split_file is None:
            for cand in (osp.join(root, "..", "chairs_split.txt"),
                         osp.join(root, "chairs_split.txt"),
                         "chairs_split.txt"):
                if osp.exists(cand):
                    split_file = cand
                    break
        if split_file is None:
            raise FileNotFoundError(
                "chairs_split.txt not found; pass split_file= explicitly")
        split_ids = np.loadtxt(split_file, dtype=np.int32)
        want = 1 if split == "training" else 2
        for i in range(len(flows)):
            if split_ids[i] == want:
                self.flow_list.append(flows[i])
                self.image_list.append((images[2 * i], images[2 * i + 1]))


class FlyingThings3D(FlowDataset):
    """Left camera, both time directions (core/datasets.py:139-160)."""

    def __init__(self, aug_params=None, root=None, dstype="frames_cleanpass"):
        super().__init__(aug_params)
        root = root or data_root("FlyingThings3D")
        for cam in ["left"]:
            for direction in ["into_future", "into_past"]:
                image_dirs = sorted(glob(osp.join(root, dstype, "TRAIN/*/*")))
                image_dirs = sorted(osp.join(f, cam) for f in image_dirs)
                flow_dirs = sorted(glob(osp.join(root, "optical_flow/TRAIN/*/*")))
                flow_dirs = sorted(osp.join(f, direction, cam) for f in flow_dirs)
                for idir, fdir in zip(image_dirs, flow_dirs):
                    images = sorted(glob(osp.join(idir, "*.png")))
                    flows = sorted(glob(osp.join(fdir, "*.pfm")))
                    for i in range(len(flows) - 1):
                        if direction == "into_future":
                            self.image_list.append((images[i], images[i + 1]))
                            self.flow_list.append(flows[i])
                        else:
                            self.image_list.append((images[i + 1], images[i]))
                            self.flow_list.append(flows[i + 1])


class KITTI(FlowDataset):
    """KITTI-2015 sparse flow (core/datasets.py:163-179)."""

    def __init__(self, aug_params=None, split="training", root=None):
        super().__init__(aug_params, sparse=True)
        root = root or data_root("Kitti_2015")
        if split == "testing":
            self.is_test = True
        root = osp.join(root, "data_scene_flow", split)
        images1 = sorted(glob(osp.join(root, "image_2/*_10.png")))
        images2 = sorted(glob(osp.join(root, "image_2/*_11.png")))
        for im1, im2 in zip(images1, images2):
            self.extra_info.append([osp.basename(im1)])
            self.image_list.append((im1, im2))
        if split == "training":
            self.flow_list = sorted(glob(osp.join(root, "flow_occ/*_10.png")))


class HD1K(FlowDataset):
    """HD1K sparse flow. The reference only walks sequence 000000 (its loop
    never iterates, core/datasets.py:186-199); we walk every sequence and
    keep consecutive-frame pairing within each."""

    def __init__(self, aug_params=None, root=None):
        super().__init__(aug_params, sparse=True)
        root = root or data_root("HD1k")
        seq_ix = 0
        while True:
            flows = sorted(glob(osp.join(root, "hd1k_flow_gt",
                                         "flow_occ/%06d_*.png" % seq_ix)))
            images = sorted(glob(osp.join(root, "hd1k_input",
                                          "image_2/%06d_*.png" % seq_ix)))
            if not flows:
                break
            for i in range(len(flows) - 1):
                self.flow_list.append(flows[i])
                self.image_list.append((images[i], images[i + 1]))
            seq_ix += 1


class EdgePairDataset(FlowDataset):
    """Flow samples with precomputed edge-map images for the v2/v3 data-edge
    contract (core/datasets_seperate.py): edge PNGs live in a parallel tree
    and receive the same augmentation as the images (lockstep — the
    reference's independent second augmentor call is a documented bug)."""

    def __init__(self, base: FlowDataset, edge_list: Sequence[Tuple[str, str]]):
        super().__init__(aug_params=None, sparse=base.sparse)
        self.base = base
        self.augmentor = base.augmentor
        self.sparse = base.sparse
        self.is_test = base.is_test
        self.flow_list = base.flow_list
        self.image_list = base.image_list
        self.extra_info = base.extra_info
        self.edge_list = list(edge_list)
        assert len(self.edge_list) == len(self.image_list)

    @classmethod
    def from_parallel_tree(cls, base: FlowDataset, image_root: str,
                           edge_root: str) -> "EdgePairDataset":
        """Map each image path to the same relative path under edge_root."""
        def remap(p: str) -> str:
            rel = osp.relpath(p, image_root)
            return osp.join(edge_root, osp.splitext(rel)[0] + ".png")

        pairs = [(remap(a), remap(b)) for a, b in base.image_list]
        return cls(base, pairs)

    def sample(self, index: int, rng: Optional[np.random.Generator] = None) -> Sample:
        raw = self._load_raw(index)
        i = index % len(self.image_list)
        em1 = read_image(self.edge_list[i][0])
        em2 = read_image(self.edge_list[i][1])
        img1, img2, flow = raw["image1"], raw["image2"], raw["flow"]
        valid = raw.get("valid")

        if self.augmentor is not None:
            if self.sparse:
                img1, img2, flow, valid, em1, em2 = self.augmentor(
                    rng, img1, img2, flow, valid, edges=(em1, em2))
            else:
                img1, img2, flow, em1, em2 = self.augmentor(
                    rng, img1, img2, flow, edges=(em1, em2))
        if valid is None:
            valid = ((np.abs(flow[..., 0]) < 1000)
                     & (np.abs(flow[..., 1]) < 1000)).astype(np.float32)
        return {"image1": img1.astype(np.float32),
                "image2": img2.astype(np.float32),
                "edges1": em1.astype(np.float32),
                "edges2": em2.astype(np.float32),
                "flow": flow.astype(np.float32),
                "valid": np.asarray(valid, np.float32)}

    __getitem__ = sample


def fetch_dataset(stage: str, image_size: Sequence[int],
                  train_ds: str = DEFAULT_TRAIN_DS,
                  edge_root: Optional[str] = None):
    """Stage-keyed training mixture (core/datasets.py:202-237).

    edge_root: parallel tree of precomputed edge-map PNGs — wraps the
    stage dataset in EdgePairDataset for the v2/v3 data-edge contract
    (core/datasets_seperate.py). Supported for the single-dataset stages
    (chairs, kitti)."""
    ds = _fetch_plain(stage, image_size, train_ds)
    if edge_root is None:
        return ds
    if isinstance(ds, ConcatFlowDataset):
        raise ValueError(
            f"edge_root is only supported for single-dataset stages, "
            f"not the {stage!r} mixture")
    return wrap_with_edge_tree(ds, edge_root)


def wrap_with_edge_tree(ds: "FlowDataset", edge_root: str) -> "EdgePairDataset":
    """Pair every image with its edge map at the same relative path under
    edge_root — the ONE path-mapping convention shared by training
    (fetch_dataset) and edge-sum evaluation (eval_cli)."""
    image_root = osp.dirname(osp.commonprefix(
        [p for pair in ds.image_list for p in pair]))
    return EdgePairDataset.from_parallel_tree(ds, image_root, edge_root)


def _fetch_plain(stage: str, image_size: Sequence[int], train_ds: str):
    if stage == "chairs":
        aug = dict(crop_size=image_size, min_scale=-0.1, max_scale=1.0, do_flip=True)
        return FlyingChairs(aug, split="training")
    if stage == "things":
        aug = dict(crop_size=image_size, min_scale=-0.4, max_scale=0.8, do_flip=True)
        return (FlyingThings3D(aug, dstype="frames_cleanpass")
                + FlyingThings3D(aug, dstype="frames_finalpass"))
    if stage == "sintel":
        aug = dict(crop_size=image_size, min_scale=-0.2, max_scale=0.6, do_flip=True)
        things = FlyingThings3D(aug, dstype="frames_cleanpass")
        clean = MpiSintel(aug, split="training", dstype="clean")
        final = MpiSintel(aug, split="training", dstype="final")
        if train_ds == "C+T+K+S+H":
            kitti = KITTI(dict(crop_size=image_size, min_scale=-0.3,
                               max_scale=0.5, do_flip=True))
            hd1k = HD1K(dict(crop_size=image_size, min_scale=-0.5,
                             max_scale=0.2, do_flip=True))
            return 100 * clean + 100 * final + 200 * kitti + 5 * hd1k + things
        return 100 * clean + 100 * final + things
    if stage == "kitti":
        aug = dict(crop_size=image_size, min_scale=-0.2, max_scale=0.4, do_flip=False)
        return KITTI(aug, split="training")
    raise ValueError(f"unknown stage {stage!r}")
