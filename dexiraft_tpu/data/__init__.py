"""Host-side data pipeline: file I/O, datasets, augmentation, loading.

TPU-first re-design of the reference's torch DataLoader stack
(core/datasets.py, core/utils/augmentor.py, core/utils/frame_utils.py):
pure numpy samples with explicit PRNG, per-host sharded batches, and a
threaded prefetcher that keeps the chips fed.

The packed-record data plane (sharded record files + manifest + the
RecordLoader serving the same Loader.batches contract with O(1) resume
seeks) lives in the ``dexiraft_tpu.data.records`` subpackage
(docs/data_plane.md).
"""

from dexiraft_tpu.data.augment import ColorJitter, FlowAugmentor, SparseFlowAugmentor
from dexiraft_tpu.data.datasets import (
    HD1K,
    KITTI,
    EdgePairDataset,
    FlowDataset,
    FlyingChairs,
    FlyingThings3D,
    MpiSintel,
    fetch_dataset,
)
from dexiraft_tpu.data.flow_io import (
    read_flo,
    read_flow_kitti,
    read_gen,
    read_pfm,
    write_flo,
    write_flow_kitti,
)
from dexiraft_tpu.data.loader import Loader, epoch_permutation
from dexiraft_tpu.data.padder import InputPadder
from dexiraft_tpu.data.prefetch import (
    DevicePrefetcher,
    PrefetchStats,
    prefetch_to_device,
)

__all__ = [
    "ColorJitter",
    "FlowAugmentor",
    "SparseFlowAugmentor",
    "FlowDataset",
    "EdgePairDataset",
    "FlyingChairs",
    "FlyingThings3D",
    "MpiSintel",
    "KITTI",
    "HD1K",
    "fetch_dataset",
    "read_flo",
    "write_flo",
    "read_pfm",
    "read_flow_kitti",
    "write_flow_kitti",
    "read_gen",
    "Loader",
    "epoch_permutation",
    "InputPadder",
    "DevicePrefetcher",
    "PrefetchStats",
    "prefetch_to_device",
]
