"""Flow-field and image file I/O.

Covers the reference's formats (core/utils/frame_utils.py):
  .flo        Middlebury: 'PIEH' float tag, int32 w/h, interleaved u,v rows
  .pfm        portable float map (FlyingThings3D flow), bottom-up scanlines
  KITTI .png  16-bit RGB: u,v encoded as uint16 (value*64 + 2^15), B=valid
plus an extension-dispatch reader. Images decode via imageio (PIL backend);
KITTI 16-bit PNGs via cv2 (imageio drops the 16-bit depth on some plugins).
"""

from __future__ import annotations

import os
import re
from typing import Optional, Tuple, Union

import numpy as np

FLO_MAGIC = 202021.25  # 'PIEH' interpreted as float32


def read_flo(path: Union[str, os.PathLike]) -> np.ndarray:
    """Middlebury .flo -> (H, W, 2) float32 (native decoder when built)."""
    from dexiraft_tpu.data import native

    out = native.read_flo_native(path)
    if out is not None:
        return out
    with open(path, "rb") as f:
        magic = np.frombuffer(f.read(4), np.float32)[0]
        if magic != np.float32(FLO_MAGIC):
            raise ValueError(f"{path}: bad .flo magic {magic!r}")
        w, h = np.frombuffer(f.read(8), np.int32)
        data = np.frombuffer(f.read(int(w) * int(h) * 8), np.float32)
    return data.reshape(int(h), int(w), 2).copy()


def write_flo(path: Union[str, os.PathLike], flow: np.ndarray) -> None:
    """(H, W, 2) float32 -> Middlebury .flo."""
    flow = np.asarray(flow, np.float32)
    if flow.ndim != 3 or flow.shape[2] != 2:
        raise ValueError(f"flow must be (H, W, 2), got {flow.shape}")
    h, w = flow.shape[:2]
    with open(path, "wb") as f:
        np.float32(FLO_MAGIC).tofile(f)
        np.int32(w).tofile(f)
        np.int32(h).tofile(f)
        flow.tofile(f)


def read_pfm(path: Union[str, os.PathLike]) -> np.ndarray:
    """PFM -> (H, W[, 3]) float32, top-down row order."""
    with open(path, "rb") as f:
        header = f.readline().rstrip()
        if header == b"PF":
            channels = 3
        elif header == b"Pf":
            channels = 1
        else:
            raise ValueError(f"{path}: not a PFM file (header {header!r})")
        dims = re.match(rb"^(\d+)\s+(\d+)\s*$", f.readline())
        if not dims:
            raise ValueError(f"{path}: malformed PFM dimensions")
        w, h = int(dims.group(1)), int(dims.group(2))
        scale = float(f.readline().rstrip())
        endian = "<" if scale < 0 else ">"
        data = np.fromfile(f, endian + "f")
    shape = (h, w, 3) if channels == 3 else (h, w)
    # PFM scanlines are stored bottom-to-top
    return np.flipud(data.reshape(shape)).astype(np.float32)


def write_pfm(path: Union[str, os.PathLike], data: np.ndarray) -> None:
    """(H, W[, 3]) float32 -> little-endian PFM."""
    data = np.asarray(data, np.float32)
    if data.ndim == 3 and data.shape[2] == 3:
        header = b"PF"
    elif data.ndim == 2:
        header = b"Pf"
    else:
        raise ValueError(f"PFM needs (H,W) or (H,W,3), got {data.shape}")
    h, w = data.shape[:2]
    with open(path, "wb") as f:
        f.write(header + b"\n")
        f.write(f"{w} {h}\n".encode())
        f.write(b"-1.0\n")
        np.flipud(data).astype("<f4").tofile(f)


def read_flow_kitti(path: Union[str, os.PathLike]) -> Tuple[np.ndarray, np.ndarray]:
    """KITTI 16-bit flow PNG -> ((H, W, 2) float32 flow, (H, W) float32 valid).

    Encoding (KITTI devkit, core/utils/frame_utils.py:102-107):
    uint16 channels R=u, G=v with value = flow*64 + 2^15; B = valid mask.
    """
    import cv2

    raw = cv2.imread(os.fspath(path), cv2.IMREAD_ANYDEPTH | cv2.IMREAD_COLOR)
    if raw is None:
        raise FileNotFoundError(path)
    raw = raw[:, :, ::-1].astype(np.float32)  # BGR -> RGB
    flow = (raw[:, :, :2] - 2**15) / 64.0
    valid = raw[:, :, 2]
    return flow, valid


def write_flow_kitti(path: Union[str, os.PathLike], flow: np.ndarray,
                     valid: Optional[np.ndarray] = None) -> None:
    """(H, W, 2) flow -> KITTI 16-bit PNG; ``valid`` (H, W) marks the
    measured pixels (KITTI GT is sparse), default all-valid."""
    import cv2

    flow = np.asarray(flow, np.float32)
    enc = 64.0 * flow + 2**15
    if valid is None:
        valid = np.ones(flow.shape[:2], np.float32)
    out = np.concatenate(
        [enc, np.asarray(valid, np.float32)[..., None]],
        axis=-1).astype(np.uint16)
    cv2.imwrite(os.fspath(path), out[:, :, ::-1])


def read_disp_kitti(path: Union[str, os.PathLike]) -> Tuple[np.ndarray, np.ndarray]:
    """KITTI 16-bit disparity PNG -> ((H,W,2) flow [-disp, 0], valid)."""
    import cv2

    disp = cv2.imread(os.fspath(path), cv2.IMREAD_ANYDEPTH)
    if disp is None:
        raise FileNotFoundError(path)
    disp = disp.astype(np.float32) / 256.0
    valid = (disp > 0.0).astype(np.float32)
    flow = np.stack([-disp, np.zeros_like(disp)], axis=-1)
    return flow, valid


def read_image(path: Union[str, os.PathLike]) -> np.ndarray:
    """8-bit image -> (H, W, 3) uint8 (grayscale promoted, alpha dropped).

    Binary PPMs (the FlyingChairs format) take the native decoder when
    available; everything else goes through imageio."""
    if os.fspath(path).lower().endswith(".ppm"):
        from dexiraft_tpu.data import native

        out = native.read_ppm_native(path)
        if out is not None:
            return out
    import imageio.v2 as imageio

    img = np.asarray(imageio.imread(os.fspath(path)))
    if img.ndim == 2:
        img = np.tile(img[..., None], (1, 1, 3))
    return np.ascontiguousarray(img[..., :3]).astype(np.uint8)


def read_gen(path: Union[str, os.PathLike]) -> Optional[np.ndarray]:
    """Extension-dispatch reader (core/utils/frame_utils.py:123-137)."""
    ext = os.path.splitext(os.fspath(path))[-1].lower()
    if ext in (".png", ".jpeg", ".jpg", ".ppm"):
        return read_image(path)
    if ext in (".bin", ".raw"):
        return np.load(path)
    if ext == ".flo":
        return read_flo(path)
    if ext == ".pfm":
        flow = read_pfm(path)
        return flow[:, :, :-1] if flow.ndim == 3 else flow
    return None
