"""Batch loader: deterministic shuffle, per-host sharding, threaded prefetch.

Replaces torch's DataLoader (core/datasets.py:233-234: bs, shuffle,
4 workers, drop_last). TPU-first:
  * the global batch is SPLIT ACROSS HOSTS — each process decodes only its
    jax.process_index() slice, the device_put in parallel.shard_batch does
    the rest (multi-host DP without any data duplication);
  * shuffling and augmentation are driven by counter-based PRNG streams
    keyed on (seed, epoch, global index) — any sample of any epoch is
    reproducible in isolation, unlike the reference's per-worker seeding;
  * a thread pool decodes ahead of the training step (the chips, not the
    host, should be the bottleneck). The optional C++ decode path plugs in
    below this layer (dexiraft_tpu.data.native).
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, Optional

import numpy as np

Batch = Dict[str, np.ndarray]


def _stack(samples) -> Batch:
    keys = [k for k in samples[0] if k != "extra_info"]
    return {k: np.stack([s[k] for s in samples]) for k in keys}


# --- process-worker plumbing -------------------------------------------------
# Decoding is a pure function of (seed, epoch, index) — the counter-based
# PRNG keys make a sample reproducible in ANY worker, so thread and
# process pools yield bit-identical batches. The dataset is shipped to
# each worker ONCE via the pool initializer (under the default fork
# context it is inherited for free); per-task traffic is just two ints
# out and the decoded arrays back.
_WORKER_STATE: dict = {}


def _process_worker_init(dataset, seed: int) -> None:
    _WORKER_STATE["dataset"] = dataset
    _WORKER_STATE["seed"] = seed


def _process_decode(epoch: int, index: int) -> Batch:
    rng = np.random.default_rng((_WORKER_STATE["seed"], epoch, index))
    return _WORKER_STATE["dataset"].sample(int(index), rng)


class Loader:
    """Iterable over batches of a FlowDataset(-like) object.

    len(dataset) defines an epoch; iteration is endless (the trainer's
    should_keep_training loop decides when to stop, train.py:163).
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        *,
        shuffle: bool = True,
        drop_last: bool = True,
        seed: int = 1234,
        num_workers: int = 4,
        prefetch: int = 4,
        process_index: int = 0,
        process_count: int = 1,
        worker_mode: str = "thread",
        mp_start_method: str = "fork",
    ):
        if batch_size % process_count:
            raise ValueError(
                f"global batch {batch_size} must divide over {process_count} hosts")
        self.dataset = dataset
        self.global_batch = batch_size
        self.local_batch = batch_size // process_count
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.num_workers = max(1, num_workers)
        self.prefetch = prefetch
        self.process_index = process_index
        self.process_count = process_count
        if worker_mode not in ("thread", "process"):
            raise ValueError(f"worker_mode must be thread|process, got {worker_mode!r}")
        # "process" sidesteps the GIL for the Python/numpy share of
        # decode+augment (the reference's DataLoader runs 4 worker
        # PROCESSES for the same reason, core/datasets.py:234). Prefer
        # constructing the Loader BEFORE heavy jax/TPU init when using
        # the default fork start method, or pass mp_start_method="spawn".
        self.worker_mode = worker_mode
        self.mp_start_method = mp_start_method

    def __len__(self) -> int:
        n = len(self.dataset) // self.global_batch
        if not self.drop_last and len(self.dataset) % self.global_batch:
            n += 1
        return n

    def _epoch_order(self, epoch: int) -> np.ndarray:
        order = np.arange(len(self.dataset))
        if self.shuffle:
            np.random.default_rng((self.seed, epoch)).shuffle(order)
        return order

    def _decode(self, epoch: int, index: int) -> Batch:
        rng = np.random.default_rng((self.seed, epoch, index))
        return self.dataset.sample(int(index), rng)

    def batches(self, start_epoch: int = 0) -> Iterator[Batch]:
        """Endless batch stream; this host's slice of each global batch."""
        if self.worker_mode == "process":
            import multiprocessing as mp
            from concurrent.futures import ProcessPoolExecutor

            pool = ProcessPoolExecutor(
                max_workers=self.num_workers,
                mp_context=mp.get_context(self.mp_start_method),
                initializer=_process_worker_init,
                initargs=(self.dataset, self.seed))
            submit = lambda epoch, i: pool.submit(_process_decode, epoch, i)  # noqa: E731
        else:
            pool = ThreadPoolExecutor(max_workers=self.num_workers)
            submit = lambda epoch, i: pool.submit(self._decode, epoch, i)  # noqa: E731
        out: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        # a trailing partial global batch cannot be split evenly across
        # hosts — some would yield one more batch than others and the
        # sharded step's collectives would deadlock; always drop it when
        # multi-host
        drop_last = self.drop_last or self.process_count > 1

        def submit_loop():
            epoch = start_epoch
            while not stop.is_set():
                order = self._epoch_order(epoch)
                usable = (len(order) // self.global_batch * self.global_batch
                          if drop_last else len(order))
                for b0 in range(0, usable, self.global_batch):
                    lo = b0 + self.process_index * self.local_batch
                    ids = order[lo:lo + self.local_batch]
                    if len(ids) == 0:
                        continue
                    futs = [submit(epoch, i) for i in ids]
                    while not stop.is_set():  # never park forever on put
                        try:
                            out.put(futs, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
                epoch += 1

        feeder = threading.Thread(target=submit_loop, daemon=True)
        feeder.start()
        try:
            while True:
                futs = out.get()
                yield _stack([f.result() for f in futs])
        finally:
            stop.set()
            pool.shutdown(wait=False, cancel_futures=True)

    def __iter__(self) -> Iterator[Batch]:
        return self.batches()
