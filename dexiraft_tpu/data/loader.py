"""Batch loader: deterministic shuffle, per-host sharding, threaded prefetch.

Replaces torch's DataLoader (core/datasets.py:233-234: bs, shuffle,
4 workers, drop_last). TPU-first:
  * the global batch is SPLIT ACROSS HOSTS — each process decodes only its
    jax.process_index() slice, the device_put in parallel.shard_batch does
    the rest (multi-host DP without any data duplication);
  * shuffling and augmentation are driven by counter-based PRNG streams
    keyed on (seed, epoch, global index) — any sample of any epoch is
    reproducible in isolation, unlike the reference's per-worker seeding;
  * a thread pool decodes ahead of the training step (the chips, not the
    host, should be the bottleneck). The optional C++ decode path plugs in
    below this layer (dexiraft_tpu.data.native).

Fault tolerance (the resilience layer's data half): a decode failure —
corrupt PNG, truncated .flo, or a pool worker dying outright — degrades
throughput, never the run. Failed decodes get bounded retry with
backoff, then skip-and-count (the batch backfills from its surviving
samples, mirroring the inference engine's tail-pad); a broken process
pool is rebuilt in place. PipelineStats carries the counts to the
logger. Exact resume rides the same counter-based PRNG design:
``batches(start_epoch=, start_offset=)`` reproduces the stream from any
(epoch, global-batch offset) position.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from concurrent.futures import BrokenExecutor, ThreadPoolExecutor
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from dexiraft_tpu.analysis.locks import OrderedLock

Batch = Dict[str, np.ndarray]


def epoch_permutation(seed: int, epoch: int, n: int) -> np.ndarray:
    """THE deterministic global-shuffle contract of the data plane.

    The epoch-``epoch`` visit order over ``n`` samples is a pure function
    of ``(seed, epoch)``: stable across processes and platforms for a
    given numpy version, so a restarted process, a packer verifying host
    slices offline, and every host of a multi-host mesh all derive the
    SAME permutation with no communication. (NEP 19 reserves the right
    to change Generator streams between numpy feature releases — all
    hosts of one run, and a resumed run, must use the same numpy
    version, which any pinned pod image already guarantees.) Exact-resume (resilience.stream) and per-host input
    sharding (Loader.batches slicing host-disjoint windows of this
    order) both lean on this function and nothing else; it is pinned by
    tests/test_zzzdata_records.py including across a process restart.
    """
    order = np.arange(n)
    np.random.default_rng((seed, epoch)).shuffle(order)
    return order


def world_compatible(batch_size: int, process_count: int) -> Optional[str]:
    """None when ``process_count`` hosts can slice a ``batch_size``
    global batch, else a one-line reason. The Loader constructor raises
    the same condition; this form lets elastic membership
    (resilience.membership) refuse a shrink target BEFORE tearing the
    old world down — the global-batch offsets in the stream sidecars
    are host-count-invariant precisely because every world slices the
    SAME global batch, so a world that cannot slice it evenly is not a
    resize, it is a different run."""
    if process_count < 1:
        return f"process_count must be positive, got {process_count}"
    if batch_size % process_count:
        return (f"global batch {batch_size} must divide over "
                f"{process_count} hosts")
    return None


def _stack(samples) -> Batch:
    keys = [k for k in samples[0] if k != "extra_info"]
    return {k: np.stack([s[k] for s in samples]) for k in keys}


class PipelineStats:
    """Data-pipeline fault accounting (the loader analog of
    prefetch.PrefetchStats / profiling.ServeStats): every degradation
    the pipeline absorbed, countable, so a run that silently skipped
    half its data cannot masquerade as a healthy one."""

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self.retries = 0          # decode re-submissions (incl. after a
                                  # pool rebuild)
        self.skipped_samples = 0  # samples abandoned after the retry
                                  # budget; their batch slot backfills
        self.dropped_batches = 0  # batches with NO surviving sample
        self.worker_restarts = 0  # decode-pool rebuilds (worker death)

    @property
    def faults(self) -> int:
        return (self.retries + self.skipped_samples + self.dropped_batches
                + self.worker_restarts)

    def as_dict(self) -> Dict[str, int]:
        return {"retries": self.retries,
                "skipped_samples": self.skipped_samples,
                "dropped_batches": self.dropped_batches,
                "worker_restarts": self.worker_restarts}

    def summary(self) -> str:
        if not self.faults:
            return "no pipeline faults"
        return (f"{self.retries} decode retries, {self.skipped_samples} "
                f"samples skipped, {self.dropped_batches} batches dropped, "
                f"{self.worker_restarts} worker-pool restarts")


# --- process-worker plumbing -------------------------------------------------
# Decoding is a pure function of (seed, epoch, index) — the counter-based
# PRNG keys make a sample reproducible in ANY worker, so thread and
# process pools yield bit-identical batches. The dataset is shipped to
# each worker ONCE via the pool initializer (under the default fork
# context it is inherited for free); per-task traffic is just two ints
# out and the decoded arrays back.
_WORKER_STATE: dict = {}


def _process_worker_init(dataset, seed: int) -> None:
    _WORKER_STATE["dataset"] = dataset
    _WORKER_STATE["seed"] = seed


def _process_decode(epoch: int, index: int) -> Batch:
    rng = np.random.default_rng((_WORKER_STATE["seed"], epoch, index))
    return _WORKER_STATE["dataset"].sample(int(index), rng)


class _FeederError:
    """Queue marker carrying a fatal feeder-thread exception to the
    consumer side of the batch stream."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class _FailedFuture:
    """Future-shaped carrier for a submit()-time error, so the consumer's
    one result-with-retry path handles enqueue failures too."""

    def __init__(self, exc: BaseException):
        self._exc = exc

    def result(self):
        raise self._exc


class _PoolManager:
    """Owns the decode pool and rebuilds it when workers die.

    A ProcessPoolExecutor whose worker exits (OOM-kill, segfault,
    injected os._exit) becomes permanently broken: every pending and
    future submission raises BrokenProcessPool. Both the feeder thread
    (submitting ahead) and the consumer (resolving results) can observe
    the break, so rebuild() is generation-guarded behind a lock — the
    first observer rebuilds, later observers of the same broken
    generation just pick up the fresh pool.
    """

    # consecutive rebuilds WITHOUT a single successful decode in between
    # before giving up: a pool whose workers die at startup (bad spawn
    # entrypoint, broken install) would otherwise rebuild forever while
    # the consumer waits on batches that can never arrive
    MAX_CONSECUTIVE_REBUILDS = 8

    def __init__(self, loader: "Loader"):
        self.loader = loader
        self._lock = OrderedLock("data.loader.pool")
        self._generation = 0
        self._rebuilds_since_success = 0
        self._closed = False
        self._pool = self._build()

    def note_success(self) -> None:
        with self._lock:
            # unlocked, this reset can interleave with rebuild()'s
            # locked increment and resurrect a stale streak count —
            # the give-up ceiling then fires early (or never)
            self._rebuilds_since_success = 0

    def _build(self):
        ld = self.loader
        if ld.worker_mode == "process":
            import multiprocessing as mp
            from concurrent.futures import ProcessPoolExecutor

            return ProcessPoolExecutor(
                max_workers=ld.num_workers,
                mp_context=mp.get_context(ld.mp_start_method),
                initializer=_process_worker_init,
                initargs=(ld.dataset, ld.seed))
        return ThreadPoolExecutor(max_workers=ld.num_workers)

    def _submit_raw(self, pool, epoch: int, index: int):
        if self.loader.worker_mode == "process":
            return pool.submit(_process_decode, epoch, int(index))
        return pool.submit(self.loader._decode, epoch, int(index))

    def rebuild(self, seen_generation: int) -> None:
        """Replace the pool unless another thread already did."""
        with self._lock:
            if self._closed:
                # shutdown() raced the feeder's last submissions: the
                # "broken" pool is the one we closed on purpose — do
                # not resurrect a pool nobody will shut down, and do
                # not count a phantom worker restart
                return
            if seen_generation != self._generation:
                return
            self._rebuilds_since_success += 1
            if self._rebuilds_since_success > self.MAX_CONSECUTIVE_REBUILDS:
                raise RuntimeError(
                    f"decode pool produced no result across "
                    f"{self._rebuilds_since_success - 1} consecutive "
                    f"rebuilds — the workers are dying at startup "
                    f"(worker_mode={self.loader.worker_mode!r}, "
                    f"mp_start_method={self.loader.mp_start_method!r}); "
                    f"this is not a recoverable data fault")
            old = self._pool
            self._pool = self._build()
            self._generation += 1
            self.loader.stats.worker_restarts += 1
        print(f"[loader] decode pool broken; rebuilt "
              f"({self.loader.stats.worker_restarts} restart(s) so far)",
              flush=True)
        try:
            old.shutdown(wait=False, cancel_futures=True)
        except Exception:
            pass

    def submit(self, epoch: int, index: int):
        """Submit a decode; the returned future is tagged with the pool
        generation that produced it, so a consumer observing its failure
        rebuilds THAT generation (idempotent under races)."""
        with self._lock:
            pool, generation = self._pool, self._generation
        try:
            fut = self._submit_raw(pool, epoch, index)
        except (BrokenExecutor, RuntimeError):
            # RuntimeError covers "cannot schedule new futures after
            # shutdown" races during a concurrent rebuild
            self.rebuild(generation)
            with self._lock:
                pool, generation = self._pool, self._generation
            try:
                fut = self._submit_raw(pool, epoch, index)
            except Exception as e2:
                fut = _FailedFuture(e2)
        except Exception as e:
            fut = _FailedFuture(e)
        fut.pool_generation = generation
        return fut

    def shutdown(self) -> None:
        with self._lock:
            self._closed = True
            pool = self._pool
        pool.shutdown(wait=False, cancel_futures=True)


class Loader:
    """Iterable over batches of a FlowDataset(-like) object.

    len(dataset) defines an epoch; iteration is endless (the trainer's
    should_keep_training loop decides when to stop, train.py:163).
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        *,
        shuffle: bool = True,
        drop_last: bool = True,
        seed: int = 1234,
        num_workers: int = 4,
        prefetch: int = 4,
        process_index: int = 0,
        process_count: int = 1,
        worker_mode: str = "thread",
        mp_start_method: str = "fork",
        max_retries: int = 3,
        retry_backoff_s: float = 0.05,
    ):
        if batch_size % process_count:
            raise ValueError(
                f"global batch {batch_size} must divide over {process_count} hosts")
        self.dataset = dataset
        self.global_batch = batch_size
        self.local_batch = batch_size // process_count
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.num_workers = max(1, num_workers)
        self.prefetch = prefetch
        self.process_index = process_index
        self.process_count = process_count
        if worker_mode not in ("thread", "process"):
            raise ValueError(f"worker_mode must be thread|process, got {worker_mode!r}")
        # "process" sidesteps the GIL for the Python/numpy share of
        # decode+augment (the reference's DataLoader runs 4 worker
        # PROCESSES for the same reason, core/datasets.py:234). Prefer
        # constructing the Loader BEFORE heavy jax/TPU init when using
        # the default fork start method, or pass mp_start_method="spawn".
        self.worker_mode = worker_mode
        self.mp_start_method = mp_start_method
        # decode-fault budget: a sample gets max_retries re-submissions
        # (exponential backoff from retry_backoff_s) before it is
        # skipped and its batch slot backfilled
        self.max_retries = max(0, max_retries)
        self.retry_backoff_s = retry_backoff_s
        self.stats = PipelineStats()
        # (epoch, offset) of each YIELDED batch, in yield order — the
        # trainer pops one entry per batch it consumes, so its stream
        # position stays exact even when a batch with no surviving
        # samples is dropped without a yield (the position of a dropped
        # batch never enters the queue); alignment survives any
        # prefetch depth because both sides are strictly FIFO. maxlen
        # bounds the memory of consumers that never pop (benches,
        # plain `for b in loader:` users) — a popping consumer can lag
        # at most its prefetch depth, far under the bound
        self.positions: "collections.deque[Tuple[int, int]]" = (
            collections.deque(maxlen=64))

    def __len__(self) -> int:
        n = len(self.dataset) // self.global_batch
        if not self.drop_last and len(self.dataset) % self.global_batch:
            n += 1
        return n

    def _epoch_order(self, epoch: int) -> np.ndarray:
        if self.shuffle:
            return epoch_permutation(self.seed, epoch, len(self.dataset))
        return np.arange(len(self.dataset))

    def _decode(self, epoch: int, index: int) -> Batch:
        rng = np.random.default_rng((self.seed, epoch, index))
        return self.dataset.sample(int(index), rng)

    def _note_decode_ok(self) -> None:
        """Hook: a sample decoded successfully (RecordLoader counts
        record reads here; the base loader keeps no per-success stat)."""

    def _note_decode_error(self, exc: BaseException) -> None:
        """Hook: one decode attempt failed with ``exc`` — called BEFORE
        the retry/skip accounting, so subclasses can classify the fault
        (e.g. RecordLoader counting CRC failures) without changing the
        retry discipline."""

    def _resolve(self, pools: _PoolManager, epoch: int, index: int, fut):
        """One sample's result, with bounded retry: pool breakage
        rebuilds + resubmits, decode errors resubmit with backoff, and
        a sample still failing after the budget is skipped (None)."""
        attempt = 0
        while True:
            try:
                sample = fut.result()
                pools.note_success()
                self._note_decode_ok()
                return sample
            except BrokenExecutor:
                # the pool died under this future; rebuild the future's
                # OWN generation (idempotent under races: a concurrent
                # observer of the same break rebuilds once) and charge
                # one attempt — a sample that deterministically kills
                # its worker must exhaust the budget, not rebuild pools
                # forever
                pools.rebuild(getattr(fut, "pool_generation", 0))
            except Exception as e:
                self._note_decode_error(e)  # classify, then retry below
            attempt += 1
            if attempt > self.max_retries:
                self.stats.skipped_samples += 1
                print(f"[loader] sample (epoch {epoch}, index {index}) "
                      f"failed {attempt} attempt(s); skipping it "
                      f"({self.stats.skipped_samples} skipped so far)",
                      flush=True)
                return None
            self.stats.retries += 1
            time.sleep(self.retry_backoff_s * (2 ** (attempt - 1)))
            fut = pools.submit(epoch, index)

    def batches(self, start_epoch: int = 0,
                start_offset: int = 0) -> Iterator[Batch]:
        """Endless batch stream; this host's slice of each global batch.

        start_epoch/start_offset position the stream at global batch
        `start_offset` of `start_epoch` — with the counter-based PRNG
        streams this reproduces the EXACT sample sequence an
        interrupted run would have consumed next (resilience.stream).
        """
        if len(self) > 0:  # normalize an offset past the epoch end
            start_epoch += start_offset // len(self)
            start_offset %= len(self)
        pools = _PoolManager(self)
        out: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        self.positions.clear()  # one live stream per Loader

        # a trailing partial global batch cannot be split evenly across
        # hosts — some would yield one more batch than others and the
        # sharded step's collectives would deadlock; always drop it when
        # multi-host
        drop_last = self.drop_last or self.process_count > 1

        def submit_loop():
            epoch = start_epoch
            skip = start_offset * self.global_batch
            try:
                while not stop.is_set():
                    order = self._epoch_order(epoch)
                    usable = (len(order) // self.global_batch
                              * self.global_batch
                              if drop_last else len(order))
                    for b0 in range(skip, usable, self.global_batch):
                        lo = b0 + self.process_index * self.local_batch
                        ids = order[lo:lo + self.local_batch]
                        if len(ids) == 0:
                            continue
                        # tagged with the batch's (epoch, offset) so the
                        # consumer can publish the exact position of
                        # every yielded batch (dropped ones never are)
                        work = (epoch, b0 // self.global_batch,
                                [(int(i), pools.submit(epoch, i))
                                 for i in ids])
                        while not stop.is_set():  # never park forever on put
                            try:
                                out.put(work, timeout=0.1)
                                break
                            except queue.Full:
                                continue
                        if stop.is_set():
                            return
                    epoch += 1
                    skip = 0
            except BaseException as e:
                # a fatal feeder error (e.g. the pool-rebuild bound) must
                # surface in the CONSUMER, not die with this thread while
                # the trainer blocks on a batch that will never come
                while not stop.is_set():
                    try:
                        out.put(_FeederError(e), timeout=0.1)
                        return
                    except queue.Full:
                        continue

        feeder = threading.Thread(target=submit_loop, daemon=True)
        feeder.start()
        try:
            while True:
                work = out.get()
                if isinstance(work, _FeederError):
                    raise work.exc
                epoch_b, offset_b, pairs = work
                samples = [self._resolve(pools, epoch_b, i, f)
                           for i, f in pairs]
                good = [s for s in samples if s is not None]
                if not good:
                    # nothing in this batch survived; drop it rather
                    # than fabricate data (single-host only: a
                    # multi-host run would need a collective agreement
                    # to drop, see docs/resilience.md). No position is
                    # published: the trainer never consumed this offset,
                    # so resume will revisit (and re-drop) it
                    self.stats.dropped_batches += 1
                    print(f"[loader] batch with no surviving samples "
                          f"dropped ({self.stats.dropped_batches} so far)",
                          flush=True)
                    continue
                n_good = len(good)
                while len(good) < len(pairs):
                    # backfill skipped slots by replicating survivors —
                    # batch shape stays stable (one compiled step), and
                    # a duplicated good sample beats a crashed run
                    good.append(good[len(good) % n_good])
                self.positions.append((epoch_b, offset_b))
                yield _stack(good)
        finally:
            stop.set()
            pools.shutdown()

    def __iter__(self) -> Iterator[Batch]:
        return self.batches()
