"""Training-time augmentation, numpy-native with explicit PRNG.

Re-implements the reference augmentors (core/utils/augmentor.py) without
torch/torchvision: photometric jitter (brightness/contrast/saturation/hue
in random order, matching torchvision.ColorJitter semantics), occlusion
eraser, random scale+stretch, flips, and crop; plus the sparse variant
that re-splats valid flow vectors after resize
(core/utils/augmentor.py:161-193).

TPU-first difference: every random draw comes from an explicit
numpy Generator passed per sample, so the whole pipeline is replayable
from (seed, epoch, index) — the reference's global np.random state is
only per-worker seeded (core/datasets.py:45-51) and not reproducible.

Edge-lockstep: augmentors accept an optional second image pair that gets
the SAME photometric and spatial transforms. The reference instead runs
its augmentor twice with fresh random draws (core/datasets_seperate.py:85-89),
so its edge maps see different crops than the images — a bug we fix
(documented divergence).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


def _resize(img: np.ndarray, fx: float, fy: float) -> np.ndarray:
    import cv2

    return cv2.resize(img, None, fx=fx, fy=fy, interpolation=cv2.INTER_LINEAR)


class ColorJitter:
    """torchvision-compatible photometric jitter on uint8 RGB.

    Factors: brightness/contrast/saturation ~ U[max(0,1-x), 1+x],
    hue ~ U[-h, h] (fraction of the hue circle); the four ops are applied
    in random order, like torchvision.transforms.ColorJitter.
    """

    def __init__(self, brightness: float = 0.0, contrast: float = 0.0,
                 saturation: float = 0.0, hue: float = 0.0):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.hue = hue

    @staticmethod
    def _blend(img: np.ndarray, other: np.ndarray, factor: float) -> np.ndarray:
        # in-place over one f32 buffer (same f32 math, value-identical;
        # the naive expression allocates three full-image temporaries)
        out = img.astype(np.float32)
        out *= factor
        out += (1.0 - factor) * other
        np.clip(out, 0, 255, out=out)
        return out.astype(np.uint8)

    def __call__(self, rng: np.random.Generator, img: np.ndarray) -> np.ndarray:
        import cv2

        ops = []
        if self.brightness > 0:
            f = rng.uniform(max(0.0, 1 - self.brightness), 1 + self.brightness)
            ops.append(("brightness", f))
        if self.contrast > 0:
            f = rng.uniform(max(0.0, 1 - self.contrast), 1 + self.contrast)
            ops.append(("contrast", f))
        if self.saturation > 0:
            f = rng.uniform(max(0.0, 1 - self.saturation), 1 + self.saturation)
            ops.append(("saturation", f))
        if self.hue > 0:
            ops.append(("hue", rng.uniform(-self.hue, self.hue)))

        # brightness/contrast blend each pixel against a SCALAR, so on
        # uint8 input they are exact 256-entry lookup tables — cv2.LUT
        # replaces two full-image float passes (the profiled hot spot of
        # the whole host pipeline) at bit-identical output: the table is
        # built with the same f32 multiply-add + truncating cast per
        # possible value that _blend applies per pixel
        ramp = np.arange(256, dtype=np.float32)
        for i in rng.permutation(len(ops)):
            name, f = ops[i]
            if name == "brightness":
                lut = np.clip(f * ramp, 0, 255).astype(np.uint8)
                img = cv2.LUT(img, lut)
            elif name == "contrast":
                # cv2.mean agrees with ndarray.mean to fp rounding and
                # is far cheaper
                gray_mean = cv2.mean(cv2.cvtColor(img, cv2.COLOR_RGB2GRAY))[0]
                lut = np.clip(f * ramp + (1.0 - f) * np.float32(gray_mean),
                              0, 255).astype(np.uint8)
                img = cv2.LUT(img, lut)
            elif name == "saturation":
                gray = cv2.cvtColor(img, cv2.COLOR_RGB2GRAY)[..., None]
                img = self._blend(img, gray.astype(np.float32), f)
            else:  # hue: shift in HSV; cv2 uint8 hue is degrees/2 in [0,180)
                hsv = cv2.cvtColor(img, cv2.COLOR_RGB2HSV)
                shift = int(round(f * 180.0)) % 180
                # int16 intermediate: uint8 would wrap at 256 before the mod
                hue = (hsv[..., 0].astype(np.int16) + shift) % 180
                hsv[..., 0] = hue.astype(np.uint8)
                img = cv2.cvtColor(hsv, cv2.COLOR_HSV2RGB)
        return img


Pair = Tuple[np.ndarray, np.ndarray]


class FlowAugmentor:
    """Dense-flow augmentation (core/utils/augmentor.py:15-120)."""

    def __init__(self, crop_size: Sequence[int], min_scale: float = -0.2,
                 max_scale: float = 0.5, do_flip: bool = True):
        self.crop_size = tuple(crop_size)
        self.min_scale = min_scale
        self.max_scale = max_scale
        self.spatial_aug_prob = 0.8
        self.stretch_prob = 0.8
        self.max_stretch = 0.2
        self.do_flip = do_flip
        self.h_flip_prob = 0.5
        self.v_flip_prob = 0.1
        self.photo_aug = ColorJitter(0.4, 0.4, 0.4, 0.5 / 3.14)
        self.asymmetric_color_aug_prob = 0.2
        self.eraser_aug_prob = 0.5
        self.eraser_bounds = (50, 100)

    def color_transform(self, rng, img1, img2) -> Pair:
        if rng.random() < self.asymmetric_color_aug_prob:
            return self.photo_aug(rng, img1), self.photo_aug(rng, img2)
        stack = np.concatenate([img1, img2], axis=0)
        stack = self.photo_aug(rng, stack)
        out1, out2 = np.split(stack, 2, axis=0)
        return out1, out2

    def eraser_transform(self, rng, img1, img2) -> Pair:
        """Occlusion aug: paint random rects of img2 with its mean color."""
        ht, wd = img1.shape[:2]
        if rng.random() < self.eraser_aug_prob:
            img2 = img2.copy()
            mean_color = img2.reshape(-1, 3).mean(axis=0)
            for _ in range(rng.integers(1, 3)):
                x0 = rng.integers(0, wd)
                y0 = rng.integers(0, ht)
                dx = rng.integers(*self.eraser_bounds)
                dy = rng.integers(*self.eraser_bounds)
                img2[y0:y0 + dy, x0:x0 + dx, :] = mean_color
        return img1, img2

    def _sample_scales(self, rng, ht: int, wd: int) -> Tuple[float, float]:
        min_scale = max((self.crop_size[0] + 8) / float(ht),
                        (self.crop_size[1] + 8) / float(wd))
        scale = 2 ** rng.uniform(self.min_scale, self.max_scale)
        sx = sy = scale
        if rng.random() < self.stretch_prob:
            sx *= 2 ** rng.uniform(-self.max_stretch, self.max_stretch)
            sy *= 2 ** rng.uniform(-self.max_stretch, self.max_stretch)
        return max(sx, min_scale), max(sy, min_scale)

    def spatial_transform(self, rng, img1, img2, flow,
                          extras: Optional[List[np.ndarray]] = None):
        ht, wd = img1.shape[:2]
        sx, sy = self._sample_scales(rng, ht, wd)
        extras = list(extras) if extras else []

        # float32 multipliers: a python-list factor would promote the
        # whole flow map to float64 (2x host memory traffic per pass)
        if rng.random() < self.spatial_aug_prob:
            img1 = _resize(img1, sx, sy)
            img2 = _resize(img2, sx, sy)
            flow = _resize(flow, sx, sy) * np.array([sx, sy], np.float32)
            extras = [_resize(e, sx, sy) for e in extras]

        if self.do_flip:
            if rng.random() < self.h_flip_prob:
                img1, img2 = img1[:, ::-1], img2[:, ::-1]
                flow = flow[:, ::-1] * np.array([-1.0, 1.0], np.float32)
                extras = [e[:, ::-1] for e in extras]
            if rng.random() < self.v_flip_prob:
                img1, img2 = img1[::-1], img2[::-1]
                flow = flow[::-1] * np.array([1.0, -1.0], np.float32)
                extras = [e[::-1] for e in extras]

        y0 = rng.integers(0, img1.shape[0] - self.crop_size[0])
        x0 = rng.integers(0, img1.shape[1] - self.crop_size[1])
        sl = np.s_[y0:y0 + self.crop_size[0], x0:x0 + self.crop_size[1]]
        img1, img2, flow = img1[sl], img2[sl], flow[sl]
        extras = [e[sl] for e in extras]
        return img1, img2, flow, extras

    def __call__(self, rng: np.random.Generator, img1, img2, flow,
                 edges: Optional[Pair] = None):
        """Returns (img1, img2, flow[, em1, em2]) contiguous float-ready."""
        img1, img2 = self.color_transform(rng, img1, img2)
        img1, img2 = self.eraser_transform(rng, img1, img2)
        extras = list(edges) if edges is not None else []
        img1, img2, flow, extras = self.spatial_transform(rng, img1, img2, flow, extras)
        out = [np.ascontiguousarray(img1), np.ascontiguousarray(img2),
               np.ascontiguousarray(flow)]
        out += [np.ascontiguousarray(e) for e in extras]
        return tuple(out)


class SparseFlowAugmentor:
    """Sparse-flow (KITTI/HD1K) augmentation (core/utils/augmentor.py:122-246)."""

    def __init__(self, crop_size: Sequence[int], min_scale: float = -0.2,
                 max_scale: float = 0.5, do_flip: bool = False):
        self.crop_size = tuple(crop_size)
        self.min_scale = min_scale
        self.max_scale = max_scale
        self.spatial_aug_prob = 0.8
        self.do_flip = do_flip
        self.h_flip_prob = 0.5
        self.photo_aug = ColorJitter(0.3, 0.3, 0.3, 0.3 / 3.14)
        self.eraser_aug_prob = 0.5
        self.eraser_bounds = (50, 100)
        self.margin_y = 20
        self.margin_x = 50

    def color_transform(self, rng, img1, img2) -> Pair:
        stack = np.concatenate([img1, img2], axis=0)
        stack = self.photo_aug(rng, stack)
        out1, out2 = np.split(stack, 2, axis=0)
        return out1, out2

    eraser_transform = FlowAugmentor.eraser_transform

    @staticmethod
    def resize_sparse_flow_map(flow, valid, fx: float, fy: float):
        """Re-splat valid flow vectors onto the scaled integer grid.

        Bilinear resize would smear invalid zeros into valid pixels; the
        reference instead scatters each valid vector to its rounded new
        location (core/utils/augmentor.py:161-193, exclusive-0 bound kept).
        """
        ht, wd = flow.shape[:2]
        coords = np.stack(np.meshgrid(np.arange(wd), np.arange(ht)), axis=-1)
        coords = coords.reshape(-1, 2).astype(np.float32)
        flow_flat = flow.reshape(-1, 2).astype(np.float32)
        valid_flat = valid.reshape(-1) >= 1

        coords0 = coords[valid_flat]
        flow0 = flow_flat[valid_flat]

        ht1 = int(round(ht * fy))
        wd1 = int(round(wd * fx))
        # float64 kept deliberately (unlike the dense-path multipliers):
        # np.round on these decides each vector's integer splat
        # destination, and the reference computes them in float64 too —
        # the temporaries are small (valid points only)
        coords1 = coords0 * [fx, fy]
        flow1 = flow0 * [fx, fy]

        xx = np.round(coords1[:, 0]).astype(np.int32)
        yy = np.round(coords1[:, 1]).astype(np.int32)
        keep = (xx > 0) & (xx < wd1) & (yy > 0) & (yy < ht1)

        flow_img = np.zeros([ht1, wd1, 2], np.float32)
        valid_img = np.zeros([ht1, wd1], np.float32)
        flow_img[yy[keep], xx[keep]] = flow1[keep]
        valid_img[yy[keep], xx[keep]] = 1.0
        return flow_img, valid_img

    def spatial_transform(self, rng, img1, img2, flow, valid,
                          extras: Optional[List[np.ndarray]] = None):
        ht, wd = img1.shape[:2]
        min_scale = max((self.crop_size[0] + 1) / float(ht),
                        (self.crop_size[1] + 1) / float(wd))
        scale = max(2 ** rng.uniform(self.min_scale, self.max_scale), min_scale)
        extras = list(extras) if extras else []

        if rng.random() < self.spatial_aug_prob:
            img1 = _resize(img1, scale, scale)
            img2 = _resize(img2, scale, scale)
            flow, valid = self.resize_sparse_flow_map(flow, valid, scale, scale)
            extras = [_resize(e, scale, scale) for e in extras]

        if self.do_flip and rng.random() < self.h_flip_prob:
            img1, img2 = img1[:, ::-1], img2[:, ::-1]
            # float32 multiplier (sign flip is exact in any dtype; a
            # python list would promote the map to float64)
            flow = flow[:, ::-1] * np.array([-1.0, 1.0], np.float32)
            valid = valid[:, ::-1]
            extras = [e[:, ::-1] for e in extras]

        # crop window may start above/left of the frame by a margin,
        # then clipped — biases KITTI crops toward the road region
        y0 = rng.integers(0, img1.shape[0] - self.crop_size[0] + self.margin_y)
        x0 = rng.integers(-self.margin_x,
                          img1.shape[1] - self.crop_size[1] + self.margin_x)
        y0 = int(np.clip(y0, 0, img1.shape[0] - self.crop_size[0]))
        x0 = int(np.clip(x0, 0, img1.shape[1] - self.crop_size[1]))
        sl = np.s_[y0:y0 + self.crop_size[0], x0:x0 + self.crop_size[1]]
        img1, img2, flow, valid = img1[sl], img2[sl], flow[sl], valid[sl]
        extras = [e[sl] for e in extras]
        return img1, img2, flow, valid, extras

    def __call__(self, rng: np.random.Generator, img1, img2, flow, valid,
                 edges: Optional[Pair] = None):
        img1, img2 = self.color_transform(rng, img1, img2)
        img1, img2 = self.eraser_transform(rng, img1, img2)
        extras = list(edges) if edges is not None else []
        img1, img2, flow, valid, extras = self.spatial_transform(
            rng, img1, img2, flow, valid, extras)
        out = [np.ascontiguousarray(img1), np.ascontiguousarray(img2),
               np.ascontiguousarray(flow), np.ascontiguousarray(valid)]
        out += [np.ascontiguousarray(e) for e in extras]
        return tuple(out)
