"""ctypes bridge to the native decode library (native/dexiraft_native.cpp).

Builds the shared object on first use with g++ (cached under
native/build/), falls back to the pure-Python codecs when the toolchain
or library is unavailable, and honors DEXIRAFT_NO_NATIVE=1. Batch decodes
release the GIL for the whole call — C++ threads do the file I/O.
"""

from __future__ import annotations

import ctypes
import os
import os.path as osp
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

_REPO_ROOT = osp.dirname(osp.dirname(osp.dirname(osp.abspath(__file__))))
_SRC = osp.join(_REPO_ROOT, "native", "dexiraft_native.cpp")
_SO = osp.join(_REPO_ROOT, "native", "build", "libdexiraft_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> Optional[str]:
    if not osp.exists(_SRC):
        return None
    os.makedirs(osp.dirname(_SO), exist_ok=True)
    if (osp.exists(_SO)
            and os.stat(_SO).st_mtime >= os.stat(_SRC).st_mtime):
        return _SO
    # compile to a private temp path, then atomically publish: concurrent
    # processes must never dlopen a half-written ELF
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
           _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return _SO


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded library, building it on first call; None if unavailable."""
    global _lib, _tried
    if os.environ.get("DEXIRAFT_NO_NATIVE") == "1":
        return None
    with _lock:
        if _tried:
            return _lib
        _tried = True
        so = _build()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            return None
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.drn_read_flo.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                     ctypes.c_int64, i32p, i32p]
        lib.drn_read_ppm.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                     ctypes.c_int64, i32p, i32p]
        lib.drn_read_flo_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int32, ctypes.c_void_p,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32]
        lib.drn_read_ppm_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int32, ctypes.c_void_p,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32]
        for fn in (lib.drn_read_flo, lib.drn_read_ppm,
                   lib.drn_read_flo_batch, lib.drn_read_ppm_batch):
            fn.restype = ctypes.c_int32
        _lib = lib
        return _lib


def read_flo_native(path) -> Optional[np.ndarray]:
    """(H, W, 2) float32, or None when the native path is unavailable OR
    declines the file (caller falls through to the Python codec, which
    owns the descriptive errors). One open, one call: the buffer is sized
    from the file length (payload = size - 12-byte header)."""
    lib = get_lib()
    if lib is None:
        return None
    try:
        n = (os.stat(path).st_size - 12) // 4
    except OSError:
        return None
    if n <= 0:
        return None
    flat = np.empty(n, np.float32)
    w = ctypes.c_int32()
    h = ctypes.c_int32()
    rc = lib.drn_read_flo(os.fspath(path).encode(),
                          flat.ctypes.data_as(ctypes.c_void_p), n,
                          ctypes.byref(w), ctypes.byref(h))
    if rc != 0 or int(h.value) * int(w.value) * 2 != n:
        return None
    return flat.reshape(int(h.value), int(w.value), 2)


def read_ppm_native(path) -> Optional[np.ndarray]:
    """(H, W, 3) uint8, or None when unavailable or declined (e.g. ASCII
    P3 or 16-bit PPMs go back to imageio). Buffer bounded by file size."""
    lib = get_lib()
    if lib is None:
        return None
    try:
        cap = os.stat(path).st_size  # >= payload (header is extra slack)
    except OSError:
        return None
    flat = np.empty(cap, np.uint8)
    w = ctypes.c_int32()
    h = ctypes.c_int32()
    rc = lib.drn_read_ppm(os.fspath(path).encode(),
                          flat.ctypes.data_as(ctypes.c_void_p), cap,
                          ctypes.byref(w), ctypes.byref(h))
    if rc != 0:
        return None
    n = int(h.value) * int(w.value) * 3
    if n > cap:
        return None
    return flat[:n].reshape(int(h.value), int(w.value), 3)


def _paths_array(paths: Sequence[str]):
    arr = (ctypes.c_char_p * len(paths))()
    arr[:] = [os.fspath(p).encode() for p in paths]
    return arr


def read_flo_batch(paths: Sequence[str], height: int, width: int,
                   nthreads: int = 8) -> Optional[np.ndarray]:
    """(N, H, W, 2) float32 in one GIL-free call; None if unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    out = np.empty((len(paths), height, width, 2), np.float32)
    rc = lib.drn_read_flo_batch(_paths_array(paths), len(paths),
                                out.ctypes.data_as(ctypes.c_void_p),
                                width, height, nthreads)
    if rc != 0:
        raise IOError(f"native batch decode failed ({rc})")
    return out


def read_ppm_batch(paths: Sequence[str], height: int, width: int,
                   nthreads: int = 8) -> Optional[np.ndarray]:
    """(N, H, W, 3) uint8 in one GIL-free call; None if unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    out = np.empty((len(paths), height, width, 3), np.uint8)
    rc = lib.drn_read_ppm_batch(_paths_array(paths), len(paths),
                                out.ctypes.data_as(ctypes.c_void_p),
                                width, height, nthreads)
    if rc != 0:
        raise IOError(f"native batch decode failed ({rc})")
    return out
