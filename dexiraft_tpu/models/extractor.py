"""Feature/context encoders at 1/8 resolution.

Flax re-design of the reference encoders (core/extractor.py): BasicEncoder
(residual blocks, 64->96->128 channels) and SmallEncoder (bottleneck
blocks, 32->64->96), one shared scaffold parameterized by block type and
stage widths, with the 4 norm modes and Kaiming fan-out init. NHWC
throughout; ``dtype`` is the compute dtype (bf16 under mixed precision),
params stay fp32.

``train`` gates dropout; ``bn_train`` (defaulting to ``train``) gates
BatchNorm statistics separately — the reference's freeze_bn only switches
BatchNorm to eval while dropout stays governed by module training mode
(core/raft.py:73-76 vs. core/extractor.py:186).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from dexiraft_tpu.models.layers import kaiming_normal_out, make_norm


class ResidualBlock(nn.Module):
    """Two 3x3 convs + skip; 1x1-conv downsample when strided.

    Reference: core/extractor.py:6-56.
    """

    planes: int
    norm_fn: str = "group"
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, bn_train: bool = False):
        groups = self.planes // 8
        conv = lambda k, s: nn.Conv(  # noqa: E731
            self.planes, (k, k), strides=(s, s), padding=k // 2,
            kernel_init=kaiming_normal_out, dtype=self.dtype,
        )
        y = nn.relu(make_norm(self.norm_fn, groups, bn_train, self.dtype)(conv(3, self.stride)(x)))
        y = nn.relu(make_norm(self.norm_fn, groups, bn_train, self.dtype)(conv(3, 1)(y)))

        if self.stride != 1:
            x = conv(1, self.stride)(x)
            x = make_norm(self.norm_fn, groups, bn_train, self.dtype)(x)

        return nn.relu(x + y)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3(strided) -> 1x1 bottleneck (planes//4 inner width).

    Reference: core/extractor.py:60-116.
    """

    planes: int
    norm_fn: str = "group"
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, bn_train: bool = False):
        groups = self.planes // 8
        quarter = self.planes // 4

        def conv(features, k, s=1):
            return nn.Conv(
                features, (k, k), strides=(s, s), padding=k // 2,
                kernel_init=kaiming_normal_out, dtype=self.dtype,
            )

        y = nn.relu(make_norm(self.norm_fn, groups, bn_train, self.dtype)(conv(quarter, 1)(x)))
        y = nn.relu(make_norm(self.norm_fn, groups, bn_train, self.dtype)(conv(quarter, 3, self.stride)(y)))
        y = nn.relu(make_norm(self.norm_fn, groups, bn_train, self.dtype)(conv(self.planes, 1)(y)))

        if self.stride != 1:
            x = conv(self.planes, 1, self.stride)(x)
            x = make_norm(self.norm_fn, groups, bn_train, self.dtype)(x)

        return nn.relu(x + y)


class Encoder(nn.Module):
    """Shared encoder scaffold: 7x7/2 stem -> 3 block stages -> 1x1 projection.

    Output is 1/8 resolution. Accepts a tuple of images and concatenates
    them on the batch dim (the reference's list-input batching trick,
    core/extractor.py:168-191).
    """

    output_dim: int = 128
    norm_fn: str = "batch"
    dropout: float = 0.0
    dtype: Any = jnp.float32
    block: str = "residual"  # residual (Basic) | bottleneck (Small)
    stem_width: int = 64
    stages: Tuple[Tuple[int, int], ...] = ((64, 1), (96, 2), (128, 2))

    @nn.compact
    def __call__(
        self,
        x: Union[jax.Array, Sequence[jax.Array]],
        train: bool = False,
        bn_train: Optional[bool] = None,
    ):
        if bn_train is None:
            bn_train = train
        block_cls = ResidualBlock if self.block == "residual" else BottleneckBlock

        is_list = isinstance(x, (tuple, list))
        if is_list:
            batch_dim = x[0].shape[0]
            x = jnp.concatenate(x, axis=0)

        x = nn.Conv(self.stem_width, (7, 7), strides=(2, 2), padding=3,
                    kernel_init=kaiming_normal_out, dtype=self.dtype)(x)
        x = nn.relu(make_norm(self.norm_fn, 8, bn_train, self.dtype)(x))

        for planes, stride in self.stages:
            x = block_cls(planes, self.norm_fn, stride, self.dtype)(x, bn_train)
            x = block_cls(planes, self.norm_fn, 1, self.dtype)(x, bn_train)

        x = nn.Conv(self.output_dim, (1, 1), kernel_init=kaiming_normal_out,
                    dtype=self.dtype)(x)

        if self.dropout > 0.0:
            # channel dropout (torch Dropout2d) — broadcast over spatial dims;
            # gated by train, NOT bn_train (freeze_bn must not disable dropout)
            x = nn.Dropout(self.dropout, broadcast_dims=(1, 2), deterministic=not train)(x)

        if is_list:
            return x[:batch_dim], x[batch_dim:]
        return x


#: Stage (planes, stride) schedules — shared by the encoder factories
#: below and the declarative conv chains so the two cannot drift.
BASIC_STAGES: Tuple[Tuple[int, int], ...] = ((64, 1), (96, 2), (128, 2))
SMALL_STAGES: Tuple[Tuple[int, int], ...] = ((32, 1), (64, 2), (96, 2))


def BasicEncoder(output_dim=128, norm_fn="batch", dropout=0.0, dtype=jnp.float32, name=None):
    """Residual encoder (64, 96/2, 128/2). Reference: core/extractor.py:118-192."""
    return Encoder(output_dim, norm_fn, dropout, dtype, block="residual",
                   stem_width=64, stages=BASIC_STAGES, name=name)


def SmallEncoder(output_dim=128, norm_fn="batch", dropout=0.0, dtype=jnp.float32, name=None):
    """Bottleneck encoder (32, 64/2, 96/2). Reference: core/extractor.py:195-267."""
    return Encoder(output_dim, norm_fn, dropout, dtype, block="bottleneck",
                   stem_width=32, stages=SMALL_STAGES, name=name)


# --------------------------------------------------------------------------
# Declarative H-axis conv chains — the halo machinery's source of truth
# --------------------------------------------------------------------------

#: One chain entry per conv, (kernel, stride, padding) along the H axis,
#: in forward order. parallel/halo.py composes these into each module's
#: receptive-field halo width (``halo_rows``), so they are pinned NEXT
#: to the convs they describe — a kernel-size change here is a one-line
#: diff away from the exchange width that must follow it, instead of
#: folklore in a distant table.


def block_conv_chain(block: str, stride: int) -> Tuple[Tuple[int, int, int], ...]:
    """Deepest sequential H-axis conv path of one block. The 1x1 skip
    conv is a parallel path with zero halo and is omitted — the halo a
    block needs is governed by its longest path."""
    if block == "residual":
        return ((3, stride, 1), (3, 1, 1))
    return ((1, 1, 0), (3, stride, 1), (1, 1, 0))


def encoder_conv_chain(block: str = "residual") -> Tuple[Tuple[int, int, int], ...]:
    """The full sequential H-axis conv chain of one Encoder forward:
    7x7/2 stem -> two blocks per stage -> 1x1 projection."""
    stages = BASIC_STAGES if block == "residual" else SMALL_STAGES
    chain = [(7, 2, 3)]
    for _, stride in stages:
        chain += list(block_conv_chain(block, stride))
        chain += list(block_conv_chain(block, 1))
    chain.append((1, 1, 0))
    return tuple(chain)
