"""Update operators: motion encoders, ConvGRU cells, flow/mask heads.

Flax re-design of the reference update blocks (core/update.py) plus the
corrected RefineFlow fusion head from the v3 variant (core/update_3.py:138-151
— the reference's version outputs 1 channel where flow needs 2, which made
v3 diverge; ours outputs 2 and documents the deviation).

The motion encoders own the fused refinement-step seam
(config.fused_update): their first layer — the 1x1 conv over the
(2r+1)^2-per-level correlation features — is exactly a per-pixel matmul,
so it can run INSIDE the Pallas lookup kernel while each pixel block's
correlation window is still VMEM-resident (ops/pallas_corr.py
pallas_fused_step). ``FusedCorrEncoder`` declares parameters with the
same names/shapes/initializers as the ``nn.Conv`` it replaces, under the
same module name ("Conv_0"), so the parameter tree — and therefore every
checkpoint and the torch interop name map (interop/torch_convert.py) —
is identical with and without fusion. The convs are explicitly named
with the auto-names they have always had, pinning that contract.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class FlowHead(nn.Module):
    """conv3x3 -> relu -> conv3x3 to a 2-channel flow delta.

    Reference: core/update.py:6-14.
    """

    hidden_dim: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Conv(self.hidden_dim, (3, 3), padding=1, dtype=self.dtype)(x))
        return nn.Conv(2, (3, 3), padding=1, dtype=self.dtype)(x)


class ConvGRU(nn.Module):
    """3x3 convolutional GRU. Reference: core/update.py:16-31."""

    hidden_dim: int = 128
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, h, x):
        hx = jnp.concatenate([h, x], axis=-1)
        z = nn.sigmoid(nn.Conv(self.hidden_dim, (3, 3), padding=1, dtype=self.dtype)(hx))
        r = nn.sigmoid(nn.Conv(self.hidden_dim, (3, 3), padding=1, dtype=self.dtype)(hx))
        q = nn.tanh(
            nn.Conv(self.hidden_dim, (3, 3), padding=1, dtype=self.dtype)(
                jnp.concatenate([r * h, x], axis=-1)
            )
        )
        return (1 - z) * h + z * q


class SepConvGRU(nn.Module):
    """Separable GRU: a 1x5 horizontal pass then a 5x1 vertical pass.

    Reference: core/update.py:33-60.
    """

    hidden_dim: int = 128
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, h, x):
        def gru_pass(h, x, ksize):
            conv = lambda: nn.Conv(  # noqa: E731
                self.hidden_dim, ksize,
                padding=((ksize[0] // 2, ksize[0] // 2), (ksize[1] // 2, ksize[1] // 2)),
                dtype=self.dtype,
            )
            hx = jnp.concatenate([h, x], axis=-1)
            z = nn.sigmoid(conv()(hx))
            r = nn.sigmoid(conv()(hx))
            q = nn.tanh(conv()(jnp.concatenate([r * h, x], axis=-1)))
            return (1 - z) * h + z * q

        h = gru_pass(h, x, (1, 5))  # horizontal
        h = gru_pass(h, x, (5, 1))  # vertical
        return h


class FusedCorrEncoder(nn.Module):
    """The motion encoder's 1x1 corr conv, executed INSIDE the fused
    Pallas lookup kernel (pre-activation; the relu stays in XLA).

    Declares ``kernel``/``bias`` with ``nn.Conv``'s exact shapes and
    initializers, so instantiating it under the name the conv would have
    had ("Conv_0") keeps the parameter tree bit-identical to the unfused
    path — the same checkpoint serves both, which is what makes the
    fused/unfused A/B (and the parity tests) meaningful.

    Per-level int8 dequantization scales are linear, so they are folded
    into the weight's per-level row blocks here, in XLA, before the
    kernel sees them — the kernel reads the pyramid in its storage
    dtype. (The 1/sqrt(C) correlation normalization is NOT folded: the
    kernel applies it itself, like every other corr path.)
    """

    features: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, pyr, coords):
        from dexiraft_tpu.ops.pallas_corr import (
            flash_fused_step,
            pallas_fused_step,
        )

        num_levels = len(pyr.fmap2_pyramid)
        win = 2 * pyr.radius + 1
        in_ch = num_levels * win * win
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (1, 1, in_ch, self.features))
        bias = self.param("bias", nn.initializers.zeros_init(),
                          (self.features,))
        w = kernel.reshape(in_ch, self.features).astype(jnp.float32)
        if pyr.scales is not None:
            ww = win * win
            w = jnp.concatenate(
                [w[lvl * ww:(lvl + 1) * ww] * pyr.scales[lvl]
                 for lvl in range(num_levels)], axis=0)
        # flash = the blocked HBM-streaming kernel (ONE call at any
        # geometry); pallas = the per-pixel VMEM formulation with its
        # fp32 budget split. Same VJP contract, same param tree.
        step = (flash_fused_step if pyr.kernel == "flash"
                else pallas_fused_step)
        out = step(pyr.fmap1, pyr.fmap2_pyramid, coords,
                   w, bias.astype(jnp.float32), pyr.radius,
                   None, pyr.row_chunk)
        return out.astype(self.dtype)


class SmallMotionEncoder(nn.Module):
    """Embed (corr, flow) -> 82-channel motion features.

    Reference: core/update.py:62-77. ``pyr``/``coords`` select the fused
    path: the Conv_0 lookup-conv runs inside the Pallas kernel and
    ``corr`` is never materialized (pass corr=None there).
    """

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, flow, corr, pyr=None, coords=None):
        if pyr is not None:
            cor = nn.relu(FusedCorrEncoder(96, self.dtype,
                                           name="Conv_0")(pyr, coords))
        else:
            cor = nn.relu(nn.Conv(96, (1, 1), dtype=self.dtype,
                                  name="Conv_0")(corr))
        flo = nn.relu(nn.Conv(64, (7, 7), padding=3, dtype=self.dtype,
                              name="Conv_1")(flow))
        flo = nn.relu(nn.Conv(32, (3, 3), padding=1, dtype=self.dtype,
                              name="Conv_2")(flo))
        out = nn.relu(
            nn.Conv(80, (3, 3), padding=1, dtype=self.dtype, name="Conv_3")(
                jnp.concatenate([cor, flo], axis=-1)
            )
        )
        return jnp.concatenate([out, flow], axis=-1)


class BasicMotionEncoder(nn.Module):
    """Embed (corr, flow) -> 128-channel motion features.

    Reference: core/update.py:79-97. ``pyr``/``coords`` select the fused
    path (see SmallMotionEncoder).
    """

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, flow, corr, pyr=None, coords=None):
        if pyr is not None:
            cor = nn.relu(FusedCorrEncoder(256, self.dtype,
                                           name="Conv_0")(pyr, coords))
        else:
            cor = nn.relu(nn.Conv(256, (1, 1), dtype=self.dtype,
                                  name="Conv_0")(corr))
        cor = nn.relu(nn.Conv(192, (3, 3), padding=1, dtype=self.dtype,
                              name="Conv_1")(cor))
        flo = nn.relu(nn.Conv(128, (7, 7), padding=3, dtype=self.dtype,
                              name="Conv_2")(flow))
        flo = nn.relu(nn.Conv(64, (3, 3), padding=1, dtype=self.dtype,
                              name="Conv_3")(flo))
        out = nn.relu(
            nn.Conv(128 - 2, (3, 3), padding=1, dtype=self.dtype,
                    name="Conv_4")(
                jnp.concatenate([cor, flo], axis=-1)
            )
        )
        return jnp.concatenate([out, flow], axis=-1)


class SmallUpdateBlock(nn.Module):
    """Motion encoder + ConvGRU + flow head; no upsampling mask.

    Reference: core/update.py:99-112.
    """

    hidden_dim: int = 96
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, net, inp, corr, flow, pyr=None, coords=None):
        motion = SmallMotionEncoder(self.dtype)(flow, corr,
                                                pyr=pyr, coords=coords)
        net = ConvGRU(self.hidden_dim, self.dtype)(net, jnp.concatenate([inp, motion], axis=-1))
        delta_flow = FlowHead(128, self.dtype)(net)
        return net, None, delta_flow


class BasicUpdateBlock(nn.Module):
    """Motion encoder + SepConvGRU + flow head + convex-upsampling mask head.

    The mask logits are scaled by 0.25 to balance gradients
    (core/update.py:114-136).
    """

    hidden_dim: int = 128
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, net, inp, corr, flow, pyr=None, coords=None):
        motion = BasicMotionEncoder(self.dtype)(flow, corr,
                                                pyr=pyr, coords=coords)
        net = SepConvGRU(self.hidden_dim, self.dtype)(net, jnp.concatenate([inp, motion], axis=-1))
        delta_flow = FlowHead(256, self.dtype)(net)

        mask = nn.relu(nn.Conv(256, (3, 3), padding=1, dtype=self.dtype)(net))
        mask = 0.25 * nn.Conv(64 * 9, (1, 1), dtype=self.dtype)(mask)
        return net, mask, delta_flow


# --------------------------------------------------------------------------
# Declarative H-axis conv chains — the halo machinery's source of truth
# --------------------------------------------------------------------------

#: (kernel, stride, padding) along the H axis, deepest sequential path,
#: forward order — parallel/halo.py composes these into per-module
#: receptive-field halo widths (see models/extractor.py for the
#: convention). Parallel branches take the longest path: both motion
#: encoders are bounded by flow(7x7) -> 3x3 -> concat-conv(3x3); the
#: GRUs by the r -> q dependency (z is parallel to r), which for the
#: separable GRU only counts the VERTICAL (5x1) pass — the (1x5)
#: horizontal pass has H-kernel 1.
MOTION_ENCODER_CHAIN = ((7, 1, 3), (3, 1, 1), (3, 1, 1))
CONV_GRU_CHAIN = ((3, 1, 1), (3, 1, 1))
SEP_CONV_GRU_CHAIN = ((5, 1, 2), (5, 1, 2))
FLOW_HEAD_CHAIN = ((3, 1, 1), (3, 1, 1))
MASK_HEAD_CHAIN = ((3, 1, 1), (1, 1, 0))


class RefineFlow(nn.Module):
    """1x1-conv fusion of (flow_up, eflow_up) -> refined 2-channel flow.

    Capability parity with the v3 variant's refine block
    (core/update_3.py:138-151) with the output-width bug fixed: the
    reference conv maps 4 channels to **1**, shape-incompatible with the
    2-channel flow loss (this is why v3 diverged, SURVEY.md §2.5); ours
    maps 4 -> 2.
    """

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, flow_up, eflow_up):
        fused = jnp.concatenate([flow_up, eflow_up], axis=-1)
        return nn.Conv(2, (1, 1), dtype=self.dtype)(fused)
