"""Shared layer helpers: normalization factory and initializers.

The reference's four norm modes (group/batch/instance/none,
core/extractor.py:16-38) with torch-matching hyperparameters:
eps 1e-5 everywhere, BatchNorm momentum 0.1 (torch) == 0.9 (flax EMA),
InstanceNorm affine-free (torch InstanceNorm2d default affine=False).
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax.numpy as jnp

# torch kaiming_normal_(mode='fan_out', nonlinearity='relu') — the extractor
# init (core/extractor.py:150-157). Conv biases start at zero.
kaiming_normal_out = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


def make_norm(
    norm_fn: str,
    num_groups: int,
    train: bool,
    dtype=jnp.float32,
) -> Callable:
    """Return a fresh norm layer (or identity) for the given mode.

    ``num_groups`` is only used for 'group'. For 'batch', ``train`` selects
    batch statistics vs. running averages — the freeze_bn staging knob
    (train.py:149-150) maps to calling with train=False.
    """
    if norm_fn == "group":
        return nn.GroupNorm(num_groups=num_groups, epsilon=1e-5, dtype=dtype)
    if norm_fn == "batch":
        return nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5, dtype=dtype
        )
    if norm_fn == "instance":
        # per-sample, per-channel normalization; no learned affine
        return nn.GroupNorm(
            num_groups=None,
            group_size=1,
            use_scale=False,
            use_bias=False,
            epsilon=1e-5,
            dtype=dtype,
        )
    if norm_fn == "none":
        return lambda x: x
    raise ValueError(f"unknown norm_fn: {norm_fn!r}")
