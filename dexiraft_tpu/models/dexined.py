"""DexiNed (Dense Extreme Inception Network for edge detection) in Flax.

Re-design of the reference model (core/DexiNed/model.py:157-268): stem
DoubleConvBlock, dense blocks with 0.5*(new+skip) fusion, left/right
1x1-conv skip paths, transposed-conv upsamplers, and a final 1x1 fusion
over the 6 concatenated scale outputs. Returns 7 maps (6 scales + fused),
each (B, H, W, 1) of raw logits — the edge contract the v5 flow model
consumes (core/raft.py:111-123, no sigmoid).

NHWC; ``train`` toggles BatchNorm statistics (the flow model always calls
with train=False — the embedded DexiNed is frozen; note the reference
would let BN running stats drift during chairs-stage training, a bug we
do not reproduce).
"""

from __future__ import annotations

from typing import Any, List

import flax.linen as nn
import jax
import jax.numpy as jnp

xavier_normal = nn.initializers.glorot_normal()

# torch ConvTranspose2d paddings per up_scale (core/DexiNed/model.py:93-96)
_UPCONV_PAD = {1: 0, 2: 1, 3: 3, 4: 7}


def _bn(train: bool, dtype):
    return nn.BatchNorm(use_running_average=not train, momentum=0.9, epsilon=1e-5, dtype=dtype)


def _conv_transpose_torchlike(features: int, k: int, torch_pad: int, dtype,
                              impl: str = "transpose", name: str | None = None):
    """ConvTranspose matching torch's output size (in-1)*2 - 2p + k == 2*in.

    lax.conv_transpose pads the dilated input, so torch padding p maps to
    lax padding q = k - p - 1 per side (verified against torch in tests).

    ``impl="subpixel"`` computes the SAME linear map (same params, same
    outputs — tests/test_models.py pins bit-level equivalence) as four
    stride-1 phase convolutions + a depth-to-space interleave instead of
    an input-dilated convolution. On TPU the dilated formulation makes
    XLA convolve a 2x-zero-stuffed full-resolution tensor with the big
    k x k kernel (75% zero taps, awkward tiling at 1-16 channels); the
    phase form runs dense half-size convs with k/2 x k/2 kernels.
    """
    q = k - torch_pad - 1
    init = xavier_normal if features > 1 else nn.initializers.normal(0.1)
    if impl == "subpixel":
        return SubpixelConvTranspose(features, k, q, kernel_init=init,
                                     dtype=dtype, name=name)
    return nn.ConvTranspose(
        features, (k, k), strides=(2, 2), padding=((q, q), (q, q)),
        kernel_init=init, dtype=dtype, name=name,
    )


class SubpixelConvTranspose(nn.Module):
    """Exact stride-2 ConvTranspose via phase decomposition.

    Param tree ({kernel: (k,k,Cin,Cout), bias: (Cout,)}) matches
    nn.ConvTranspose, so checkpoints are interchangeable between impls
    (callers pass an explicit ConvTranspose_N name to keep paths equal).

    Derivation: conv_transpose with explicit padding q is a stride-1
    conv over the 2x-input-dilated signal. Output row 2u+a only sees
    kernel taps t with (2u + a - q + t) even, i.e. t = 2s + r_a where
    r_a = (q - a) mod 2, at input rows u + s + off_a with
    off_a = (a + r_a - q) / 2 — a plain stride-1 conv with the tap
    subset K[r_a::2] and padding (-off_a, off_a + k/2 - 1). The four
    (row, col) phases interleave into the 2x output.
    """

    features: int
    k: int
    q: int
    kernel_init: Any = xavier_normal
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        cin = x.shape[-1]
        kernel = self.param("kernel", self.kernel_init,
                            (self.k, self.k, cin, self.features))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        dt = self.dtype
        x = x.astype(dt)
        kernel = kernel.astype(dt)

        def phase_conv(ay, ax):
            ry, rx = (self.q - ay) % 2, (self.q - ax) % 2
            sub = kernel[ry::2, rx::2]
            pads = []
            for axis, (a, r) in enumerate(((ay, ry), (ax, rx))):
                off = (a + r - self.q) // 2
                pads.append((-off, off + sub.shape[axis] - 1))
            return jax.lax.conv_general_dilated(
                x, sub, window_strides=(1, 1), padding=pads,
                dimension_numbers=("NHWC", "HWIO", "NHWC"))

        rows = [jnp.stack([phase_conv(ay, 0), phase_conv(ay, 1)], axis=3)
                for ay in (0, 1)]
        out = jnp.stack(rows, axis=2)  # (B, H, 2, W, 2, C)
        b, h, _, w, _, c = out.shape
        return out.reshape(b, 2 * h, 2 * w, c) + bias.astype(dt)


class DoubleConvBlock(nn.Module):
    """conv3x3(stride)+BN+relu -> conv3x3+BN(+relu). Reference model.py:129-154."""

    mid_features: int
    out_features: int | None = None
    stride: int = 1
    use_act: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        out_features = self.out_features if self.out_features is not None else self.mid_features
        x = nn.Conv(self.mid_features, (3, 3), strides=(self.stride, self.stride),
                    padding=1, kernel_init=xavier_normal, dtype=self.dtype)(x)
        x = nn.relu(_bn(train, self.dtype)(x))
        x = nn.Conv(out_features, (3, 3), padding=1, kernel_init=xavier_normal,
                    dtype=self.dtype)(x)
        x = _bn(train, self.dtype)(x)
        if self.use_act:
            x = nn.relu(x)
        return x


class SingleConvBlock(nn.Module):
    """1x1 conv (+BN). Reference model.py:112-126."""

    out_features: int
    stride: int = 1
    use_bn: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.out_features, (1, 1), strides=(self.stride, self.stride),
                    kernel_init=xavier_normal, dtype=self.dtype)(x)
        if self.use_bn:
            x = _bn(train, self.dtype)(x)
        else:
            # torch constructs self.bn unconditionally (model.py:120) so its
            # params exist even when unused (block_cat); mirror that for
            # param-count/checkpoint parity. Output discarded -> XLA DCEs it;
            # running stats are never updated (use_running_average=True).
            _ = _bn(False, self.dtype)(x)
        return x


class DenseLayer(nn.Module):
    """relu -> conv3x3(pad 2) -> BN -> relu -> conv3x3(pad 0) -> BN, then
    0.5 * (new + skip). The asymmetric paddings cancel so spatial size is
    preserved. Reference model.py:49-69."""

    out_features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x1, x2, train: bool = False):
        y = nn.relu(x1)
        y = nn.Conv(self.out_features, (3, 3), padding=2, kernel_init=xavier_normal,
                    dtype=self.dtype)(y)
        y = nn.relu(_bn(train, self.dtype)(y))
        y = nn.Conv(self.out_features, (3, 3), padding=0, kernel_init=xavier_normal,
                    dtype=self.dtype)(y)
        y = _bn(train, self.dtype)(y)
        return 0.5 * (y + x2), x2


class DenseBlock(nn.Module):
    """Chain of DenseLayers sharing one skip input. Reference model.py:72-78."""

    num_layers: int
    out_features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x1, x2, train: bool = False):
        for _ in range(self.num_layers):
            x1, x2 = DenseLayer(self.out_features, self.dtype)(x1, x2, train)
        return x1


class UpConvBlock(nn.Module):
    """Stages of 1x1 conv + relu + 2x transposed conv; feature width 16
    except the final stage which emits 1 channel. Reference model.py:81-109.

    ``upconv`` picks the transposed-conv implementation ("transpose" or
    the numerically identical "subpixel" phase form); the param tree is
    the same either way."""

    up_scale: int
    dtype: Any = jnp.float32
    upconv: str = "subpixel"

    @nn.compact
    def __call__(self, x):
        k = 2 ** self.up_scale
        pad = _UPCONV_PAD[self.up_scale]
        for i in range(self.up_scale):
            out_features = 1 if i == self.up_scale - 1 else 16
            x = nn.Conv(out_features, (1, 1), kernel_init=xavier_normal, dtype=self.dtype)(x)
            x = nn.relu(x)
            x = _conv_transpose_torchlike(out_features, k, pad, self.dtype,
                                          impl=self.upconv,
                                          name=f"ConvTranspose_{i}")(x)
        return x


class CoFusion(nn.Module):
    """Attention-weighted fusion over the 6 stacked scale maps — the
    reference's unused alternative to the 1x1 block_cat fusion
    (core/DexiNed/model.py:25-47): two conv3x3+GroupNorm(4)+relu stages
    produce per-pixel channel attention, softmax over channels, then the
    output is the attention-weighted sum of the input channels (a convex
    combination per pixel). Input (B, H, W, C) -> (B, H, W, 1).
    """

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        # the reference applies xavier_normal to every conv weight via
        # weight_init (model.py:272-281), CoFusion's included
        init = xavier_normal
        attn = nn.Conv(64, (3, 3), padding=1, kernel_init=init,
                       dtype=self.dtype)(x)
        attn = nn.relu(nn.GroupNorm(num_groups=4, dtype=self.dtype)(attn))
        attn = nn.Conv(64, (3, 3), padding=1, kernel_init=init,
                       dtype=self.dtype)(attn)
        attn = nn.relu(nn.GroupNorm(num_groups=4, dtype=self.dtype)(attn))
        attn = nn.Conv(x.shape[-1], (3, 3), padding=1, kernel_init=init,
                       dtype=self.dtype)(attn)
        attn = jax.nn.softmax(attn, axis=-1)
        return jnp.sum(x * attn, axis=-1, keepdims=True)


def _maxpool_3x3_s2(x):
    # torch MaxPool2d(3, stride=2, padding=1): output size ceil(H/2)
    return nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))


class DexiNed(nn.Module):
    """The full network. Reference model.py:157-268.

    ``fusion`` selects the final fusion head: "cat" (the reference's live
    1x1 block_cat path, default — required for checkpoint interop) or
    "cofusion" (the reference's defined-but-unused CoFusion attention
    fusion, model.py:25-47, exposed here as a working capability).
    """

    dtype: Any = jnp.float32
    fusion: str = "cat"
    # "subpixel" is the shipped default everywhere (config.py, CLIs):
    # identical params/outputs to "transpose", 5x faster on-chip
    # (docs/perf.md r4 A/B) and avoids a pathological multi-minute XLA
    # conv_transpose compile at full eval resolution.
    upconv: str = "subpixel"

    @nn.compact
    def __call__(self, x, train: bool = False) -> List[jax.Array]:
        if self.fusion not in ("cat", "cofusion"):
            raise ValueError(f"unknown fusion {self.fusion!r}; "
                             "expected 'cat' or 'cofusion'")
        dt = self.dtype

        block_1 = DoubleConvBlock(32, 64, stride=2, dtype=dt)(x, train)
        block_1_side = SingleConvBlock(128, stride=2, dtype=dt)(block_1, train)

        block_2 = DoubleConvBlock(128, use_act=False, dtype=dt)(block_1, train)
        block_2_down = _maxpool_3x3_s2(block_2)
        block_2_add = block_2_down + block_1_side
        block_2_side = SingleConvBlock(256, stride=2, dtype=dt)(block_2_add, train)

        block_3_pre_dense = SingleConvBlock(256, dtype=dt)(block_2_down, train)
        block_3 = DenseBlock(2, 256, dtype=dt)(block_2_add, block_3_pre_dense, train)
        block_3_down = _maxpool_3x3_s2(block_3)
        block_3_add = block_3_down + block_2_side
        block_3_side = SingleConvBlock(512, stride=2, dtype=dt)(block_3_add, train)

        block_4_pre_dense = SingleConvBlock(512, dtype=dt)(block_3_down, train)
        block_4 = DenseBlock(3, 512, dtype=dt)(block_3_add, block_4_pre_dense, train)
        block_4_down = _maxpool_3x3_s2(block_4)
        block_4_add = block_4_down + block_3_side
        block_4_side = SingleConvBlock(512, dtype=dt)(block_4_add, train)

        block_5_pre_dense = SingleConvBlock(512, dtype=dt)(block_4_down, train)
        block_5 = DenseBlock(3, 512, dtype=dt)(block_4_add, block_5_pre_dense, train)
        block_5_add = block_5 + block_4_side
        # side_5 is constructed but never used by the reference forward pass
        # (model.py:175 vs. :234-238); keep its params for parity (dead, DCE'd)
        _ = SingleConvBlock(256, dtype=dt, name="side_5")(block_5_add, False)

        block_6_pre_dense = SingleConvBlock(256, dtype=dt)(block_5, train)
        block_6 = DenseBlock(3, 256, dtype=dt)(block_5_add, block_6_pre_dense, train)

        up = self.upconv
        out_1 = UpConvBlock(1, dtype=dt, upconv=up)(block_1)
        out_2 = UpConvBlock(1, dtype=dt, upconv=up)(block_2)
        out_3 = UpConvBlock(2, dtype=dt, upconv=up)(block_3)
        out_4 = UpConvBlock(3, dtype=dt, upconv=up)(block_4)
        out_5 = UpConvBlock(4, dtype=dt, upconv=up)(block_5)
        out_6 = UpConvBlock(4, dtype=dt, upconv=up)(block_6)

        # crop deeper outputs when rounding made them overshoot
        # (reference model.py:251-257)
        h, w = out_1.shape[1], out_1.shape[2]
        if out_5.shape[1:3] != (h, w):
            h_off = out_5.shape[1] - h
            w_off = out_5.shape[2] - w
            assert h_off >= 0 and w_off >= 0
            out_5 = out_5[:, h_off : h_off + h, w_off : w_off + w, :]
            out_6 = out_6[:, h_off : h_off + h, w_off : w_off + w, :]

        results = [out_1, out_2, out_3, out_4, out_5, out_6]
        block_cat = jnp.concatenate(results, axis=-1)
        if self.fusion == "cofusion":
            block_cat = CoFusion(dtype=dt)(block_cat)
        else:
            block_cat = SingleConvBlock(1, use_bn=False, dtype=dt)(block_cat, train)
        results.append(block_cat)
        return results


def stack_edge_maps(outputs: List[jax.Array]) -> jax.Array:
    """Stack DexiNed's 7 per-scale logit maps into a (B, H, W, 7) tensor —
    the raw-logit edge contract of the v5 flow model (core/raft.py:115-123)."""
    return jnp.concatenate(outputs, axis=-1)
