"""Flax model zoo: encoders, update operators, DexiNed, and RAFT variants."""

from dexiraft_tpu.models.extractor import BasicEncoder, SmallEncoder
from dexiraft_tpu.models.update import (
    BasicUpdateBlock,
    SmallUpdateBlock,
    ConvGRU,
    SepConvGRU,
    FlowHead,
    RefineFlow,
)
from dexiraft_tpu.models.dexined import DexiNed
from dexiraft_tpu.models.raft import RAFT

__all__ = [
    "BasicEncoder",
    "SmallEncoder",
    "BasicUpdateBlock",
    "SmallUpdateBlock",
    "ConvGRU",
    "SepConvGRU",
    "FlowHead",
    "RefineFlow",
    "DexiNed",
    "RAFT",
]
